"""Assemble CLI — score every chain pair of one k-chain complex.

The assembly workload (ROADMAP item 5): k chains in, C(k,2) oriented
pairs scored with ONE encoder pass per unique chain, an interface graph
(edges = pairs whose calibrated interaction score clears
``--edge_threshold``), a complex-level interactability score, and the
``input_indep`` control baseline riding next to every number::

    # 6 synthetic chains, everything-vs-everything
    python -m deepinteract_tpu.cli.assemble --synthetic_chains 6 \
        --synthetic_len 20,40 --out runs/asm1

    # a real complex library, calibrated probabilities
    python -m deepinteract_tpu.cli.assemble --chains_npz_dir complexes/ \
        --calibration runs/calibration.json --out runs/asm2

Outputs: ``<out>.jsonl`` (ranked pair records), ``<out>.maps.npz``
(per-pair contact maps, durable artifact), and ``<out>.assembly.json``
(the bundle manifest: interface graph + provenance, durable artifact —
``cli/fsck.py`` verifies both). The FINAL stdout line is the
``assemble/v1`` machine contract (tools/check_cli_contract.py).
"""

from __future__ import annotations

import io
import json
import os
import sys
import time

from deepinteract_tpu.cli.args import (
    add_assembly_args,
    add_calibration_args,
    add_screening_args,
    build_parser,
    configs_from_args,
)
from deepinteract_tpu.robustness import artifacts


def write_bundle(out_prefix: str, result, weights_signature: str,
                 calibration_path, write_maps: bool = True):
    """Persist the assembly outputs; returns (ranked, bundle, maps)
    paths. The jsonl is atomic; the maps npz and bundle manifest are
    durable artifacts (sidecar-verified, fsck-covered)."""
    ranked_path = out_prefix + ".jsonl"
    lines = [json.dumps({"rank": rank, **rec})
             for rank, rec in enumerate(result.records, start=1)]
    artifacts.atomic_write(ranked_path,
                           "\n".join(lines) + ("\n" if lines else ""))

    maps_path = None
    if write_maps and result.maps:
        import numpy as np

        buf = io.BytesIO()
        np.savez(buf, **result.maps)
        maps_path = out_prefix + ".maps.npz"
        artifacts.atomic_write_artifact(
            maps_path, buf.getvalue(),
            kind="assembly-maps",
            extra={"weights_signature": weights_signature,
                   "pairs": result.pairs_total})

    from deepinteract_tpu.assembly import ASSEMBLY_BUNDLE_KIND

    bundle_path = out_prefix + ".assembly.json"
    bundle = {
        "schema": "assembly-bundle/v1",
        "weights_signature": weights_signature,
        "calibration": calibration_path,
        "interface": result.interface,
        "files": {
            "ranked": os.path.basename(ranked_path),
            "maps": (os.path.basename(maps_path) if maps_path else None),
        },
        **result.summary(),
    }
    artifacts.atomic_write_artifact(
        bundle_path, json.dumps(bundle, sort_keys=True),
        kind=ASSEMBLY_BUNDLE_KIND,
        extra={"weights_signature": weights_signature})
    return ranked_path, bundle_path, maps_path


def main(argv=None) -> int:
    parser = build_parser(__doc__)
    add_screening_args(parser)
    add_assembly_args(parser)
    add_calibration_args(parser)
    args = parser.parse_args(argv)

    from deepinteract_tpu.assembly import AssemblyConfig, AssemblyRunner
    from deepinteract_tpu.cli.screen import build_library
    from deepinteract_tpu.screening import EmbeddingCache
    from deepinteract_tpu.serving import EngineConfig, InferenceEngine
    from deepinteract_tpu.tuning.compile_cache import (
        enable_compile_cache,
        resolve_cache_dir,
    )

    enable_compile_cache(
        resolve_cache_dir(args.compile_cache_dir,
                          args.ckpt_name or args.ckpt_dir))
    library = build_library(args)
    chain_ids = ([c for c in args.query.split(",") if c]
                 if args.query else None)

    model_cfg, _, _ = configs_from_args(args)
    engine = InferenceEngine(
        model_cfg,
        ckpt_dir=args.ckpt_name,
        cfg=EngineConfig(
            max_batch=args.screen_batch,
            result_cache_size=0,
            diagonal_buckets=args.diagonal_buckets,
            pad_to_max_bucket=args.pad_to_max_bucket,
            input_indep=args.input_indep,
        ),
        seed=args.seed,
        metric_to_track=args.metric_to_track,
    )
    try:
        calibrator = None
        if args.calibration:
            from deepinteract_tpu.calibration import load_calibration

            calibrator = load_calibration(
                args.calibration,
                expect_signature=engine.weights_signature(),
                allow_stale=args.allow_stale_calibration)
            print(f"assemble: calibration {args.calibration} "
                  f"({calibrator.method})", flush=True)
        runner = AssemblyRunner(
            engine,
            cache=EmbeddingCache(capacity=args.emb_cache_entries,
                                 spill_dir=args.emb_cache_dir),
            cfg=AssemblyConfig(
                top_k=args.top_k,
                decode_batch=args.screen_batch,
                encode_batch=args.screen_batch,
                edge_threshold=args.edge_threshold,
                control=not args.no_control,
                keep_maps=not args.no_maps,
            ),
            calibrator=calibrator)
        t0 = time.perf_counter()
        result = runner.assemble(library, chain_ids=chain_ids)
        elapsed = time.perf_counter() - t0
    finally:
        engine.close()

    ranked_out, bundle_out, maps_out = write_bundle(
        args.out, result, engine.weights_signature(), args.calibration,
        write_maps=not args.no_maps)
    summary = result.summary()
    contract = {
        "schema": "assemble/v1",
        "metric": "assembly_pairs_per_sec",
        "value": round(result.pairs_scored / max(elapsed, 1e-9), 3),
        "unit": "pairs/s",
        "ok": True,
        "chains": result.chains,
        "pairs_total": result.pairs_total,
        "pairs_scored": result.pairs_scored,
        "unique_encodes": result.unique_encodes,
        "encode_cache_hits": result.encode_cache_hits,
        "decode_batches": result.decode_batches,
        "interface_edges": summary["interface_edges"],
        "interactability": summary["interactability"],
        "control_score": summary["control_score"],
        "calibrated": result.calibrated,
        "calibration": args.calibration,
        "weights_signature": engine.weights_signature(),
        "ranked_out": ranked_out,
        "bundle_out": bundle_out,
        "maps_out": maps_out,
        "elapsed_s": round(elapsed, 3),
    }
    # FINAL stdout line = the machine-readable contract
    # (tools/check_cli_contract.py keeps this un-regressable).
    print(json.dumps(contract), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
