"""Test/eval CLI — reference ``project/lit_model_test.py`` equivalent.

Restores a checkpoint, runs the held-out test pass (DIPS-Plus test n=32,
DB5-Plus test n=55, or CASP-CAPRI n=19) with the reference's test-time
metric conventions (L = min(n1, n2), deepinteract_modules.py:2045), writes
the per-target top-k CSV, and prints median metrics.
"""

from __future__ import annotations

import sys

import os

from deepinteract_tpu.cli.args import build_parser, configs_from_args, make_mesh_from_args


def resolve_checkpoint_source(args, download=None) -> str:
    """Local checkpoint dir, or — when it does not exist and
    ``--wandb_run_id`` is given — the downloaded ``model-<run_id>:best``
    W&B artifact (reference restore order, lit_model_test.py:121-130).
    ``download`` is injectable for tests."""
    ckpt_dir = args.ckpt_name or args.ckpt_dir
    if ckpt_dir and os.path.exists(ckpt_dir):
        return ckpt_dir
    run_id = getattr(args, "wandb_run_id", None)
    if run_id:
        if download is None:
            from deepinteract_tpu.training.wandb_logger import (
                download_checkpoint_artifact,
            )

            download = download_checkpoint_artifact
        art_dir = download(args.wandb_project, run_id,
                           entity=getattr(args, "wandb_entity", None))
        if art_dir:
            return art_dir
        raise SystemExit(
            f"no local checkpoint at {ckpt_dir!r} and the W&B artifact "
            f"model-{run_id}:best could not be downloaded"
        )
    if not ckpt_dir:
        raise SystemExit("provide --ckpt_name/--ckpt_dir or --wandb_run_id")
    return ckpt_dir


def _find_torch_checkpoint(path: str):
    """Path of a reference torch/Lightning checkpoint inside ``path`` (the
    layout of W&B model artifacts: <dir>/model.ckpt), else None."""
    if os.path.isfile(path) and path.endswith((".ckpt", ".pt")):
        return path
    if os.path.isdir(path):
        for name in ("model.ckpt", "model.pt"):
            cand = os.path.join(path, name)
            if os.path.isfile(cand):
                return cand
    return None


def main(argv=None) -> int:
    parser = build_parser(__doc__)
    parser.add_argument("--csv_out", type=str, default=None,
                        help="per-target CSV path (default mirrors the "
                             "reference naming, deepinteract_modules.py:2139-2143)")
    parser.add_argument("--unsafe-load", action="store_true",
                        help="allow full (code-executing) pickle load for "
                             "torch checkpoints the safe weights_only path "
                             "rejects; trusted files only")
    args = parser.parse_args(argv)

    from deepinteract_tpu.data.datasets import PICPDataModule
    from deepinteract_tpu.data.loader import BucketedLoader
    from deepinteract_tpu.models.model import DeepInteract
    from deepinteract_tpu.training.checkpoint import Checkpointer, CheckpointConfig
    from deepinteract_tpu.training.loop import Trainer, state_template

    model_cfg, optim_cfg, loop_cfg = configs_from_args(args)
    dm = PICPDataModule(
        dips_root=args.dips_root,
        db5_root=args.db5_root,
        casp_capri_root=args.casp_capri_root,
        train_with_db5=args.train_with_db5,
        test_with_casp_capri=args.test_with_casp_capri,
        input_indep=args.input_indep,
        split_ver=args.split_ver,
        seed=args.seed,
    )
    test_loader = BucketedLoader(dm.test, batch_size=args.eval_batch_size)

    model = DeepInteract(model_cfg)
    trainer = Trainer(model, loop_cfg, optim_cfg, mesh=make_mesh_from_args(args))
    example = next(iter(test_loader))
    state = trainer.init_state(example)

    ckpt_dir = resolve_checkpoint_source(args)
    torch_ckpt = _find_torch_checkpoint(ckpt_dir)
    if torch_ckpt is not None:
        # A reference-layout artifact (Lightning's model.ckpt): route
        # through the torch importer instead of orbax.
        from deepinteract_tpu.cli.import_checkpoint import load_reference_checkpoint
        from deepinteract_tpu.training.import_torch import convert_state_dict

        sd, _ = load_reference_checkpoint(torch_ckpt, args.unsafe_load)
        variables, report = convert_state_dict(sd, model_cfg, example)
        print(f"imported torch checkpoint {torch_ckpt}: {report.summary()}")
        state = state.replace(params=variables["params"],
                              batch_stats=variables["batch_stats"])
    else:
        ckpt = Checkpointer(CheckpointConfig(directory=ckpt_dir,
                                             metric_to_track=args.metric_to_track))
        tree = state_template(state)
        restored = ckpt.restore({"params": tree["params"],
                                 "batch_stats": tree["batch_stats"]},
                                which="best", partial=True)
        ckpt.close()
        state = state.replace(params=restored["params"],
                              batch_stats=restored["batch_stats"])

    # Reference CSV naming (deepinteract_modules.py:2139-2143).
    if args.csv_out:
        csv_path = args.csv_out
    elif args.test_with_casp_capri:
        csv_path = "casp_capri_top_metrics.csv"
    elif args.train_with_db5:
        csv_path = "db5_plus_test_top_metrics.csv"
    else:
        csv_path = "dips_plus_test_top_metrics.csv"

    metrics = trainer.evaluate(
        state, test_loader, stage="test", targets=test_loader.targets(),
        csv_path=csv_path,
    )
    for key in sorted(metrics):
        print(f"{key}: {metrics[key]:.6f}")
    print(f"wrote {csv_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
