"""Test/eval CLI — reference ``project/lit_model_test.py`` equivalent.

Restores a checkpoint, runs the held-out test pass (DIPS-Plus test n=32,
DB5-Plus test n=55, or CASP-CAPRI n=19) with the reference's test-time
metric conventions (L = min(n1, n2), deepinteract_modules.py:2045), writes
the per-target top-k CSV, and prints median metrics.
"""

from __future__ import annotations

import sys

from deepinteract_tpu.cli.args import build_parser, configs_from_args, make_mesh_from_args


def main(argv=None) -> int:
    parser = build_parser(__doc__)
    parser.add_argument("--csv_out", type=str, default=None,
                        help="per-target CSV path (default mirrors the "
                             "reference naming, deepinteract_modules.py:2139-2143)")
    args = parser.parse_args(argv)

    from deepinteract_tpu.data.datasets import PICPDataModule
    from deepinteract_tpu.data.loader import BucketedLoader
    from deepinteract_tpu.models.model import DeepInteract
    from deepinteract_tpu.training.checkpoint import Checkpointer, CheckpointConfig
    from deepinteract_tpu.training.loop import Trainer, state_to_tree

    model_cfg, optim_cfg, loop_cfg = configs_from_args(args)
    dm = PICPDataModule(
        dips_root=args.dips_root,
        db5_root=args.db5_root,
        casp_capri_root=args.casp_capri_root,
        train_with_db5=args.train_with_db5,
        test_with_casp_capri=args.test_with_casp_capri,
        input_indep=args.input_indep,
        split_ver=args.split_ver,
        seed=args.seed,
    )
    test_loader = BucketedLoader(dm.test, batch_size=args.eval_batch_size)

    model = DeepInteract(model_cfg)
    trainer = Trainer(model, loop_cfg, optim_cfg, mesh=make_mesh_from_args(args))
    state = trainer.init_state(next(iter(test_loader)))

    ckpt_dir = args.ckpt_name or args.ckpt_dir
    ckpt = Checkpointer(CheckpointConfig(directory=ckpt_dir,
                                         metric_to_track=args.metric_to_track))
    tree = state_to_tree(state)
    restored = ckpt.restore({"params": tree["params"],
                             "batch_stats": tree["batch_stats"]},
                            which="best", partial=True)
    ckpt.close()
    state = state.replace(params=restored["params"], batch_stats=restored["batch_stats"])

    # Reference CSV naming (deepinteract_modules.py:2139-2143).
    if args.csv_out:
        csv_path = args.csv_out
    elif args.test_with_casp_capri:
        csv_path = "casp_capri_top_metrics.csv"
    elif args.train_with_db5:
        csv_path = "db5_plus_test_top_metrics.csv"
    else:
        csv_path = "dips_plus_test_top_metrics.csv"

    metrics = trainer.evaluate(
        state, test_loader, stage="test", targets=test_loader.targets(),
        csv_path=csv_path,
    )
    for key in sorted(metrics):
        print(f"{key}: {metrics[key]:.6f}")
    print(f"wrote {csv_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
