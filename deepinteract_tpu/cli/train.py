"""Train CLI — reference ``project/lit_model_train.py`` equivalent.

Usage:
  python -m deepinteract_tpu.cli.train --dips_root /data/DIPS [...]

Flow (mirrors lit_model_train.py:22-232): data module -> model -> trainer
with EarlyStopping + checkpointing -> fit -> final test pass.
"""

from __future__ import annotations

import contextlib
import sys

from deepinteract_tpu.cli.args import (
    build_parser,
    configs_from_args,
    make_mesh_from_args,
    make_metric_writer,
)


def main(argv=None) -> int:
    args = build_parser(__doc__).parse_args(argv)

    from deepinteract_tpu.data.datasets import PICPDataModule
    from deepinteract_tpu.data.loader import BucketedLoader
    from deepinteract_tpu.models.model import DeepInteract
    from deepinteract_tpu.training.loop import Trainer

    model_cfg, optim_cfg, loop_cfg = configs_from_args(args)

    dm = PICPDataModule(
        dips_root=args.dips_root,
        db5_root=args.db5_root,
        casp_capri_root=args.casp_capri_root,
        train_with_db5=args.train_with_db5,
        test_with_casp_capri=args.test_with_casp_capri,
        percent_to_use=args.percent_to_use,
        input_indep=args.input_indep,
        split_ver=args.split_ver,
        seed=args.seed,
    )
    train_loader = BucketedLoader(
        dm.train, batch_size=args.batch_size, shuffle=True, drop_remainder=True,
        seed=args.seed, pad_to_max_bucket=args.pad_to_max_bucket,
    )
    val_loader = BucketedLoader(dm.val, batch_size=1)
    test_loader = BucketedLoader(dm.test, batch_size=1)

    # Calibrate the cosine-restart schedule on the actual epoch length
    # (reference T_0=10 epochs, deepinteract_modules.py:2196).
    import dataclasses

    optim_cfg = dataclasses.replace(
        optim_cfg, steps_per_epoch=max(train_loader.num_batches(), 1)
    )

    model = DeepInteract(model_cfg)

    if args.find_lr:
        # Optional LR range test before training (lit_model_train.py:121-127).
        from itertools import islice

        from deepinteract_tpu.training.lr_finder import lr_find

        probe = list(islice(iter(train_loader), 8))
        suggested, _ = lr_find(model, probe[0], probe, optim_cfg,
                               seed=args.seed,
                               weight_classes=args.weight_classes)
        print(f"lr_find suggestion: {suggested:.2e} (was {optim_cfg.lr:.2e})")
        optim_cfg = dataclasses.replace(optim_cfg, lr=suggested)

    mesh = make_mesh_from_args(args)
    trainer = Trainer(model, loop_cfg, optim_cfg, mesh=mesh,
                      metric_writer=make_metric_writer(args))

    example = next(iter(train_loader))
    state = trainer.init_state(
        example,
        fine_tune_from=args.ckpt_name if args.fine_tune else None,
    )

    profile = contextlib.nullcontext()
    if args.profile_dir:
        import jax

        profile = jax.profiler.trace(args.profile_dir)
    with profile:
        state, history = trainer.fit(
            state, train_loader, val_data=val_loader, resume=args.resume
        )

    test_metrics = trainer.evaluate(
        state, test_loader, stage="test", targets=test_loader.targets(),
        csv_path="test_top_metrics.csv",
    )
    print({k: round(v, 4) for k, v in test_metrics.items()})
    return 0


if __name__ == "__main__":
    sys.exit(main())
