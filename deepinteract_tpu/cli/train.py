"""Train CLI — reference ``project/lit_model_train.py`` equivalent.

Usage:
  python -m deepinteract_tpu.cli.train --dips_root /data/DIPS [...]

Flow (mirrors lit_model_train.py:22-232): data module -> model -> trainer
with EarlyStopping + checkpointing -> fit -> final test pass.
"""

from __future__ import annotations

import json
import sys

from deepinteract_tpu.cli.args import (
    build_parser,
    configs_from_args,
    make_mesh_from_args,
    make_metric_writer,
)


def _supervise_main(args, argv) -> int:
    """--supervise: spawn this command line (supervisor flags stripped,
    --heartbeat_seconds forced on) as a watched child; crashes and hangs
    restart into --resume with backoff, flappers trip the circuit. The
    final stdout line is the train_supervise/v1 contract."""
    import os

    from deepinteract_tpu.training.supervisor import (
        SuperviseConfig,
        TrainingSupervisor,
        strip_supervisor_flags,
        train_child_cmd_fn,
    )

    # The watched heartbeat is the one the Trainer writes for this host's
    # process index (training/loop.py fit).
    process_index = args.process_id or 0
    heartbeat_path = os.path.join(
        args.ckpt_dir, "obs", f"heartbeat_p{process_index}.json")
    heartbeat_seconds = (args.heartbeat_seconds
                         if args.heartbeat_seconds > 0 else 5.0)
    supervisor = TrainingSupervisor(
        train_child_cmd_fn(strip_supervisor_flags(argv), heartbeat_seconds),
        SuperviseConfig(
            heartbeat_path=heartbeat_path,
            state_dir=args.ckpt_dir,
            heartbeat_seconds=heartbeat_seconds,
            poll_interval_s=args.watch_interval_s,
            hang_timeout_s=args.hang_timeout_s,
            start_grace_s=args.start_grace_s,
            restart_backoff_s=args.train_restart_backoff_s,
            circuit_max_restarts=args.train_circuit_max_restarts,
            circuit_window_s=args.train_circuit_window_s,
        ),
        log=lambda s: print(s, flush=True))
    rc = supervisor.run()
    # The FINAL stdout line is the machine contract (tools/
    # check_cli_contract.py kind ``train_supervise``).
    print(json.dumps(supervisor.contract()), flush=True)
    return rc


def main(argv=None) -> int:
    parser = build_parser(__doc__)
    g = parser.add_argument_group("distributed")
    g.add_argument("--coordinator_address", type=str, default=None,
                   help="host:port of process 0 (multi-host training; the "
                        "reference's --num_compute_nodes analog, "
                        "lit_model_train.py:217,226)")
    g.add_argument("--num_processes", type=int, default=None)
    g.add_argument("--process_id", type=int, default=None)
    args = parser.parse_args(argv)

    if args.supervise:
        # Supervisor mode (training/supervisor.py): run this same command
        # line as a watched child — BEFORE initialize_distributed, so the
        # parent stays a plain control plane and the child owns the
        # coordination service (a restarted rank-0 child rebinds the
        # coordinator port only because the parent never held it).
        return _supervise_main(args, list(sys.argv[1:] if argv is None
                                          else argv))

    # Must run before anything touches the XLA backend (parallel/multihost
    # .py docstring); on TPU pods everything auto-detects, on CPU/GPU the
    # three flags (or JAX_COORDINATOR_ADDRESS etc.) select the topology.
    from deepinteract_tpu.parallel.multihost import (
        initialize_distributed,
        is_primary_host,
    )

    initialize_distributed(
        coordinator_address=args.coordinator_address,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )

    import jax

    from deepinteract_tpu.data.datasets import PICPDataModule
    from deepinteract_tpu.data.loader import BucketedLoader
    from deepinteract_tpu.models.model import DeepInteract
    from deepinteract_tpu.training.loop import Trainer
    from deepinteract_tpu.tuning.compile_cache import (
        enable_compile_cache,
        resolve_cache_dir,
    )

    # Persistent XLA compilation cache: repeat compiles of unchanged
    # graphs (48-247 s each on the benched config) become disk reads;
    # cache hit/miss counts land in di_compile_cache_* metrics.
    enable_compile_cache(
        resolve_cache_dir(args.compile_cache_dir, args.ckpt_dir))

    model_cfg, optim_cfg, loop_cfg = configs_from_args(args)

    dm = PICPDataModule(
        dips_root=args.dips_root,
        db5_root=args.db5_root,
        casp_capri_root=args.casp_capri_root,
        train_with_db5=args.train_with_db5,
        test_with_casp_capri=args.test_with_casp_capri,
        percent_to_use=args.percent_to_use,
        input_indep=args.input_indep,
        split_ver=args.split_ver,
        seed=args.seed,
    )
    # Multi-host: hosts plan identical GLOBAL batches and load disjoint
    # per-host slices of each (BucketedLoader shard) — the
    # DistributedSampler analog that also keeps bucket shapes and step
    # counts aligned across hosts (a raw file-list split would not).
    # Val/test stay unsharded: every host evaluates the same complexes,
    # keeping the sharded eval collectives aligned and the metrics
    # identical on all hosts.
    shard = (
        (jax.process_index(), jax.process_count())
        if jax.process_count() > 1 else None
    )
    train_ds, val_ds, test_ds = dm.train, dm.val, dm.test
    if args.packed_cache_dir:
        # Pre-padded memmap packs (data/packed.py): built once (first run
        # pays one pass over the npz tree), then every epoch's host path
        # is mmap + stack. Pack-time buckets use the same flags as the
        # loaders below.
        import os as _os

        from deepinteract_tpu.data.loader import make_bucket_fn
        from deepinteract_tpu.data.packed import PackedDataset, pack_dataset

        bucket_fn = make_bucket_fn(args.pad_to_max_bucket,
                                   args.diagonal_buckets)
        eval_bucket_fn = make_bucket_fn(False, False)
        # The signature must encode EVERY flag that changes pack content:
        # bucket-fn flags (bucket layout) and input_indep (the stored
        # features themselves are zeroed under the ablation).
        train_sig = (f"pad_max={args.pad_to_max_bucket},"
                     f"diag={args.diagonal_buckets},"
                     f"indep={args.input_indep}")
        eval_sig = f"eval,indep={args.input_indep}"
        specs = (("train", train_ds, bucket_fn, train_sig),
                 ("val", val_ds, eval_bucket_fn, eval_sig),
                 ("test", test_ds, eval_bucket_fn, eval_sig))
        # Multi-host: only process 0 writes the pack (concurrent writers
        # on shared storage would corrupt it); everyone else waits at the
        # barrier and then reads it.
        if jax.process_index() == 0:
            for split, ds, fn, sig in specs:
                pack_dataset(ds, _os.path.join(args.packed_cache_dir, split),
                             fn, signature=sig)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("packed_cache_built")
        train_ds, val_ds, test_ds = (
            PackedDataset(_os.path.join(args.packed_cache_dir, split))
            for split, *_ in specs)
    if args.data_skip_budget and shard:
        # Drop decisions are host-0-broadcast through the coordination
        # KV store (data/loader.py _skip_agreement): every host skips
        # identical batches, so step counts stay aligned by construction.
        print("multi-host run: --data_skip_budget drop decisions are "
              "host-0-coordinated (all hosts skip identical batches)")
    train_loader = BucketedLoader(
        train_ds, batch_size=args.batch_size, shuffle=True, drop_remainder=True,
        seed=args.seed, pad_to_max_bucket=args.pad_to_max_bucket, shard=shard,
        dispatch_run=max(1, args.steps_per_dispatch),
        diagonal_buckets=args.diagonal_buckets,
        skip_budget=args.data_skip_budget,
    )
    if shard:
        print(f"host {shard[0]}/{shard[1]}: {train_loader.num_batches()} "
              f"coordinated global steps/epoch, {args.batch_size} local x "
              f"{shard[1]} hosts per step")
    val_loader = BucketedLoader(val_ds, batch_size=args.eval_batch_size)
    test_loader = BucketedLoader(test_ds, batch_size=args.eval_batch_size)

    # Calibrate the cosine-restart schedule on the actual epoch length
    # (reference T_0=10 epochs, deepinteract_modules.py:2196).
    import dataclasses

    optim_cfg = dataclasses.replace(
        optim_cfg, steps_per_epoch=max(train_loader.num_batches(), 1)
    )

    if args.autotune:
        # Model-side tuned knobs (remat/scan_chunks/Pallas blocks) must
        # land BEFORE the model is constructed; the Trainer resolves the
        # loop-side scan_k from the same store at startup and logs the
        # full adopted tuple (training/loop.py). Active bucket = the most
        # populated (bucket1, bucket2) pair of the training plan.
        from deepinteract_tpu.tuning import consume
        from deepinteract_tpu.tuning.store import default_store_path

        store_path = args.tuning_store or default_store_path(args.ckpt_dir)
        buckets = train_loader._buckets
        active = (max(buckets.items(), key=lambda kv: len(kv[1]))[0]
                  if buckets else (128, 128))
        pad = max(active)
        adopted = consume.lookup_path(store_path, model_cfg,
                                      args.batch_size, pad)
        # The tuned Pallas grid must be legal at EVERY pad this run can
        # compile (both chain dims, train + eval plans) — the kernel runs
        # at each chain's own pad, and an indivisible block count is a
        # trace-time error, not a slow path.
        from deepinteract_tpu import constants as C

        plan_pads = {p for loader in (train_loader, val_loader, test_loader)
                     for key in loader._buckets for p in key}
        adopted, blocks_note = consume.restrict_pallas_blocks(
            adopted, plan_pads, knn=C.KNN)
        # Explicitly typed --interaction_stem / --compute_dtype are pinned:
        # the adopted trial keeps its perf knobs but cannot override them.
        from deepinteract_tpu.cli.args import pinned_knobs

        pins = pinned_knobs(args)
        adopted = consume.respect_explicit(
            adopted, stem=pins["stem"], dtype=pins["dtype"])
        model_cfg = consume.adopt_model_config(model_cfg, adopted)
        if args.accumulate_grad_batches == 1:
            # Respect an explicit --accumulate_grad_batches: the tuned
            # microbatch only fills the default.
            optim_cfg = consume.adopt_optim_config(optim_cfg, adopted)
        if adopted is not None:
            print(f"autotune: model config adopts ({adopted.summary()})"
                  f"{blocks_note}")
        loop_cfg = dataclasses.replace(
            loop_cfg, autotune=True, tuning_store=store_path,
            tuning_bucket=(args.batch_size, pad))

    model = DeepInteract(model_cfg)

    if args.find_lr:
        # Optional LR range test before training (lit_model_train.py:121-127).
        from itertools import islice

        from deepinteract_tpu.training.lr_finder import lr_find

        probe = list(islice(iter(train_loader), 8))
        suggested, _ = lr_find(model, probe[0], probe, optim_cfg,
                               seed=args.seed,
                               weight_classes=args.weight_classes)
        print(f"lr_find suggestion: {suggested:.2e} (was {optim_cfg.lr:.2e})")
        optim_cfg = dataclasses.replace(optim_cfg, lr=suggested)

    mesh = make_mesh_from_args(args)
    if mesh is None and jax.process_count() > 1:
        # Multi-host requires the GSPMD path; span every global device.
        from deepinteract_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(num_pair=args.num_pair_shards)
        print(f"multi-host: auto mesh over {len(jax.devices())} devices")
    trainer = Trainer(model, loop_cfg, optim_cfg, mesh=mesh,
                      metric_writer=make_metric_writer(args) if is_primary_host() else None)

    example = next(iter(train_loader))
    state = trainer.init_state(
        example,
        fine_tune_from=args.ckpt_name if args.fine_tune else None,
    )

    # --profile_dir is handled inside the loop now (LoopConfig.profile_dir):
    # the capture covers train dispatches 1..--profile_steps with phase-span
    # annotations, instead of one unannotated whole-fit trace.
    from deepinteract_tpu.robustness.preemption import TrainingPreempted

    try:
        state, history = trainer.fit(
            state, train_loader, val_data=val_loader, resume=args.resume
        )
    except TrainingPreempted as exc:
        # Clean preemption exit (robustness/preemption.py): the last/
        # checkpoint is flushed; the scheduler restarts us with --resume.
        print(f"training preempted ({exc}); checkpoint state is flushed — "
              f"rerun with --resume to continue from epoch boundaries")
        # Leave TOGETHER: rank-0 just spent seconds draining checkpoints
        # the peers did not — exiting staggered races the coordination
        # service's shutdown handshake (parallel/multihost.exit_barrier).
        from deepinteract_tpu.parallel.multihost import exit_barrier

        exit_barrier("preempted-exit")
        return 0

    # Publish the checkpoint directory as this run's model artifact
    # (Lightning WandbLogger log_model convention; restored by cli.test
    # --wandb_run_id, reference lit_model_test.py:121-130). No-op for
    # writers without artifact support (TensorBoard-only, offline).
    writer = trainer.metric_writer
    if (is_primary_host() and writer is not None and args.ckpt_dir
            and hasattr(writer, "log_checkpoint_artifact")):
        try:
            writer.log_checkpoint_artifact(args.ckpt_dir)
        except Exception as exc:  # artifact upload must not fail the run
            print(f"checkpoint artifact upload failed: {exc}")

    test_metrics = trainer.evaluate(
        state, test_loader, stage="test", targets=test_loader.targets(),
        csv_path="test_top_metrics.csv" if is_primary_host() else None,
    )
    if is_primary_host():
        print({k: round(v, 4) for k, v in test_metrics.items()})
    from deepinteract_tpu.parallel.multihost import exit_barrier

    exit_barrier("train-exit")
    return 0


if __name__ == "__main__":
    sys.exit(main())
