"""Ops/analysis CLI: dataset statistics, split partitioning, leakage audit.

The reference ships these as separate click CLIs
(``builder/collect_dataset_statistics.py``, ``builder/log_dataset_statistics.py``,
``builder/partition_dataset_filenames.py``, ``builder/check_percent_identity.py``,
``misc/check_leakage.py``, ``misc/check_length.py`` — SURVEY.md §1 Lx); here
they are subcommands over the npz dataset tree, backed by
:mod:`deepinteract_tpu.data.analysis`.

  python -m deepinteract_tpu.cli.analyze stats --root DS [--csv_out s.csv]
  python -m deepinteract_tpu.cli.analyze partition --root DS [--seed 42]
  python -m deepinteract_tpu.cli.analyze leakage --root DS [--threshold 0.3]
  python -m deepinteract_tpu.cli.analyze lengths --root DS
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List


def _processed_paths(root: str) -> List[str]:
    paths = sorted(glob.glob(os.path.join(root, "processed", "**", "*.npz"),
                             recursive=True))
    if not paths:
        raise SystemExit(f"no processed npz complexes under {root}/processed")
    return paths


def _split_paths(root: str, mode: str) -> List[str]:
    split = os.path.join(root, f"pairs-postprocessed-{mode}.txt")
    with open(split) as f:
        names = [l.strip() for l in f if l.strip()]
    return [os.path.join(root, "processed", os.path.splitext(n)[0] + ".npz")
            for n in names]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("stats", help="per-complex + aggregate statistics")
    sp.add_argument("--root", required=True)
    sp.add_argument("--csv_out", default=None)

    pp = sub.add_parser("partition", help="size-filter + random split files")
    pp.add_argument("--root", required=True)
    pp.add_argument("--seed", type=int, default=42)

    lp = sub.add_parser("leakage", help="train-vs-test sequence-identity audit")
    lp.add_argument("--root", required=True)
    lp.add_argument("--threshold", type=float, default=0.3)

    np_ = sub.add_parser("lengths", help="chain-length distribution audit")
    np_.add_argument("--root", required=True)

    args = p.parse_args(argv)

    from deepinteract_tpu.data import analysis

    if args.cmd == "stats":
        agg = analysis.collect_statistics(_processed_paths(args.root),
                                          csv_out=args.csv_out)
        print(json.dumps(agg))
    elif args.cmd == "partition":
        from deepinteract_tpu.data.io import complex_lengths_from_file

        paths = _processed_paths(args.root)
        nl = []
        for path in paths:
            rel = os.path.relpath(path, os.path.join(args.root, "processed"))
            nl.append((rel, *complex_lengths_from_file(path)))
        splits = analysis.partition_filenames(nl, seed=args.seed)
        analysis.write_split_files(args.root, splits)
        print(json.dumps({k: len(v) for k, v in splits.items()}))
    elif args.cmd == "leakage":
        leaks = analysis.check_leakage(
            _split_paths(args.root, "train"), _split_paths(args.root, "test"),
            threshold=args.threshold,
        )
        for cand, test_name, pid in leaks:
            print(f"LEAK {cand} ~ {test_name}: {pid:.2f}")
        print(json.dumps({"num_leaks": len(leaks)}))
        return 1 if leaks else 0
    elif args.cmd == "lengths":
        print(json.dumps(analysis.length_audit(_processed_paths(args.root))))
    return 0


if __name__ == "__main__":
    sys.exit(main())
