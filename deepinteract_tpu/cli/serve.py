"""Serve CLI — resident HTTP inference engine (deepinteract_tpu.serving).

Three modes share one flag surface:

**Single engine** (default). A persistent process that restores the
checkpoint once, compiles one executable per padded shape bucket
(optionally ahead of time via ``--warmup_buckets``), micro-batches
concurrent requests per bucket, and answers a JSON API::

    python -m deepinteract_tpu.cli.serve --ckpt_name ckpts/run1 \
        --port 8008 --warmup_buckets 128x128x1,128x128x8

    curl -X POST --data-binary @complex.npz http://127.0.0.1:8008/predict
    curl http://127.0.0.1:8008/stats
    curl http://127.0.0.1:8008/metrics   # Prometheus text exposition

SIGTERM drains in-flight requests and exits 0 (the PR-1 preemption
discipline), so rolling restarts never drop accepted work.

**Fleet** (``--workers N``). A supervisor/router pair
(``serving/fleet.py`` + ``serving/router.py``) in front of N
single-engine worker processes (each a child running this CLI with
``--workers 0`` on a free port): crashed workers restart with
exponential backoff (flappers trip a circuit breaker), dead-worker
requests fail over to a sibling, and ``POST /admin/rollover`` / SIGHUP
performs a zero-downtime warm weights rollover. The final stdout line on
exit is the machine-readable ``fleet/v1`` contract. ``--fleet_stub_workers``
swaps the engine workers for ``serving/worker_stub.py`` null engines
(fleet game-days / bench rehearsal).

**Rollover client** (``--rollover``). Sends ``POST /admin/rollover`` to
the router at ``--host``/``--port`` (optionally with ``--rollover_ckpt``
/ ``--rollover_signature``) and exits 0 iff the rollover completed; the
final stdout line is the router's ``fleet/v1`` response.

``--autoscale`` (with ``--workers``) adds the elastic capacity
controller (``serving/autoscaler.py``): the worker set grows/shrinks
between ``--autoscale_min_workers`` and ``--autoscale_max_workers``
from live overload signals, with hysteresis + cooldown, warm-before-
adopt scale-up, and drain-through scale-down. ``--versions`` is the
matching admin client: it fetches ``GET /admin/versions`` and exits
with the ``versions/v1`` contract as the final stdout line.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Tuple


from deepinteract_tpu.cli.args import add_serving_args, build_parser, configs_from_args


def parse_warmup_spec(spec: str) -> Tuple[Tuple[int, int, int], ...]:
    """``"128x128x1,128x128x8"`` -> ((128, 128, 1), (128, 128, 8)).

    Each entry is bucket_n1 x bucket_n2 x batch; batch defaults to 1 when
    omitted (``"128x128"``)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        dims = [int(v) for v in part.lower().split("x")]
        if len(dims) == 2:
            dims.append(1)
        if len(dims) != 3 or min(dims) < 1:
            raise ValueError(
                f"malformed warmup bucket {part!r} (want B1xB2 or B1xB2xBATCH)")
        out.append(tuple(dims))
    return tuple(out)


def warm_bucket_prefixes(spec: str, max_batch: int = 8,
                         pad_to_max_bucket: bool = False,
                         diagonal_buckets: bool = False,
                         mesh_shape: Optional[Tuple[int, int]] = None,
                         pair_shard_threshold: int = 512) -> Tuple[str, ...]:
    """Warmup specs -> the compile-inventory label prefixes a rollover
    replacement must report warm.

    Mirrors the engine's own spec normalization (``normalize_warmup``:
    loader bucket policy for the shapes, power-of-two slot rounding
    capped at ``max_batch`` for the batch) so ``(128, 128, 8)``
    requires ``"128x128/b8/"`` — the BATCH dimension is part of
    readiness, or a replacement warm at b1 only would pass the check
    and the first b8 flush would pay the cold-compile cliff the
    rollover contract promises away. Only the per-graph signature tail
    (``k20g2...``) is left open. Over-top-bucket specs additionally
    tile-lift inside the engine and may not match — a loud rollover
    abort, never a silent cold switch.

    ``mesh_shape`` mirrors the engine's topology labeling: a meshed
    worker prefixes every label with ``mesh{D}x{P}/`` and lifts
    data-placement batch slots to the data-axis size, so the readiness
    prefixes must too — otherwise a mesh rollover would wait on labels
    the replacement can never report and abort every warm switch."""
    from deepinteract_tpu.data.loader import make_bucket_fn
    from deepinteract_tpu.serving.fleet import (
        batch_slots,
        mesh_label_prefix,
        mesh_placement,
        parse_mesh_shape,
    )

    shape = parse_mesh_shape(mesh_shape)
    prefix = mesh_label_prefix(shape)
    bucket_fn = make_bucket_fn(pad_to_max_bucket, diagonal_buckets)
    out = []
    for b1, b2, bs in parse_warmup_spec(spec):
        nb1, nb2 = bucket_fn(b1, b2)
        placement = mesh_placement(shape, nb1, nb2, pair_shard_threshold)
        lift = shape[0] if placement == "data" else 1
        out.append(
            f"{prefix}{nb1}x{nb2}/b{batch_slots(bs, max_batch, lift_to=lift)}/")
    return tuple(out)


def engine_worker_cmd_fn(argv: List[str]):
    """Worker command factory for REAL engine workers: this CLI again,
    with the fleet flags neutralized by appending single-engine
    overrides (argparse last-occurrence-wins) plus the worker's port and
    heartbeat file. Rollover ``overrides`` append last of all, so
    ``{"ckpt_name": new}`` repoints the replacement's checkpoint."""
    base = list(argv)

    def cmd_fn(worker_id: str, port: int, heartbeat_path: str,
               overrides: Dict) -> List[str]:
        import os

        cmd = [sys.executable, "-m", "deepinteract_tpu.cli.serve"]
        cmd += base
        cmd += ["--workers", "0", "--host", "127.0.0.1",
                "--port", str(port), "--heartbeat_file", heartbeat_path,
                "--parent_pid", str(os.getpid())]
        for key in ("ckpt_name", "ckpt_dir", "compute_dtype",
                    "warmup_buckets", "mesh_shape"):
            if overrides.get(key):
                cmd += [f"--{key}", str(overrides[key])]
        return cmd

    return cmd_fn


def _fleet_main(args, argv: List[str], guard=None) -> int:
    """Supervisor + router (no engine in THIS process — workers own
    their engines, so the parent stays a lightweight control plane)."""
    import tempfile

    from deepinteract_tpu.serving.fleet import (
        FleetConfig,
        WorkerSupervisor,
        mesh_label,
        parse_mesh_shape,
        stub_worker_cmd,
    )
    from deepinteract_tpu.serving.router import FleetRouter, RouterConfig

    state_dir = args.fleet_dir or tempfile.mkdtemp(prefix="di_fleet_")
    cmd_fn = (stub_worker_cmd if args.fleet_stub_workers
              else engine_worker_cmd_fn(argv))
    mesh_shape = parse_mesh_shape(args.mesh_shape)
    required_warm = warm_bucket_prefixes(
        args.warmup_buckets, max_batch=args.max_batch,
        pad_to_max_bucket=args.pad_to_max_bucket,
        diagonal_buckets=args.diagonal_buckets,
        mesh_shape=mesh_shape,
        pair_shard_threshold=args.pair_shard_threshold)
    base_overrides = {}
    if args.fleet_stub_workers and required_warm:
        # Stubs must REPORT the operator's warmup buckets warm, or the
        # router's rollover readiness check (prefix match against
        # --warmup_buckets) would wait out the warm timeout and abort
        # every rehearsal rollover on a non-default spec.
        base_overrides["warm_buckets"] = ",".join(required_warm)
    if args.fleet_stub_workers and mesh_shape != (1, 1):
        # Stubs advertise the fleet's topology so topology-aware routing
        # and the rollover mesh-shape proof are rehearsable without jax.
        base_overrides["mesh_shape"] = mesh_label(mesh_shape)
    supervisor = WorkerSupervisor(
        cmd_fn,
        overrides=base_overrides,
        cfg=FleetConfig(
            num_workers=args.workers,
            probe_interval_s=args.probe_interval_s,
            heartbeat_max_age_s=args.heartbeat_max_age_s,
            restart_backoff_s=args.restart_backoff_s,
            circuit_max_restarts=args.circuit_max_restarts,
            circuit_window_s=args.circuit_window_s,
            state_dir=state_dir,
        ))
    router = FleetRouter(
        supervisor, host=args.host, port=args.port,
        cfg=RouterConfig(
            proxy_timeout_s=args.request_timeout_s,
            default_deadline_ms=args.default_deadline_ms,
            required_warm_buckets=required_warm,
            required_mesh_shape=(mesh_label(mesh_shape)
                                 if mesh_shape != (1, 1) else None),
            pair_bucket_threshold=(args.pair_shard_threshold
                                   if mesh_shape[1] > 1 else 0),
            warm_timeout_s=args.fleet_warm_timeout_s,
        ))
    router.start()
    autoscaler = None
    if args.autoscale:
        from deepinteract_tpu.serving.autoscaler import (
            Autoscaler,
            AutoscalerConfig,
        )

        autoscaler = Autoscaler(
            supervisor, router,
            cfg=AutoscalerConfig(
                min_workers=args.autoscale_min_workers,
                max_workers=args.autoscale_max_workers,
                interval_s=args.autoscale_interval_s,
                queue_high=args.autoscale_queue_high,
                queue_low=args.autoscale_queue_low,
                breach_polls=args.autoscale_breach_polls,
                cooldown_s=args.autoscale_cooldown_s,
                warm_timeout_s=args.fleet_warm_timeout_s,
            ),
            overrides=dict(base_overrides))
        autoscaler.start()
    host, port = router.address
    print(f"fleet router on http://{host}:{port} "
          f"({args.workers} worker(s)"
          f"{', stub' if args.fleet_stub_workers else ''}"
          f"{', autoscaling' if autoscaler is not None else ''}; "
          f"state in {state_dir})", flush=True)
    try:
        return router.run(guard=guard)
    finally:
        if autoscaler is not None:
            autoscaler.stop()
        print(json.dumps(router.final_contract()), flush=True)


def _rollover_main(args) -> int:
    """One-shot rollover client against a running fleet router."""
    from deepinteract_tpu.serving.fleet import request_json

    body: Dict = {}
    if args.rollover_ckpt:
        body["ckpt_name"] = args.rollover_ckpt
    if args.rollover_signature:
        body["weights_signature"] = args.rollover_signature
    # The admin call spans replacement warm-up AND the old fleet's
    # PARALLEL drain (bounded by the router's drain_timeout_s, 60s —
    # not --request_timeout_s, which only bounds individual predicts);
    # budget both phases plus slack so a slow-but-successful rollover
    # never reads as a client timeout.
    status, record = request_json(
        args.host, args.port, "POST", "/admin/rollover",
        body=json.dumps(body).encode(),
        timeout_s=args.fleet_warm_timeout_s + 60.0
        + args.request_timeout_s + 30.0)
    print(f"rollover answered {status}", flush=True)
    print(json.dumps(record), flush=True)
    # Exit code follows the ROLLOVER's own outcome, not the fleet-wide
    # contract "ok" (which means "no circuit open" and could be false
    # for an unrelated flapping worker while this rollover succeeded).
    roll = record.get("rollover", {}) if isinstance(record, dict) else {}
    return 0 if status == 200 and roll.get("ok") else 1


def _versions_main(args) -> int:
    """One-shot versions client: fetch the router's multi-version state
    (canary weights, per-version worker counts, shadow agreement); the
    final stdout line is the ``versions/v1`` contract."""
    from deepinteract_tpu.serving.fleet import request_json

    status, record = request_json(
        args.host, args.port, "GET", "/admin/versions",
        timeout_s=args.request_timeout_s)
    print(f"versions answered {status}", flush=True)
    print(json.dumps(record), flush=True)
    return 0 if status == 200 and isinstance(record, dict) else 1


def main(argv=None, guard=None) -> int:
    parser = build_parser(__doc__)
    add_serving_args(parser)
    from deepinteract_tpu.cli.args import add_calibration_args

    add_calibration_args(parser)
    args = parser.parse_args(argv)

    if args.rollover:
        return _rollover_main(args)
    if args.versions:
        return _versions_main(args)
    if args.workers > 0:
        return _fleet_main(
            args, list(sys.argv[1:] if argv is None else argv),
            guard=guard)

    from deepinteract_tpu.obs import spans as obs_spans
    from deepinteract_tpu.serving import EngineConfig, InferenceEngine, ServingServer
    from deepinteract_tpu.serving.fleet import parse_mesh_shape
    from deepinteract_tpu.tuning.compile_cache import (
        enable_compile_cache,
        resolve_cache_dir,
    )
    from deepinteract_tpu.tuning.store import default_store_path

    if args.events_out:
        # Request-scoped tracing sink: every request's trace_id +
        # queue-wait/compile/device decomposition (obs/reqtrace.py) is
        # durable and joinable against the ?trace=1 response echo.
        obs_spans.configure(args.events_out)

    enable_compile_cache(
        resolve_cache_dir(args.compile_cache_dir,
                          args.ckpt_name or args.ckpt_dir))

    tuning_store = None
    if args.autotune:
        import os

        tuning_store = args.tuning_store or default_store_path(
            args.ckpt_name or args.ckpt_dir)
        if not os.path.exists(tuning_store):
            print(f"autotune: tuning store {tuning_store} not found; "
                  "serving with default configs")
            tuning_store = None

    heartbeat: Optional[object] = None
    if args.heartbeat_file:
        # Started BEFORE engine construction: checkpoint restore + AOT
        # warmup is the most hang-prone window a worker has, and a
        # supervisor watching a missing-until-warm heartbeat would be
        # blind to exactly that phase. The beat thread is independent
        # of the (busy) main thread, so liveness coverage begins now.
        from deepinteract_tpu.obs.heartbeat import Heartbeat

        heartbeat = Heartbeat(args.heartbeat_file,
                              interval_s=args.heartbeat_interval_s)
        heartbeat.progress(role="engine-worker-starting")
        heartbeat.start()

    model_cfg, _, _ = configs_from_args(args)
    from deepinteract_tpu.cli.args import pinned_knobs

    pins = pinned_knobs(args)
    engine_cfg = EngineConfig(
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        warmup_buckets=parse_warmup_spec(args.warmup_buckets),
        result_cache_size=args.result_cache_size,
        diagonal_buckets=args.diagonal_buckets,
        pad_to_max_bucket=args.pad_to_max_bucket,
        input_indep=args.input_indep,
        max_queue_depth=args.max_queue_depth,
        max_inflight=args.max_inflight,
        tuning_store=tuning_store,
        mesh_shape=(parse_mesh_shape(args.mesh_shape)
                    if args.mesh_shape else None),
        pair_shard_threshold=args.pair_shard_threshold,
        # Explicitly typed --interaction_stem / --compute_dtype survive
        # tuned-entry adoption (tuning/consume.respect_explicit).
        pin_interaction_stem=pins["stem"],
        pin_compute_dtype=pins["dtype"],
    )
    engine = InferenceEngine(
        model_cfg,
        ckpt_dir=args.ckpt_name,
        cfg=engine_cfg,
        seed=args.seed,
        metric_to_track=args.metric_to_track,
    )
    from deepinteract_tpu.serving import ShedderConfig

    server = ServingServer(
        engine, host=args.host, port=args.port,
        request_timeout_s=args.request_timeout_s,
        screen_max_pairs=args.screen_max_pairs,
        default_deadline_ms=args.default_deadline_ms,
        index_path=args.index_path,
        calibration_path=args.calibration,
        shedder_cfg=ShedderConfig(
            enabled=not args.no_load_shedding,
            enter_utilization=args.shed_enter_util,
            exit_utilization=args.shed_exit_util,
            min_degraded_s=args.shed_min_degraded_s,
        ),
    )
    host, port = server.address
    stats = engine.stats()
    print(f"serving on http://{host}:{port} "
          f"(buckets warm: {stats['num_compiled_executables']})",
          flush=True)
    if stats["tuning"]["adopted"]:
        print(f"autotune: adopted ({stats['tuning']['adopted']})", flush=True)
    if heartbeat is not None:
        # Serving now: the beat carries the served weights' identity so
        # a stale-vs-wrong-weights worker is diagnosable from the file
        # alone.
        heartbeat.progress(role="engine-worker", port=port,
                           weights_signature=engine.weights_signature())
    if args.parent_pid > 0:
        # A hard-killed supervisor must not leave this worker serving
        # as an orphan: route parent death into the normal SIGTERM
        # drain (the guard path run() installs).
        import os as _os
        import signal as _signal

        from deepinteract_tpu.serving.fleet import watch_parent

        watch_parent(args.parent_pid,
                     lambda: _os.kill(_os.getpid(), _signal.SIGTERM))
    try:
        return server.run(guard=guard)
    finally:
        if heartbeat is not None:
            heartbeat.stop()


if __name__ == "__main__":
    sys.exit(main())
