"""Serve CLI — resident HTTP inference engine (deepinteract_tpu.serving).

Starts a persistent process that restores the checkpoint once, compiles
one executable per padded shape bucket (optionally ahead of time via
``--warmup_buckets``), micro-batches concurrent requests per bucket, and
answers a JSON API::

    python -m deepinteract_tpu.cli.serve --ckpt_name ckpts/run1 \
        --port 8008 --warmup_buckets 128x128x1,128x128x8

    curl -X POST --data-binary @complex.npz http://127.0.0.1:8008/predict
    curl http://127.0.0.1:8008/stats
    curl http://127.0.0.1:8008/metrics   # Prometheus text exposition

SIGTERM drains in-flight requests and exits 0 (the PR-1 preemption
discipline), so rolling restarts never drop accepted work.
"""

from __future__ import annotations

import sys
from typing import Tuple

from deepinteract_tpu.cli.args import add_serving_args, build_parser, configs_from_args


def parse_warmup_spec(spec: str) -> Tuple[Tuple[int, int, int], ...]:
    """``"128x128x1,128x128x8"`` -> ((128, 128, 1), (128, 128, 8)).

    Each entry is bucket_n1 x bucket_n2 x batch; batch defaults to 1 when
    omitted (``"128x128"``)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        dims = [int(v) for v in part.lower().split("x")]
        if len(dims) == 2:
            dims.append(1)
        if len(dims) != 3 or min(dims) < 1:
            raise ValueError(
                f"malformed warmup bucket {part!r} (want B1xB2 or B1xB2xBATCH)")
        out.append(tuple(dims))
    return tuple(out)


def main(argv=None) -> int:
    parser = build_parser(__doc__)
    add_serving_args(parser)
    args = parser.parse_args(argv)

    from deepinteract_tpu.obs import spans as obs_spans
    from deepinteract_tpu.serving import EngineConfig, InferenceEngine, ServingServer
    from deepinteract_tpu.tuning.compile_cache import (
        enable_compile_cache,
        resolve_cache_dir,
    )
    from deepinteract_tpu.tuning.store import default_store_path

    if args.events_out:
        # Request-scoped tracing sink: every request's trace_id +
        # queue-wait/compile/device decomposition (obs/reqtrace.py) is
        # durable and joinable against the ?trace=1 response echo.
        obs_spans.configure(args.events_out)

    enable_compile_cache(
        resolve_cache_dir(args.compile_cache_dir,
                          args.ckpt_name or args.ckpt_dir))

    tuning_store = None
    if args.autotune:
        import os

        tuning_store = args.tuning_store or default_store_path(
            args.ckpt_name or args.ckpt_dir)
        if not os.path.exists(tuning_store):
            print(f"autotune: tuning store {tuning_store} not found; "
                  "serving with default configs")
            tuning_store = None

    model_cfg, _, _ = configs_from_args(args)
    from deepinteract_tpu.cli.args import pinned_knobs

    pins = pinned_knobs(args)
    engine_cfg = EngineConfig(
        max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms,
        warmup_buckets=parse_warmup_spec(args.warmup_buckets),
        result_cache_size=args.result_cache_size,
        diagonal_buckets=args.diagonal_buckets,
        pad_to_max_bucket=args.pad_to_max_bucket,
        input_indep=args.input_indep,
        max_queue_depth=args.max_queue_depth,
        max_inflight=args.max_inflight,
        tuning_store=tuning_store,
        # Explicitly typed --interaction_stem / --compute_dtype survive
        # tuned-entry adoption (tuning/consume.respect_explicit).
        pin_interaction_stem=pins["stem"],
        pin_compute_dtype=pins["dtype"],
    )
    engine = InferenceEngine(
        model_cfg,
        ckpt_dir=args.ckpt_name,
        cfg=engine_cfg,
        seed=args.seed,
        metric_to_track=args.metric_to_track,
    )
    from deepinteract_tpu.serving import ShedderConfig

    server = ServingServer(
        engine, host=args.host, port=args.port,
        request_timeout_s=args.request_timeout_s,
        screen_max_pairs=args.screen_max_pairs,
        default_deadline_ms=args.default_deadline_ms,
        shedder_cfg=ShedderConfig(
            enabled=not args.no_load_shedding,
            enter_utilization=args.shed_enter_util,
            exit_utilization=args.shed_exit_util,
            min_degraded_s=args.shed_min_degraded_s,
        ),
    )
    host, port = server.address
    stats = engine.stats()
    print(f"serving on http://{host}:{port} "
          f"(buckets warm: {stats['num_compiled_executables']})",
          flush=True)
    if stats["tuning"]["adopted"]:
        print(f"autotune: adopted ({stats['tuning']['adopted']})", flush=True)
    return server.run()


if __name__ == "__main__":
    sys.exit(main())
