"""Unified static-analysis subsystem: one AST rule engine, many detectors.

The repo grew its static checks one script at a time (``tools/check_*``:
no-print, dtype discipline, CLI-contract, perf-regression) — each with
its own walker, its own exit-code convention, and no way to suppress or
baseline a finding. This package is the consolidation: a rule registry
over a shared parsed-file cache, per-finding ``# di: allow[rule]``
suppression pragmas, and a checked-in ``LINT_BASELINE.json`` so
pre-existing findings don't block CI while NEW ones fail loudly.

Entry point::

    python -m deepinteract_tpu.cli.lint            # all rules, repo-wide
    python -m deepinteract_tpu.cli.lint --rules jit-host-sync
    python -m deepinteract_tpu.cli.lint --update_baseline

The final stdout line is a machine-readable ``lint/v1`` contract
(validated by ``tools/check_cli_contract.py lint``); the run is wired
into tier-1 as ``tests/test_lint.py``.

Rule catalog (see each module's docstring for the precise semantics):

* ``no-print`` — no bare ``print()`` outside ``cli/`` (migrated from
  ``tools/check_no_print.py``, which remains as a thin shim);
* ``dtype-discipline`` — no hardcoded float dtypes in ``models/``
  outside ``policy.py`` (migrated from
  ``tools/check_dtype_discipline.py``, shim kept);
* ``jit-host-sync`` — host syncs (``.item()``, ``float()``,
  ``np.asarray``, branching on traced values) inside functions reachable
  from ``jax.jit``/``pjit``/``lax.scan``/``remat``;
* ``lock-discipline`` — attributes guarded by a class's
  ``threading.Lock`` in one method but accessed bare in another;
* ``prng-key-reuse`` — a ``jax.random`` key consumed twice without an
  intervening ``split``;
* ``dead-cli-flag`` — flags registered in ``cli/args.py`` whose dest is
  never read.
"""

from deepinteract_tpu.analysis.core import (  # noqa: F401
    Finding,
    Rule,
    SourceFile,
    all_rules,
    get_rule,
    register,
)
from deepinteract_tpu.analysis.runner import load_files, run_rules  # noqa: F401
