"""Rule ``dead-cli-flag``: registered flags whose dest is never read.

``cli/args.py`` is the shared argument surface for every entry point; a
flag that parses but is read nowhere is worse than missing — the operator
types it, gets no error, and silently doesn't get the behavior. This
rule cross-references every ``add_argument("--name", ...)`` registration
against attribute reads of its dest anywhere in the scanned tree.

A "read" is counted conservatively, so false positives stay rare:

* any attribute access ``<obj>.<dest>`` with a matching attribute name —
  the args namespace travels under many local names (``args``, ``a``,
  partially unpacked), and a same-named dataclass field being read also
  proves the NAME is load-bearing;
* ``getattr(x, "<dest>"[, default])`` with the dest as a string constant.

Registrations inside ``add_argument`` calls themselves never count, and
``dest=`` overrides are honored. The flag's finding anchors at its
``add_argument`` line, so the fix (wire it or delete it) is one jump
away.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from deepinteract_tpu.analysis.core import Finding, SourceFile, register

RULE = "dead-cli-flag"

# Files whose add_argument calls define the checked surface.
REGISTRY_FILES = ("deepinteract_tpu/cli/args.py", "cli/args.py")


def _registered_flags(tree: ast.AST) -> List[Tuple[str, str, int]]:
    """(flag, dest, line) for every long-option add_argument call."""
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            continue
        flag = None
        for arg in node.args:
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("--")):
                flag = arg.value
                break
        if flag is None:
            continue
        dest = flag.lstrip("-").replace("-", "_")
        for kw in node.keywords:
            if kw.arg == "dest" and isinstance(kw.value, ast.Constant):
                dest = str(kw.value.value)
        out.append((flag, dest, node.lineno))
    return out


def _registration_nodes(tree: ast.AST) -> Set[int]:
    """ids of every node inside an ``add_argument(...)`` call — reads in
    a registration (``default=cfg.x_flag``) must not count as consuming
    the dest, or exactly the flags most likely dead (wired only to a
    config default) would self-mask."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            for sub in ast.walk(node):
                out.add(id(sub))
    return out


def _attribute_reads(tree: ast.AST, skip: Set[int] = frozenset()
                     ) -> Set[str]:
    """Names that count as reading a dest: attribute Loads, getattr/
    hasattr string constants, string subscripts (``vars(args)['x']``),
    and ``.get('x')`` calls — the dict-shaped consumption paths a
    ``vars(args)`` round trip produces. Nodes in ``skip`` (registration
    subtrees) are ignored."""
    reads: Set[str] = set()
    for node in ast.walk(tree):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load):
            reads.add(node.attr)
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.slice, ast.Constant)
              and isinstance(node.slice.value, str)):
            reads.add(node.slice.value)
        elif isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Name)
                    and node.func.id in ("getattr", "hasattr")
                    and len(node.args) >= 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, str)):
                reads.add(node.args[1].value)
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "get" and node.args
                  and isinstance(node.args[0], ast.Constant)
                  and isinstance(node.args[0].value, str)):
                reads.add(node.args[0].value)
    return reads


@register(RULE, "cli/args.py flags whose args.<dest> is never read")
def check(files: Sequence[SourceFile]) -> Iterable[Finding]:
    registries = [f for f in files
                  if f.path in REGISTRY_FILES and f.tree is not None]
    if not registries:
        return
    reads: Set[str] = set()
    flags: Dict[str, Tuple[str, str, int]] = {}
    for f in files:
        if f.tree is None:
            continue
        skip = (_registration_nodes(f.tree)
                if f.path in REGISTRY_FILES else frozenset())
        reads |= _attribute_reads(f.tree, skip)
    for reg in registries:
        for flag, dest, line in _registered_flags(reg.tree):
            flags[flag] = (reg.path, dest, line)
    for flag, (path, dest, line) in sorted(flags.items()):
        if dest not in reads:
            yield Finding(
                rule=RULE, path=path, line=line,
                message=(f"flag {flag} registers dest `{dest}` but "
                         "nothing reads it — wire it up or delete the "
                         "registration"))
