"""Rule ``no-print``: no bare ``print()`` in the package outside ``cli/``.

Library, training, serving, and pipeline code must report through
``logging`` or the telemetry registry (``deepinteract_tpu/obs``) so output
is structured, filterable, and visible to exposition — a stray print
bypasses all three and disappears in multi-host runs. The CLI entry
points and the repo-level scripts (``bench.py``, ``tools/``) are the
sanctioned stdout surfaces.

Only real ``print(...)`` *calls* to the builtin name count — ``log_fn=
print`` defaults, methods named print, and strings mentioning print() do
not. ``tools/check_no_print.py`` is the standalone shim over this rule.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, Sequence, Tuple

from deepinteract_tpu.analysis.core import Finding, SourceFile, register

RULE = "no-print"

# Path prefixes (relative to the scan root) where bare print() is the
# intended UX.
ALLOWED_PREFIXES = ("deepinteract_tpu/cli/", "cli/", "tools/")
ALLOWED_FILES = ("bench.py", "__graft_entry__.py")

MESSAGE = ("bare print() — use logging or the obs registry "
           "(cli/ and bench.py are exempt)")


def in_scope(path: str) -> bool:
    if path in ALLOWED_FILES:
        return False
    return not path.startswith(ALLOWED_PREFIXES)


def violations_in_tree(tree: ast.AST) -> Iterator[Tuple[int, str]]:
    """(line, message) for each bare builtin print call — the single
    implementation behind both the rule and the tools/ shim."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            yield node.lineno, MESSAGE


@register(RULE, "no bare print() outside cli/ (use logging / obs)")
def check(files: Sequence[SourceFile]) -> Iterable[Finding]:
    for f in files:
        if f.tree is None or not in_scope(f.path):
            continue
        for line, message in violations_in_tree(f.tree):
            yield Finding(rule=RULE, path=f.path, line=line, message=message)
