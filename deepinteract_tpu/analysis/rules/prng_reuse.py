"""Rule ``prng-key-reuse``: a jax.random key consumed twice without a split.

JAX PRNG discipline: a key is single-use. Passing the same key to two
samplers yields IDENTICAL randomness (correlated dropout masks, duplicate
init noise) — silently, since nothing crashes. The convention is
``key, sub = jax.random.split(key)`` before every consumption, or
``fold_in`` with a distinct step.

Detection (per function, linear over the statement order):

* a name becomes a **key** when assigned from ``jax.random.PRNGKey`` /
  ``key`` / ``split`` / ``fold_in`` (tuple unpacking from ``split``
  marks every target);
* any appearance of a key name inside a later call's arguments counts as
  one **consumption** — including ``split(key)`` itself (after splitting,
  the parent key must not be used again) and passing the key to a user
  function (which presumably consumes it);
* the SECOND consumption without an intervening reassignment is flagged.

Reassignment (``key, sub = split(key)``) resets the count — the standard
threading pattern stays silent. Uses on different branches of one ``if``
are counted together (conservative: a reuse across exclusive branches is
a false positive — suppress with ``# di: allow[prng-key-reuse]``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from deepinteract_tpu.analysis.core import (
    Finding,
    SourceFile,
    dotted_name as _dotted,
    register,
)

RULE = "prng-key-reuse"

SCOPE_PREFIX = ("deepinteract_tpu/",)
# Producers: assignment RHS rooted here makes the target a key.
_PRODUCERS = {"PRNGKey", "key", "split", "fold_in", "clone"}

# Parameters that ARE keys by naming convention. `*_rng` / `*prng_key` /
# `rng_key` are unambiguous and seed unconditionally — a received key
# consumed twice is the dominant real-world reuse, including when both
# consumptions are helper calls. Bare `key`/`rng` and generic `*_key`
# collide with CACHE keys (serving/cache.py `key`, engine `bucket_key`)
# and numpy Generators (data/synthetic.py `rng`), so those only seed
# when the function itself calls jax.random.*.
_STRONG_KEY_PARAM_RE = re.compile(r"_rng$|prng_key$|^rng_key$")
_WEAK_KEY_PARAM_RE = re.compile(r"^(key|rng)$|_key$")


def _random_aliases(tree: ast.AST) -> Set[str]:
    """Names that refer to the jax.random module in this file:
    always {'jax.random'}, plus ``import jax.random as jr`` /
    ``from jax import random`` aliases."""
    aliases = {("jax", "random")}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.random" and a.asname:
                    aliases.add((a.asname,))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        aliases.add(((a.asname or "random"),))
    return aliases


class _FnChecker:
    def __init__(self, fn: ast.FunctionDef, aliases: Set[Tuple[str, ...]],
                 qual: str):
        self.fn = fn
        self.aliases = aliases
        self.qual = qual
        self.uses: Dict[str, int] = {}       # key name -> consumptions
        self.flagged: Set[str] = set()       # one finding per key per fn
        self.findings: List[Tuple[int, str]] = []
        args = fn.args
        calls_random = self._calls_jax_random(fn)
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            if _STRONG_KEY_PARAM_RE.search(a.arg) or (
                    calls_random and _WEAK_KEY_PARAM_RE.search(a.arg)):
                self.uses[a.arg] = 0

    def _calls_jax_random(self, fn: ast.FunctionDef) -> bool:
        return any(isinstance(n, ast.Call)
                   and self._random_call(n) is not None
                   for n in ast.walk(fn))

    def _random_call(self, node: ast.expr) -> Optional[str]:
        """'split' for jax.random.split(...) (under any alias)."""
        if not isinstance(node, ast.Call):
            return None
        d = _dotted(node.func)
        if d is None or len(d) < 2:
            return None
        return d[-1] if d[:-1] in self.aliases else None

    def run(self) -> List[Tuple[int, str]]:
        for stmt in self._ordered_stmts(self.fn):
            self._stmt(stmt)
        return self.findings

    @staticmethod
    def _ordered_stmts(fn: ast.FunctionDef) -> List[ast.stmt]:
        """All statements in the function in source order (nested blocks
        flattened; nested function bodies excluded — they execute on
        their own schedule)."""
        out: List[ast.stmt] = []

        def visit(stmts):
            for s in stmts:
                out.append(s)
                if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                for field in ("body", "orelse", "finalbody"):
                    child = getattr(s, field, None)
                    if child:
                        visit(child)
                for h in getattr(s, "handlers", []) or []:
                    visit(h.body)

        visit(fn.body)
        return out

    @staticmethod
    def _own_exprs(stmt: ast.stmt) -> List[ast.expr]:
        """The expressions evaluated BY this statement itself — compound
        statements contribute only their header (test/iter/items); their
        bodies are separate entries in the flattened order."""
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return [stmt.iter]
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return [item.context_expr for item in stmt.items]
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Try)):
            return []
        return [n for n in ast.iter_child_nodes(stmt)
                if isinstance(n, ast.expr)]

    def _stmt(self, stmt: ast.stmt) -> None:
        # Consumption first (RHS evaluates before targets bind). Each
        # Name node is counted at most once even when it sits inside
        # nested calls (f(g(key)) is ONE consumption of key).
        counted: Set[int] = set()
        for expr in self._own_exprs(stmt):
            for node in ast.walk(expr):
                if isinstance(node, ast.Call):
                    self._count_call(node, counted)
        if isinstance(stmt, ast.Assign):
            produced = self._produces_key(stmt.value)
            for t in stmt.targets:
                self._bind(t, produced)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target, self._produces_key(stmt.value))
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                self.uses.pop(stmt.target.id, None)

    def _produces_key(self, value: ast.expr) -> bool:
        kind = self._random_call(value)
        return kind in _PRODUCERS if kind else False

    def _bind(self, target: ast.expr, is_key: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, is_key)
            return
        if isinstance(target, ast.Name):
            if is_key:
                self.uses[target.id] = 0
                self.flagged.discard(target.id)
            else:
                self.uses.pop(target.id, None)

    def _count_call(self, call: ast.Call, counted: Set[int]) -> None:
        consumed: List[Tuple[str, int]] = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            # `keys = split(key, n)` then `keys[0]`, `keys[1]` is the
            # canonical batch-split idiom: a SUBSCRIPTED key name selects
            # a distinct subkey per index, so it never counts as reuse of
            # the array variable itself.
            subscripted = {
                id(sub.value) for sub in ast.walk(arg)
                if isinstance(sub, ast.Subscript)
                and isinstance(sub.value, ast.Name)}
            for sub in ast.walk(arg):
                if (isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in self.uses
                        and id(sub) not in counted
                        and id(sub) not in subscripted):
                    counted.add(id(sub))
                    consumed.append((sub.id, sub.lineno))
        for name, lineno in consumed:
            self.uses[name] += 1
            if self.uses[name] >= 2 and name not in self.flagged:
                self.flagged.add(name)
                self.findings.append((
                    lineno,
                    f"PRNG key `{name}` consumed again in `{self.qual}` "
                    "without an intervening jax.random.split — identical "
                    "randomness at both sites"))


def in_scope(path: str) -> bool:
    return path.startswith(SCOPE_PREFIX) or "/" not in path


@register(RULE, "jax.random key consumed twice without split/fold_in")
def check(files: Sequence[SourceFile]) -> Iterable[Finding]:
    for f in files:
        if f.tree is None or not in_scope(f.path):
            continue
        aliases = _random_aliases(f.tree)
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = node.name
                for line, message in _FnChecker(node, aliases, qual).run():
                    yield Finding(rule=RULE, path=f.path, line=line,
                                  message=message)
