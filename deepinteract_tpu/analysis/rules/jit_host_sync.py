"""Rule ``jit-host-sync``: host synchronization inside traced functions.

The MFU burn-down (ROADMAP item 2) lives and dies by device-loop purity:
one ``.item()`` / ``float()`` / ``np.asarray`` on a traced value inside a
jitted hot path either fails at trace time or — worse, when it survives
via a ``jax.debug`` escape or a rarely-hit branch — forces a blocking
device→host transfer per step. AlphaFold-class JAX stacks enforce exactly
this discipline statically; this rule is that enforcement for models/,
ops/, training/, parallel/ and the serving engine.

**Which functions are "traced"** (module-local, name-based):

* functions decorated with ``jax.jit`` / ``pjit`` / ``jax.checkpoint``
  (bare or under ``functools.partial``);
* functions passed to ``jax.jit(...)`` / ``pjit(...)`` /
  ``jax.checkpoint(...)`` / ``nn.remat(...)`` anywhere in the module —
  including ``jax.jit(self._forward)``-style method references — and
  scan bodies handed to ``jax.lax.scan(f, ...)``;
* every method of a ``flax.linen`` module class (bases mentioning
  ``nn.Module`` / ``Module`` / a known module base) — flax ``__call__``
  graphs only ever execute under a trace here;
* functions transitively called from the above by bare name or
  ``self.<method>`` within the same module.

**What is flagged inside them**, using an intraprocedural taint pass
(parameters are tracers — minus ``static_argnames``/``static_argnums`` —
and taint propagates through assignments; ``.shape``/``.dtype``/
``.ndim``/``.size`` reads are static under trace and drop taint):

* ``x.item()`` / ``x.tolist()`` on a tainted value;
* builtin ``float()`` / ``int()`` / ``bool()`` over a tainted value;
* ``np.asarray`` / ``np.array`` / ``jax.device_get`` over a tainted
  value (host materialization mid-trace);
* ``if`` / ``while`` / ``assert`` / ternary conditions that read a
  tainted value (Python control flow on a tracer) — ``x is None``
  checks, ``isinstance``, and shape/dtype reads are exempt.

False positives are expected to be rare but possible (a helper shared by
traced and host-side callers); suppress with ``# di: allow[jit-host-sync]
<reason>`` or accept into ``LINT_BASELINE.json``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from deepinteract_tpu.analysis.core import (
    Finding,
    SourceFile,
    dotted_name as _dotted,
    register,
)

RULE = "jit-host-sync"

SCOPE_PREFIXES = (
    "deepinteract_tpu/models/", "deepinteract_tpu/ops/",
    "deepinteract_tpu/training/", "deepinteract_tpu/parallel/",
    "deepinteract_tpu/serving/",
    # fixture trees (tests point --root at a mini package)
    "models/", "ops/", "training/", "parallel/", "serving/",
)

# Attribute reads that are STATIC under trace: taint does not flow out.
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding"}

# flax module base names (by last attribute segment).
MODULE_BASES = {"Module"}

# Call roots that make an argument function "traced".
_JIT_CALLS = {("jax", "jit"), ("jax", "pjit"), ("jax", "checkpoint"),
              ("nn", "remat"), ("nn", "jit"), ("jax", "remat")}
# lax control-flow primitives: WHICH positional args are the function
# operands (scan(f,...), while_loop(cond_fun, body_fun,...),
# fori_loop(lo, hi, body,...), cond(pred, true_fn, false_fn,...)) —
# predicates/bounds at the other positions must not mark same-named
# functions as traced.
_LAX_FN_ARGS = {
    "scan": (0,), "map": (0,), "while_loop": (0, 1), "fori_loop": (2,),
    "cond": (1, 2), "switch": (1, 2, 3, 4),
}


def _unwrap_partial(call: ast.expr) -> ast.expr:
    """partial(jax.jit, ...) -> jax.jit; anything else unchanged."""
    if isinstance(call, ast.Call):
        d = _dotted(call.func)
        if d and d[-1] == "partial" and call.args:
            return call.args[0]
    return call


def _static_params(deco: ast.expr, fn: ast.FunctionDef) -> Set[str]:
    """Parameter names pinned static by a jit decorator's
    static_argnames/static_argnums (they are Python values, not tracers)."""
    out: Set[str] = set()
    if not isinstance(deco, ast.Call):
        return out
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for kw in deco.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if (isinstance(n, ast.Constant)
                        and isinstance(n.value, int)
                        and 0 <= n.value < len(params)):
                    out.add(params[n.value])
    return out


class _ModuleIndex:
    """Per-file function inventory + traced-entry discovery."""

    def __init__(self, tree: ast.AST):
        # qualname -> (FunctionDef, owning class name or None)
        self.functions: Dict[str, Tuple[ast.FunctionDef, Optional[str]]] = {}
        self.methods_by_class: Dict[str, Set[str]] = {}
        self.flax_classes: Set[str] = set()
        self.traced: Dict[str, Set[str]] = {}  # qualname -> static params
        self._collect(tree)
        self._find_traced_refs(tree)

    def _collect(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                bases = set()
                for b in node.bases:
                    d = _dotted(b)
                    if d:
                        bases.add(d[-1])
                if bases & MODULE_BASES:
                    self.flax_classes.add(node.name)
                self.methods_by_class[node.name] = set()
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qual = f"{node.name}.{item.name}"
                        self.functions[qual] = (item, node.name)
                        self.methods_by_class[node.name].add(item.name)
        # Module-level (and nested) functions not claimed by a class.
        claimed = {fn for fn, _ in self.functions.values()}
        for node in ast.walk(tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node not in claimed
                    and node.name not in self.functions):
                self.functions[node.name] = (node, None)

    def _mark(self, qual: str, static: Set[str]) -> None:
        if qual in self.functions:
            self.traced.setdefault(qual, set()).update(static)

    def _mark_by_name(self, name: str, static: Set[str],
                      static_idx: Set[int] = frozenset()) -> None:
        """A bare or attribute function reference: mark every matching
        def (method name collisions are conservative — better two
        analyses than a missed hot path). ``static_idx`` holds
        call-site ``static_argnums`` integers, resolved against each
        matched function's own parameter list."""
        for qual in self.functions:
            if qual == name or qual.endswith(f".{name}"):
                fn, _cls = self.functions[qual]
                params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
                resolved = set(static) | {
                    params[i] for i in static_idx if 0 <= i < len(params)}
                self._mark(qual, resolved)

    def _find_traced_refs(self, tree: ast.AST) -> None:
        # 1. decorators
        for qual, (fn, _cls) in list(self.functions.items()):
            for deco in fn.decorator_list:
                target = _unwrap_partial(deco)
                d = _dotted(target)
                if d and (d in _JIT_CALLS or d[-1] in ("jit", "pjit")):
                    self._mark(qual, _static_params(deco, fn))
        # 2. call sites: jax.jit(f) / lax.scan(body, ...) / nn.remat(f)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            refs: List[ast.expr] = []
            if d in _JIT_CALLS or (len(d) >= 2 and d[-1] == "jit"):
                refs = list(node.args[:1])
            elif (d[-1] in _LAX_FN_ARGS
                  and d[:-1] in ((), ("lax",), ("jax", "lax"))
                  and d != ("map",)):  # bare map() is the host builtin
                refs = [node.args[i] for i in _LAX_FN_ARGS[d[-1]]
                        if i < len(node.args)]
            static: Set[str] = set()
            static_idx: Set[int] = set()
            for kw in node.keywords:
                if kw.arg in ("static_argnames", "static_argnums"):
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant):
                            if isinstance(n.value, str):
                                static.add(n.value)
                            elif isinstance(n.value, int):
                                static_idx.add(n.value)
            for ref in refs:
                rd = _dotted(ref)
                if rd is None:
                    continue
                # self._forward -> _forward; module fn -> name as-is
                self._mark_by_name(rd[-1], static, static_idx)
        # 3. flax module methods
        for qual, (fn, cls) in self.functions.items():
            if cls in self.flax_classes:
                self._mark(qual, set())

    def close_over_calls(self) -> None:
        """Transitive closure: a function called (by bare name or
        ``self.x``) from a traced function is traced too."""
        changed = True
        while changed:
            changed = False
            for qual in list(self.traced):
                fn, cls = self.functions[qual]
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    d = _dotted(node.func)
                    if d is None:
                        continue
                    callee: Optional[str] = None
                    if len(d) == 1 and d[0] in self.functions:
                        callee = d[0]
                    elif (len(d) == 2 and d[0] == "self" and cls
                          and d[1] in self.methods_by_class.get(cls, ())):
                        callee = f"{cls}.{d[1]}"
                    if callee and callee not in self.traced:
                        self.traced[callee] = set()
                        changed = True


# Parameter annotations that mark a STATIC Python value, not a tracer
# (flax's ``train: bool`` convention and friends).
_STATIC_ANNOTATIONS = {"bool", "str", "int", "Optional[bool]",
                       "Optional[str]", "Optional[int]"}


def _annotated_static(arg: ast.arg) -> bool:
    if arg.annotation is None:
        return False
    try:
        text = ast.unparse(arg.annotation).replace(" ", "")
    except Exception:  # pragma: no cover - unparse is total on real ASTs
        return False
    return text in _STATIC_ANNOTATIONS


class _TaintChecker:
    """Intraprocedural taint from tracer-bearing params to host syncs."""

    def __init__(self, fn: ast.FunctionDef, static_params: Set[str],
                 qual: str):
        self.fn = fn
        self.qual = qual
        args = fn.args
        params = list(args.posonlyargs + args.args + args.kwonlyargs)
        names = []
        for a in params:
            if not _annotated_static(a):
                names.append(a.arg)
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        self.tainted: Set[str] = {
            n for n in names
            if n not in ("self", "cls") and n not in static_params}
        self.findings: List[Tuple[int, str]] = []

    # -- taint queries ----------------------------------------------------

    def _static_name_ids(self, root: ast.expr) -> Set[int]:
        """ids of Name nodes whose value is STATIC at trace time even if
        the name is tainted: operands of ``is``/``is not`` comparisons,
        comparisons against string constants (tracers are never strings),
        arguments of isinstance/hasattr/callable/len, and anything that
        only feeds a ``.shape``/``.dtype``/``.ndim``/``.size`` read."""
        static: Set[int] = set()

        def blank(node: ast.expr) -> None:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    static.add(id(sub))

        for sub in ast.walk(root):
            if isinstance(sub, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot))
                       for op in sub.ops):
                    blank(sub)
                elif any(self._is_strish_constant(c)
                         for c in [sub.left] + list(sub.comparators)):
                    blank(sub)
            elif (isinstance(sub, ast.Call)
                  and isinstance(sub.func, ast.Name)
                  and sub.func.id in ("isinstance", "hasattr", "callable",
                                      "len", "getattr")):
                blank(sub)
            elif (isinstance(sub, ast.Attribute)
                  and sub.attr in STATIC_ATTRS):
                blank(sub.value)
        return static

    @staticmethod
    def _is_strish_constant(node: ast.expr) -> bool:
        """A string constant, or a tuple/list of constants containing one
        (``x in ("auto", "pallas")`` — tracers are never strings)."""
        if isinstance(node, ast.Constant):
            return isinstance(node.value, str)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(isinstance(el, ast.Constant)
                       and isinstance(el.value, str) for el in node.elts)
        return False

    def _expr_tainted(self, node: ast.expr) -> bool:
        """Does evaluating ``node`` read a tainted value (ignoring reads
        that are static under trace)?"""
        static = self._static_name_ids(node)
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                    and sub.id in self.tainted and id(sub) not in static):
                return True
        return False

    def _producer_call(self, node: ast.expr) -> bool:
        """jnp./jax.lax./jax.nn. calls produce traced arrays even from
        constant inputs."""
        if not isinstance(node, ast.Call):
            return False
        d = _dotted(node.func)
        return bool(d) and d[0] in ("jnp", "lax") or bool(
            d and len(d) >= 2 and d[0] == "jax")

    # -- walk -------------------------------------------------------------

    def run(self) -> List[Tuple[int, str]]:
        self._block(self.fn.body)
        return self.findings

    def _block(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs (scan bodies etc.): params of a nested function
            # handed to lax.scan are traced; analyzed via the module index
            # when referenced — here just propagate current taint.
            self._block(stmt.body)
            return
        if isinstance(stmt, ast.Assign):
            self._check_expr(stmt.value)
            if self._expr_tainted(stmt.value) or self._producer_call(
                    stmt.value):
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            self.tainted.add(n.id)
            else:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.tainted.discard(t.id)
            return
        if isinstance(stmt, ast.AugAssign):
            self._check_expr(stmt.value)
            if isinstance(stmt.target, ast.Name) and (
                    self._expr_tainted(stmt.value)
                    or self._producer_call(stmt.value)):
                self.tainted.add(stmt.target.id)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._check_expr(stmt.value)
            if isinstance(stmt.target, ast.Name):
                if self._expr_tainted(stmt.value) or self._producer_call(
                        stmt.value):
                    self.tainted.add(stmt.target.id)
                else:
                    self.tainted.discard(stmt.target.id)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_condition(stmt.test,
                                  "if" if isinstance(stmt, ast.If)
                                  else "while")
            self._check_expr(stmt.test)
            self._block(stmt.body)
            self._block(stmt.orelse)
            return
        if isinstance(stmt, ast.Assert):
            self._check_condition(stmt.test, "assert")
            self._check_expr(stmt.test)
            return
        if isinstance(stmt, ast.For):
            # Iterating a Python LIST of tracers is trace-legal and
            # common (layer stacks); iterating a traced array is not, but
            # the two are statically indistinguishable — so `for` is not
            # flagged, only checked for nested sync calls.
            self._check_expr(stmt.iter)
            # Loop targets inherit the iterated expression's taint only:
            # `for blk in self.blocks` yields static config, `for row in
            # tainted_list` yields traced values.
            if self._expr_tainted(stmt.iter) or self._producer_call(
                    stmt.iter):
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        self.tainted.add(n.id)
            else:
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        self.tainted.discard(n.id)
            self._block(stmt.body)
            self._block(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            self._block(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body)
            for h in stmt.handlers:
                self._block(h.body)
            self._block(stmt.orelse)
            self._block(stmt.finalbody)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._check_expr(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            self._check_expr(stmt.value)
            return
        # Everything else (pass, break, raise, ...): check nested exprs.
        for n in ast.walk(stmt):
            if isinstance(n, ast.expr):
                self._check_expr(n)
                break

    # -- checks -----------------------------------------------------------

    def _prune_static_tests(self, test: ast.expr) -> List[ast.expr]:
        """Split a condition into operands, dropping host-legal ones:
        ``x is (not) None`` and ``isinstance(...)``."""
        if isinstance(test, ast.BoolOp):
            out: List[ast.expr] = []
            for v in test.values:
                out.extend(self._prune_static_tests(v))
            return out
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._prune_static_tests(test.operand)
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return []
        if (isinstance(test, ast.Call) and isinstance(test.func, ast.Name)
                and test.func.id in ("isinstance", "hasattr", "callable",
                                     "getattr", "len")):
            return []
        return [test]

    def _check_condition(self, test: ast.expr, kind: str) -> None:
        for operand in self._prune_static_tests(test):
            if self._expr_tainted(operand):
                self.findings.append((
                    test.lineno,
                    f"Python `{kind}` on a traced value in "
                    f"`{self.qual}` — control flow must be lax.cond/"
                    "select/where inside a jitted function"))
                return

    def _check_expr(self, node: ast.expr) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.IfExp):
                self._check_condition(sub.test, "ternary")
                continue
            if not isinstance(sub, ast.Call):
                continue
            # x.item() / x.tolist()
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ("item", "tolist")
                    and self._expr_tainted(sub.func.value)):
                self.findings.append((
                    sub.lineno,
                    f"`.{sub.func.attr}()` on a traced value in "
                    f"`{self.qual}` — blocking device->host sync inside "
                    "a jitted function"))
                continue
            d = _dotted(sub.func)
            if d is None:
                continue
            # float()/int()/bool() on a traced value
            if (d in (("float",), ("int",), ("bool",)) and sub.args
                    and self._expr_tainted(sub.args[0])):
                self.findings.append((
                    sub.lineno,
                    f"`{d[0]}()` over a traced value in `{self.qual}` — "
                    "concretizes the tracer (host sync or trace error)"))
                continue
            # np.asarray / np.array / jax.device_get on a traced value
            if ((d in (("np", "asarray"), ("np", "array"),
                       ("numpy", "asarray"), ("numpy", "array"),
                       ("jax", "device_get")))
                    and sub.args and self._expr_tainted(sub.args[0])):
                self.findings.append((
                    sub.lineno,
                    f"`{'.'.join(d)}` over a traced value in "
                    f"`{self.qual}` — host materialization inside a "
                    "jitted function"))


def in_scope(path: str) -> bool:
    return path.startswith(SCOPE_PREFIXES)


@register(RULE, "host syncs / Python branching inside jit-traced functions")
def check(files: Sequence[SourceFile]) -> Iterable[Finding]:
    for f in files:
        if f.tree is None or not in_scope(f.path):
            continue
        index = _ModuleIndex(f.tree)
        index.close_over_calls()
        for qual, static in sorted(index.traced.items()):
            fn, _cls = index.functions[qual]
            for line, message in _TaintChecker(fn, static, qual).run():
                yield Finding(rule=RULE, path=f.path, line=line,
                              message=message)
