"""Rule ``artifact-write``: no bare write-mode ``open()`` in the package.

Persistence must go through ``robustness/artifacts.py`` (atomic tmp +
fsync + replace, optional integrity sidecar) so a kill -9 can never tear
a file a later run trusts — the ISSUE-12 durability contract. A bare
``open(path, "w")`` anywhere else is exactly how the next subsystem
quietly reintroduces torn-write bugs, so it is flagged at lint time.

Flags calls to the BUILTIN ``open`` whose mode (second positional or
``mode=`` keyword) is a string constant containing a write intent
(``w``, ``a``, ``x``, or ``+``). Read-mode opens, non-constant modes,
and method calls (``path.open``, ``gzip.open``) are out of scope.
Sanctioned exceptions carry ``# di: allow[artifact-write] <reason>`` —
streaming append sinks whose readers tolerate a torn tail, and
regenerable offline build outputs. ``robustness/artifacts.py`` itself
(the one place allowed to open tmp files for writing) is exempt, as are
the repo-level script surfaces (``tools/``, ``bench.py``, tests).
"""

from __future__ import annotations

import ast
from typing import Iterable, Sequence

from deepinteract_tpu.analysis.core import Finding, SourceFile, register

RULE = "artifact-write"

# The package is in scope; the durable layer itself and non-package
# script surfaces are not.
SCOPE_PREFIX = "deepinteract_tpu/"
EXEMPT_FILES = ("deepinteract_tpu/robustness/artifacts.py",)

MESSAGE = ("bare write-mode open() — persist through "
           "robustness/artifacts.py (atomic_write / atomic_write_artifact)"
           " or annotate why a torn file is tolerable")

_WRITE_CHARS = set("wax+")


def _write_mode(call: ast.Call) -> bool:
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default 'r'
    if not (isinstance(mode, ast.Constant) and isinstance(mode.value, str)):
        return False  # dynamic mode: undecidable, stay quiet
    return bool(_WRITE_CHARS & set(mode.value))


def in_scope(path: str) -> bool:
    return path.startswith(SCOPE_PREFIX) and path not in EXEMPT_FILES


@register(RULE, "no bare write-mode open() outside robustness/artifacts "
                "(atomic writes + integrity sidecars)")
def check(files: Sequence[SourceFile]) -> Iterable[Finding]:
    for f in files:
        if f.tree is None or not in_scope(f.path):
            continue
        for node in ast.walk(f.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "open"
                    and _write_mode(node)):
                yield Finding(rule=RULE, path=f.path, line=node.lineno,
                              message=MESSAGE)
