"""Rule modules — importing this package populates the registry."""

from deepinteract_tpu.analysis.rules import (  # noqa: F401
    artifact_write,
    dead_cli_flag,
    dtype_discipline,
    jit_host_sync,
    loader_boundary,
    lock_discipline,
    no_print,
    prng_reuse,
)
