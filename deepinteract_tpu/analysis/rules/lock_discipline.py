"""Rule ``lock-discipline``: guarded attributes accessed without the lock.

The serving/scheduler/screening layers are thread-shared by design
(handler threads, the micro-batch worker, loader prefetch threads), and
their invariant is lexical: a class that owns a ``threading.Lock`` /
``RLock`` / ``Condition`` mutates its shared attributes only inside
``with self._lock:`` blocks. A read or write that escapes the block is a
data race the tests will never reliably catch — exactly the class of bug
multi-worker serving (ROADMAP item 1) turns load-bearing.

Two patterns per lock-owning class:

1. **guarded-attr escape** — ``self.x`` is *mutated* under a ``with
   self._lock:`` block somewhere (assignment, augmented assignment,
   ``self.x[k] = v``, or a mutating method call like ``.append``/
   ``.popitem``), but read or written outside any such block in another
   method. ``__init__`` is exempt (construction happens-before sharing).
2. **unguarded read-modify-write** — ``self.x += ...`` outside any lock
   block in a class that owns a lock: ``+=`` on shared state is a load/
   store pair that interleaves, whether or not the attribute is also
   touched under the lock elsewhere.

Helpers that are only ever CALLED with the lock held (the
``_take_ready_group`` convention) are lexical false positives: suppress
with ``# di: allow[lock-discipline] caller holds <lock>``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from deepinteract_tpu.analysis.core import Finding, SourceFile, register

RULE = "lock-discipline"

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# Method calls that mutate their receiver in place.
MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "remove", "discard",
    "pop", "popleft", "popitem", "clear", "update", "setdefault",
    "move_to_end", "sort", "reverse", "__setitem__",
}

# Methods where bare access is construction, not sharing.
EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}


def _self_attr(node: ast.expr) -> Optional[str]:
    """'x' for a ``self.x`` expression, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lock_ctor(value: ast.expr) -> bool:
    """threading.Lock() / Lock() / threading.Condition(lock) ..."""
    if not isinstance(value, ast.Call):
        return False
    fn = value.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in LOCK_FACTORIES
    if isinstance(fn, ast.Name):
        return fn.id in LOCK_FACTORIES
    return False


# Anchored to the attribute's final name token: `_exec_lock`, `_cv`,
# `lock`, `io_mutex`, `ready_cond` — but NOT `self._blocker` or
# `self.block` (a non-lock context manager must not turn the class into
# a lock-owner and spray false findings).
_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex|cv|cond|condition)$",
                           re.IGNORECASE)


class _ClassAnalysis:
    def __init__(self, cls: ast.ClassDef):
        self.cls = cls
        self.lock_attrs: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        self.lock_attrs.add(attr)
            # ``with self._lock:`` on a lock-named attribute counts even
            # without a visible constructor — the Lock may be assigned in
            # a base class (obs/metrics.py's _Family hierarchy).
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr and _LOCK_NAME_RE.search(attr):
                        self.lock_attrs.add(attr)
        # (attr, line, kind) accesses, split by under-lock / outside.
        self.guarded_mutated: Set[str] = set()
        self.outside: List[Tuple[str, int, str, str]] = []  # attr, line, kind, method
        self.methods: List[ast.FunctionDef] = [
            item for item in cls.body
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))]

    def _with_holds_lock(self, stmt: ast.With) -> bool:
        return any(_self_attr(item.context_expr) in self.lock_attrs
                   for item in stmt.items)

    def scan(self) -> None:
        if not self.lock_attrs:
            return
        for method in self.methods:
            self._scan_block(method.body, under_lock=False,
                             method=method.name)

    def _scan_block(self, stmts: Sequence[ast.stmt], under_lock: bool,
                    method: str) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                holds = under_lock or self._with_holds_lock(stmt)
                for item in stmt.items:
                    self._scan_expr(item.context_expr, under_lock, method)
                self._scan_block(stmt.body, holds, method)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested def: runs later, lock NOT held at run time.
                self._scan_block(stmt.body, False, method)
                continue
            # Statement-level mutations first, then nested expressions.
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    self._record_target(t, under_lock, method)
                self._scan_expr(stmt.value, under_lock, method)
                continue
            if isinstance(stmt, ast.AugAssign):
                self._record_target(stmt.target, under_lock, method,
                                    aug=True)
                self._scan_expr(stmt.value, under_lock, method)
                continue
            if isinstance(stmt, ast.AnnAssign):
                self._record_target(stmt.target, under_lock, method)
                if stmt.value is not None:
                    self._scan_expr(stmt.value, under_lock, method)
                continue
            if isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    self._record_target(t, under_lock, method)
                continue
            # Control flow: recurse into child blocks with same state.
            for field in ("body", "orelse", "finalbody"):
                child = getattr(stmt, field, None)
                if child:
                    self._scan_block(child, under_lock, method)
            for h in getattr(stmt, "handlers", []) or []:
                self._scan_block(h.body, under_lock, method)
            for field in ("test", "iter", "value", "exc"):
                child = getattr(stmt, field, None)
                if isinstance(child, ast.expr):
                    self._scan_expr(child, under_lock, method)

    def _record_target(self, target: ast.expr, under_lock: bool,
                       method: str, aug: bool = False) -> None:
        attr = _self_attr(target)
        if attr is None and isinstance(target, (ast.Subscript,
                                                ast.Attribute)):
            # self.x[k] = v  /  self.x.y = v  mutate self.x
            attr = _self_attr(getattr(target, "value", None))
        if attr is None or attr in self.lock_attrs:
            if isinstance(target, (ast.Tuple, ast.List)):
                for el in target.elts:
                    self._record_target(el, under_lock, method, aug=aug)
            return
        kind = "augmented write" if aug else "write"
        if under_lock:
            self.guarded_mutated.add(attr)
        else:
            self.outside.append((attr, target.lineno, kind, method))

    def _scan_expr(self, expr: ast.expr, under_lock: bool,
                   method: str) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute):
                attr = _self_attr(sub.func.value)
                if attr and attr not in self.lock_attrs and (
                        sub.func.attr in MUTATORS):
                    if under_lock:
                        self.guarded_mutated.add(attr)
                    else:
                        self.outside.append(
                            (attr, sub.lineno, f".{sub.func.attr}()",
                             method))
                    continue
            attr = _self_attr(sub)
            if attr and attr not in self.lock_attrs and isinstance(
                    sub.ctx, ast.Load):
                if not under_lock:
                    self.outside.append((attr, sub.lineno, "read", method))

    def findings(self, path: str) -> Iterable[Finding]:
        if not self.lock_attrs:
            return
        locks = "/".join(sorted(self.lock_attrs))
        reported: Set[Tuple[str, int]] = set()
        for attr, line, kind, method in self.outside:
            if method in EXEMPT_METHODS:
                continue
            key = (attr, line)
            if key in reported:
                continue
            if attr in self.guarded_mutated:
                reported.add(key)
                yield Finding(
                    rule=RULE, path=path, line=line,
                    message=(f"{self.cls.name}.{attr} {kind} in "
                             f"`{method}` without holding self.{locks} — "
                             "the attribute is mutated under the lock "
                             "elsewhere"))
            elif kind == "augmented write":
                reported.add(key)
                yield Finding(
                    rule=RULE, path=path, line=line,
                    message=(f"{self.cls.name}.{attr} `+=` in `{method}` "
                             f"without holding self.{locks} — unguarded "
                             "read-modify-write on shared state in a "
                             "lock-owning class"))


@register(RULE, "lock-guarded attributes accessed without the lock")
def check(files: Sequence[SourceFile]) -> Iterable[Finding]:
    for f in files:
        if f.tree is None:
            continue
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                analysis = _ClassAnalysis(node)
                analysis.scan()
                yield from analysis.findings(f.path)
