"""Rule ``loader-boundary``: no bare ``jax.device_put`` inside training/.

Batch placement is a first-class stage of the input pipeline
(``data/pipeline.py``): it is sharding-aware (mesh batches land
pre-sharded via the same ``NamedSharding`` constructors the sharded
steps use for ``in_shardings``), multi-host safe (each host places only
its local shard), double-buffered under ``--device_prefetch``, and
telemetered (``di_data_h2d_*``). A bare ``jax.device_put`` on a batch
pytree inside ``training/`` is exactly how the pre-ISSUE-15 trainer
reintroduced the single-device-only prefetch limitation — it commits to
one device, bypasses the mesh sharding, and hides the h2d from the
pipeline's accounting — so it is flagged at lint time.

Flags calls to ``jax.device_put`` (or a bare ``device_put`` imported
from jax) AND bare references to it (the historical regression was an
assignment, ``train_data.device_transfer = jax.device_put`` — no call
node involved) in any file under ``deepinteract_tpu/training/``.
Non-batch placements with a reason (e.g. the SWA params placement in
``training/loop.py``) carry ``# di: allow[loader-boundary] <reason>``.
The placement layer itself (``data/pipeline.py``) and the mesh helpers
(``parallel/mesh.py``) are out of scope by construction — they ARE the
sanctioned boundary.
"""

from __future__ import annotations

import ast
from typing import Iterable, Sequence

from deepinteract_tpu.analysis.core import Finding, SourceFile, dotted_name, register

RULE = "loader-boundary"

SCOPE_PREFIX = "deepinteract_tpu/training/"

MESSAGE = ("bare jax.device_put in training/ — batch placement belongs to "
           "the input pipeline's placement layer (data/pipeline.py "
           "BatchPlacement / parallel/mesh.py shard_batch); annotate why a "
           "trainer-side placement is not a batch")


def in_scope(path: str) -> bool:
    return path.startswith(SCOPE_PREFIX)


@register(RULE, "no bare jax.device_put inside training/ — placement is a "
                "pipeline stage (data/pipeline.py)")
def check(files: Sequence[SourceFile]) -> Iterable[Finding]:
    for f in files:
        if f.tree is None or not in_scope(f.path):
            continue
        # Calls first: jax.device_put(...), any attribute chain ending in
        # device_put, or a bare ``device_put(...)`` pulled in via
        # ``from jax import device_put``. The call's func node is marked
        # consumed so the reference walk below does not double-report it.
        consumed = set()
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name is not None and name[-1] == "device_put":
                consumed.add(id(node.func))
                yield Finding(rule=RULE, path=f.path, line=node.lineno,
                              message=MESSAGE)
        # Bare references — the historical regression class was an
        # ASSIGNMENT of the function object (loader hook install), which
        # has no Call node at all.
        for node in ast.walk(f.tree):
            if id(node) in consumed:
                continue
            if ((isinstance(node, ast.Attribute)
                 and node.attr == "device_put")
                    or (isinstance(node, ast.Name)
                        and node.id == "device_put")):
                yield Finding(rule=RULE, path=f.path, line=node.lineno,
                              message=MESSAGE)
