"""Rule ``dtype-discipline``: no hardcoded float dtypes in ``models/``
outside ``models/policy.py``.

The dtype policy (``deepinteract_tpu/models/policy.py``) is the single
place model code may name a precision: statistics accumulate in
``STATS_DTYPE``, outward-facing tensors are ``OUTPUT_DTYPE``, activations
follow the configured compute dtype. A stray ``jnp.float32`` cast inside
a model silently pins part of the graph to full precision (the pre-r6
decoder had exactly such islands, which neutralized bf16 until they were
hunted down one by one) — or worse, a stray ``jnp.bfloat16`` bypasses the
policy's float32 guarantees for params/norms/logits.

Only real attribute references to the dtype names on the ``jnp`` / ``np``
/ ``jax.numpy`` / ``numpy`` modules count — strings mentioning 'float32'
(config values like ``compute_dtype="float32"``) and comparisons against
those strings do not. ``tools/check_dtype_discipline.py`` is the
standalone shim over this rule.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, Sequence, Tuple

from deepinteract_tpu.analysis.core import Finding, SourceFile, register

RULE = "dtype-discipline"

# Files (by basename) inside the scanned scope where naming a dtype is
# the point.
ALLOWED_FILES = {"policy.py"}

# Forbidden attribute names on a numpy-ish module object.
DTYPE_ATTRS = {"float32", "bfloat16", "float16", "float64"}

# Module aliases whose dtype attributes count as hardcoding.
NUMPY_MODULES = {"jnp", "np", "numpy"}

SCOPE_PREFIXES = ("deepinteract_tpu/models/", "models/")


def _is_numpy_module(node: ast.expr) -> bool:
    """True for ``jnp`` / ``np`` / ``numpy`` names and ``jax.numpy``."""
    if isinstance(node, ast.Name):
        return node.id in NUMPY_MODULES
    if isinstance(node, ast.Attribute):  # jax.numpy
        return (isinstance(node.value, ast.Name)
                and node.value.id == "jax" and node.attr == "numpy")
    return False


def in_scope(path: str) -> bool:
    if path.rsplit("/", 1)[-1] in ALLOWED_FILES:
        return False
    return path.startswith(SCOPE_PREFIXES)


def violations_in_tree(tree: ast.AST) -> Iterator[Tuple[int, str]]:
    """(line, message) per hardcoded dtype reference — the single
    implementation behind both the rule and the tools/ shim."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and node.attr in DTYPE_ATTRS
                and _is_numpy_module(node.value)):
            yield (node.lineno,
                   f"hardcoded dtype '{ast.unparse(node)}' — import it "
                   "from models/policy.py (STATS_DTYPE / OUTPUT_DTYPE / "
                   "FLOAT32 / compute_dtype()) so precision has one "
                   "authority")


@register(RULE, "no hardcoded float dtypes in models/ outside policy.py")
def check(files: Sequence[SourceFile]) -> Iterable[Finding]:
    for f in files:
        if f.tree is None or not in_scope(f.path):
            continue
        for line, message in violations_in_tree(f.tree):
            yield Finding(rule=RULE, path=f.path, line=line, message=message)
