"""File discovery + rule execution: parse once, run every rule, report.

The scanned set is the PACKAGE plus the repo-level Python surfaces
(``bench.py``, ``tools/``, ``__graft_entry__.py``) — tests are excluded
by default (deliberate violations live there as fixtures), and each rule
further scopes itself (e.g. dtype-discipline only reports on
``models/``).
"""

from __future__ import annotations

import dataclasses
import pathlib
from typing import Dict, List, Optional, Sequence

from deepinteract_tpu.analysis.core import (
    Finding,
    Rule,
    SourceFile,
    all_rules,
    assign_fingerprints,
    get_rule,
)

# Repo-root entries scanned in addition to the package. docker/ is build
# scaffolding, tests/ holds deliberate-violation fixtures.
EXTRA_SCAN = ("bench.py", "__graft_entry__.py", "tools")
SKIP_DIRS = {"__pycache__", ".git", "tests", "docker", "checkpoints"}


def discover(root: pathlib.Path) -> List[pathlib.Path]:
    """Python files under ``root``. When ``root`` is the repo (it contains
    ``deepinteract_tpu/``), scan the package + EXTRA_SCAN; otherwise scan
    the tree as-is (fixture trees in tests point --root anywhere)."""
    root = root.resolve()
    if (root / "deepinteract_tpu").is_dir():
        candidates: List[pathlib.Path] = []
        for sub in ("deepinteract_tpu",) + EXTRA_SCAN:
            p = root / sub
            if p.is_file():
                candidates.append(p)
            elif p.is_dir():
                candidates.extend(sorted(p.rglob("*.py")))
        paths = candidates
    else:
        paths = sorted(root.rglob("*.py")) if root.is_dir() else [root]
    return [
        p for p in paths
        if not (set(p.relative_to(root).parts[:-1]) & SKIP_DIRS)
    ]


def load_files(root: pathlib.Path,
               paths: Optional[Sequence[pathlib.Path]] = None
               ) -> List[SourceFile]:
    root = root.resolve()
    return [SourceFile(root, p) for p in (paths or discover(root))]


@dataclasses.dataclass
class RunResult:
    files: List[SourceFile]
    findings: List[Finding]       # active (unsuppressed)
    suppressed: List[Finding]
    parse_failures: List[Finding]

    @property
    def files_by_path(self) -> Dict[str, SourceFile]:
        return {f.path: f for f in self.files}

    def fingerprinted(self):
        return assign_fingerprints(self.findings, self.files_by_path)


def run_rules(root: pathlib.Path,
              rule_names: Optional[Sequence[str]] = None,
              files: Optional[List[SourceFile]] = None) -> RunResult:
    """Run the named rules (default: all registered) over ``root``."""
    files = files if files is not None else load_files(root)
    rules: List[Rule] = ([get_rule(n) for n in rule_names]
                         if rule_names else all_rules())
    parse_failures = [
        Finding(rule="parse", path=f.path,
                line=f.parse_error.lineno or 0,
                message=f"unparseable: {f.parse_error.msg}")
        for f in files if f.parse_error is not None
    ]
    by_path = {f.path: f for f in files}
    active: List[Finding] = []
    suppressed: List[Finding] = []
    for rule in rules:
        for finding in rule.check(files):
            sf = by_path.get(finding.path)
            if sf is not None and sf.is_suppressed(rule.name, finding.line):
                suppressed.append(
                    dataclasses.replace(finding, suppressed=True))
            else:
                active.append(finding)
    active.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    return RunResult(files=files, findings=active, suppressed=suppressed,
                     parse_failures=parse_failures)
