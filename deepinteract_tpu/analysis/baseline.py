"""Checked-in finding baseline: pre-existing debt doesn't block, new debt fails.

``LINT_BASELINE.json`` (repo root) holds the fingerprints of findings that
were present — and consciously accepted — when a rule landed. The lint
run classifies every unsuppressed finding as *baselined* (fingerprint in
the file) or *new* (fails the run), and reports baseline entries that no
longer match anything as *stale* so the file shrinks as debt is paid.

``--update_baseline`` rewrites the file from the current run. The
workflow for a rule change or an accepted finding::

    python -m deepinteract_tpu.cli.lint                  # see what's new
    # fix it, or # di: allow[rule] it with a reason, or:
    python -m deepinteract_tpu.cli.lint --update_baseline

The file is sorted and keyed by fingerprint with the human-readable
location alongside, so diffs in review show WHAT was accepted, not just
that something was.
"""

from __future__ import annotations

import json
import pathlib
from typing import Dict, List, Sequence, Tuple

from deepinteract_tpu.analysis.core import Finding

SCHEMA_VERSION = 1
DEFAULT_BASELINE_NAME = "LINT_BASELINE.json"


def load(path: pathlib.Path) -> Dict[str, dict]:
    """fingerprint -> entry dict. A missing file is an empty baseline; a
    wrong schema version fails loudly (a silently ignored baseline would
    re-fail every accepted finding)."""
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    if data.get("schema_version") != SCHEMA_VERSION:
        raise ValueError(
            f"{path}: baseline schema_version "
            f"{data.get('schema_version')!r} != {SCHEMA_VERSION} — "
            "regenerate with --update_baseline")
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def save(path: pathlib.Path,
         fingerprinted: Sequence[Tuple[Finding, str]],
         keep_entries: Sequence[dict] = ()) -> None:
    """Write the baseline. ``keep_entries`` carries existing entries that
    this run did NOT re-evaluate (a ``--rules`` subset run must not wipe
    the other rules' accepted debt)."""
    entries = [
        {
            "fingerprint": fp,
            "rule": f.rule,
            "path": f.path,
            "line": f.line,  # informational; identity is the fingerprint
            "message": f.message,
        }
        for f, fp in sorted(fingerprinted,
                            key=lambda t: (t[0].path, t[0].line, t[0].rule))
    ]
    known = {e["fingerprint"] for e in entries}
    entries.extend(e for e in keep_entries
                   if e["fingerprint"] not in known)
    entries.sort(key=lambda e: (e["path"], e["line"], e["rule"]))
    payload = {
        "schema_version": SCHEMA_VERSION,
        "comment": ("Accepted pre-existing lint findings "
                    "(python -m deepinteract_tpu.cli.lint "
                    "--update_baseline). New findings fail the run."),
        "findings": entries,
    }
    path.write_text(json.dumps(payload, indent=1) + "\n", encoding="utf-8")


def classify(
    fingerprinted: Sequence[Tuple[Finding, str]],
    baseline: Dict[str, dict],
) -> Tuple[List[Tuple[Finding, str]], List[Tuple[Finding, str]], List[dict]]:
    """(new, baselined, stale_entries). ``fingerprinted`` must be the
    UNSUPPRESSED findings only — a suppressed finding neither consumes
    nor invalidates a baseline entry."""
    new, matched = [], []
    seen = set()
    for f, fp in fingerprinted:
        if fp in baseline:
            matched.append((f, fp))
            seen.add(fp)
        else:
            new.append((f, fp))
    stale = [e for fp, e in sorted(baseline.items()) if fp not in seen]
    return new, matched, stale
