"""Rule engine core: findings, the rule registry, suppression pragmas.

Design decisions that every rule inherits:

* **One parse per file.** Rules receive :class:`SourceFile` objects whose
  AST is parsed once by the runner — six rules over ~100 files stay a
  single-process, sub-second run.
* **Line-content fingerprints, not line numbers.** A finding's baseline
  identity is ``sha1(rule | relpath | stripped source line | occurrence
  index)`` — editing an unrelated part of the file moves line numbers but
  not fingerprints, so the checked-in baseline doesn't churn.
* **Suppression is per-finding and named.** ``# di: allow[rule]`` on the
  flagged line (or the line directly above, for long statements) waives
  exactly that rule there; the pragma text is expected to carry a one-line
  reason, and suppressed findings are still counted in the report so an
  over-suppressed file is visible.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import pathlib
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence

# ``# di: allow[rule-a,rule-b] optional reason`` — the pragma grammar.
_PRAGMA_RE = re.compile(r"#\s*di:\s*allow\[([A-Za-z0-9_,\- ]+)\]")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int  # 1-indexed
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


class SourceFile:
    """A parsed repo file: path, text, AST, and per-line pragma map."""

    def __init__(self, root: pathlib.Path, path: pathlib.Path):
        self.abspath = path
        self.path = path.relative_to(root).as_posix()
        self.parse_error: Optional[SyntaxError] = None
        self.tree: Optional[ast.AST] = None
        try:
            self.text = path.read_text(encoding="utf-8")
        except (UnicodeDecodeError, OSError) as exc:
            # Surfaced as a per-file parse failure (same path as a
            # SyntaxError) — one bad file must not kill the whole run
            # before the contract line.
            self.text = ""
            err = SyntaxError(f"unreadable: {exc}")
            err.lineno = 0
            self.parse_error = err
        self.lines = self.text.splitlines()
        if self.parse_error is None:
            try:
                self.tree = ast.parse(self.text, filename=str(path))
            except SyntaxError as exc:
                self.parse_error = exc
        self._allowed: Dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(line)
            if m:
                self._allowed[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()}

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def is_suppressed(self, rule: str, line: int) -> bool:
        """True when the flagged line — or the line directly above it —
        carries ``# di: allow[<rule>]`` (or ``allow[all]``)."""
        for ln in (line, line - 1):
            allowed = self._allowed.get(ln)
            if allowed and (rule in allowed or "all" in allowed):
                return True
        return False


@dataclasses.dataclass(frozen=True)
class Rule:
    """A registered detector.

    ``check`` sees the FULL file list (cross-file rules like
    ``dead-cli-flag`` need it) and yields findings; ``scope`` prunes which
    files a per-file rule reports on, but the full list is always passed
    so a rule may consult out-of-scope files for context.
    """

    name: str
    help: str
    check: Callable[[Sequence[SourceFile]], Iterable[Finding]]


_RULES: Dict[str, Rule] = {}


def register(name: str, help: str):
    """Decorator: ``@register("rule-name", "one-line description")`` over
    a ``check(files) -> Iterable[Finding]`` function. Idempotent per name
    (module re-import must not duplicate), conflicting re-registration
    raises."""

    def deco(fn):
        existing = _RULES.get(name)
        if existing is not None and existing.check is not fn:
            raise ValueError(f"rule {name!r} is already registered")
        _RULES[name] = Rule(name=name, help=help, check=fn)
        return fn

    return deco


def all_rules() -> List[Rule]:
    # Importing the rules package populates the registry; do it lazily so
    # ``core`` has no import cycle with the rule modules.
    import deepinteract_tpu.analysis.rules  # noqa: F401

    return [_RULES[n] for n in sorted(_RULES)]


def get_rule(name: str) -> Rule:
    import deepinteract_tpu.analysis.rules  # noqa: F401

    if name not in _RULES:
        raise KeyError(
            f"unknown rule {name!r} (registered: {sorted(_RULES)})")
    return _RULES[name]


def dotted_name(node: ast.expr) -> Optional[tuple]:
    """('jax', 'lax', 'scan') for a ``jax.lax.scan`` attribute chain
    rooted at a Name; None for anything else (calls, subscripts,
    literals). Shared by every rule that resolves call targets."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def fingerprint(finding: Finding, line_text: str, occurrence: int) -> str:
    """Stable identity of a finding for the baseline: rule + path + the
    flagged line's stripped TEXT (not its number) + the occurrence index
    among identical (rule, path, text) triples."""
    payload = f"{finding.rule}|{finding.path}|{line_text}|{occurrence}"
    return hashlib.sha1(payload.encode()).hexdigest()[:16]


def assign_fingerprints(
    findings: Sequence[Finding], files_by_path: Dict[str, SourceFile]
) -> List[tuple]:
    """(finding, fingerprint) pairs with per-duplicate occurrence
    numbering, ordered by (path, line, rule)."""
    ordered = sorted(findings, key=lambda f: (f.path, f.line, f.rule))
    seen: Dict[tuple, int] = {}
    out = []
    for f in ordered:
        sf = files_by_path.get(f.path)
        text = sf.line_text(f.line) if sf is not None else ""
        key = (f.rule, f.path, text)
        occurrence = seen.get(key, 0)
        seen[key] = occurrence + 1
        out.append((f, fingerprint(f, text, occurrence)))
    return out
