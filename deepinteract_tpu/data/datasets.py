"""Dataset classes: DIPS-Plus, DB5-Plus, CASP-CAPRI over the npz tree.

Mirrors the reference's split handling (``DIPSDGLDataset`` et al.,
project/datasets/DIPS/dips_dgl_dataset.py:76-271) without DGL's dataset
machinery: a root directory holds ``processed/`` (npz complexes, see
``data.io``) and split list files ``pairs-postprocessed-{mode}.txt`` (one
relative path per line, same naming as the reference). Features:

* ``percent_to_use`` subsampling with a persisted sample file so re-runs see
  the same subset (reference ``construct_filenames_frame_txt_filenames``,
  deepinteract_utils.py:87-100).
* ``input_indep`` zero-feature ablation (deepinteract_utils.py:968-974).
* ``train_viz`` mode repeating the first complex (dips_dgl_dataset.py:139-143).
* Lazy per-item loading; items are unpadded raw dicts, padded/bucketed by
  the loader (TPU needs shape buckets, not per-item shapes).
"""

from __future__ import annotations

import os
import random
from typing import Dict, List, Optional

from deepinteract_tpu import constants
from deepinteract_tpu.data.io import complex_lengths_from_file, load_complex_npz


class ComplexDataset:
    """File-list driven dataset of npz complexes."""

    name = "generic"
    num_node_features = constants.NUM_NODE_FEATS
    num_edge_features = constants.NUM_EDGE_FEATS
    num_classes = constants.NUM_CLASSES

    def __init__(
        self,
        root: str,
        mode: str = "train",
        percent_to_use: float = 1.0,
        input_indep: bool = False,
        train_viz: bool = False,
        split_ver: Optional[str] = None,
        seed: int = 42,
    ):
        assert mode in ("train", "val", "test"), mode
        assert 0.0 < percent_to_use <= 1.0
        self.root = root
        self.mode = mode
        self.input_indep = input_indep
        self.processed_dir = os.path.join(root, "processed")
        self.filenames = self._resolve_filenames(mode, percent_to_use, split_ver, seed)
        if train_viz:
            # Reference: repeat the first complex so every data-parallel
            # rank sees the same viz sample (dips_dgl_dataset.py:139-143).
            self.filenames = [self.filenames[0]] * max(len(self.filenames), 1)

    def _split_file(self, mode: str, split_ver: Optional[str]) -> str:
        base = f"pairs-postprocessed-{mode}.txt"
        if split_ver:
            return os.path.join(self.root, split_ver, base)
        return os.path.join(self.root, base)

    def _resolve_filenames(
        self, mode: str, percent: float, split_ver: Optional[str], seed: int
    ) -> List[str]:
        split_path = self._split_file(mode, split_ver)
        if not os.path.exists(split_path):
            raise FileNotFoundError(
                f"{type(self).__name__}: missing split file {split_path}"
            )
        with open(split_path) as f:
            names = [line.strip() for line in f if line.strip()]
        if percent < 1.0:
            # Persist the sampled subset next to the split file (reference
            # behavior: sampled filename frames are written once and reused).
            sampled_path = split_path.replace(".txt", f"-{int(percent * 100)}%.txt")
            if os.path.exists(sampled_path):
                with open(sampled_path) as f:
                    names = [line.strip() for line in f if line.strip()]
            else:
                rng = random.Random(seed)
                names = rng.sample(names, max(1, int(len(names) * percent)))
                # di: allow[artifact-write] seed-deterministic sample cache, regenerated if lost
                with open(sampled_path, "w") as f:
                    f.write("\n".join(names) + "\n")
        return names

    def __len__(self) -> int:
        return len(self.filenames)

    def path_of(self, idx: int) -> str:
        rel = os.path.splitext(self.filenames[idx])[0] + ".npz"
        return os.path.join(self.processed_dir, rel)

    def target_of(self, idx: int) -> str:
        return os.path.splitext(os.path.basename(self.filenames[idx]))[0]

    def __getitem__(self, idx: int) -> Dict:
        raw = load_complex_npz(self.path_of(idx))
        raw["input_indep"] = self.input_indep
        raw["target"] = self.target_of(idx)
        return raw

    def lengths(self) -> List[tuple]:
        """(n1, n2) per item, reading only npy headers (cheap bucket
        planning over thousands of complexes — no array decompression)."""
        return [complex_lengths_from_file(self.path_of(i)) for i in range(len(self))]


class DIPSDataset(ComplexDataset):
    """DIPS-Plus: 15,618 train / 3,548 val / 32 test complexes
    (dips_dgl_dataset.py:22-30)."""

    name = "DIPS-Plus"


class DB5Dataset(ComplexDataset):
    """DB5-Plus: 140 train / 35 val / 55 test unbound dimers
    (db5_dgl_dataset.py:16-24). Test batch size is forced to 1 by the data
    module (picp_dgl_data_module.py:146-157)."""

    name = "DB5-Plus"


class CASPCAPRIDataset(ComplexDataset):
    """CASP-CAPRI 13/14: 19 test-only dimers, 14 homo + 5 hetero
    (casp_capri_dgl_dataset.py:16-23)."""

    name = "CASP-CAPRI"

    def __init__(self, root: str, mode: str = "test", **kw):
        assert mode == "test", "CASP-CAPRI is a test-only dataset"
        super().__init__(root, mode=mode, **kw)


class PICPDataModule:
    """Composite protein-interface-contact-prediction data source
    (reference ``PICPDGLDataModule``, picp_dgl_data_module.py:71-157):
    train/val on DIPS-Plus or DB5-Plus, test on DIPS-Plus or CASP-CAPRI."""

    def __init__(
        self,
        dips_root: Optional[str] = None,
        db5_root: Optional[str] = None,
        casp_capri_root: Optional[str] = None,
        train_with_db5: bool = False,
        test_with_casp_capri: bool = False,
        percent_to_use: float = 1.0,
        input_indep: bool = False,
        split_ver: Optional[str] = None,
        seed: int = 42,
    ):
        kw = dict(percent_to_use=percent_to_use, input_indep=input_indep, seed=seed)
        if train_with_db5:
            assert db5_root, "train_with_db5 requires db5_root"
            self.train = DB5Dataset(db5_root, mode="train", **kw)
            self.val = DB5Dataset(db5_root, mode="val", **kw)
        else:
            assert dips_root, "training requires dips_root"
            self.train = DIPSDataset(dips_root, mode="train", split_ver=split_ver, **kw)
            self.val = DIPSDataset(dips_root, mode="val", split_ver=split_ver, **kw)
        if test_with_casp_capri:
            assert casp_capri_root, "test_with_casp_capri requires casp_capri_root"
            self.test = CASPCAPRIDataset(casp_capri_root, input_indep=input_indep)
        elif train_with_db5:
            self.test = DB5Dataset(db5_root, mode="test", **kw)
        else:
            self.test = DIPSDataset(dips_root, mode="test", split_ver=split_ver, **kw)
