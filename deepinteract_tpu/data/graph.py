"""Statically-shaped residue-graph containers.

TPU-first replacement for the reference's dynamic ``dgl.DGLGraph``
(``project/utils/deepinteract_utils.py:386-555``). A kNN residue graph has
fixed *out*-degree K, so instead of a sparse edge list we store edges densely
as ``[N, K]`` neighbor slots, matching the reference's DGL ``knn_graph``
convention exactly:

* edge ``(i, k)`` points from **source/center node i** to its k-th nearest
  neighbor ``dst = nbr_idx[i, k]`` (DGL 0.6 ``knn_graph``: src = arange
  repeated, dst = argtopk indices; consumed per-source-grouped at
  ``deepinteract_utils.py:476``)
* its flat edge id is ``i * K + k`` (row-major), identical to the reference's
  DGL edge ids, so converted ``src_nbr_e_ids``/``dst_nbr_e_ids`` line up
* the reference's edge softmax (``deepinteract_modules.py:76-96``) normalizes
  over a node's *incoming* edges — the reverse-kNN neighborhood, variable
  degree. The model supports both that exact semantics (static-shape
  ``segment_sum`` scatter over ``nbr_idx``) and a TPU-optimal dense mode
  that normalizes over each row's fixed K out-edges (a transposed-graph
  attention; identical when the kNN graph is symmetric).

All arrays are padded to a fixed ``N`` per shape bucket; ``node_mask`` marks
real nodes. Batches stack along a leading axis (no DGL-style concatenation),
so per-graph normalizations stay per-graph by construction.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from deepinteract_tpu import constants


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ProteinGraph:
    """One protein chain as a padded fixed-degree residue graph.

    Shapes (unbatched; a leading batch axis may be added by ``stack_graphs``):
      node_feats:    [N, 113] float   — schema in ``constants``
      coords:        [N, 3]   float   — CA coordinates
      edge_feats:    [N, K, 28] float — schema in ``constants``
      nbr_idx:       [N, K]  int32    — destination of edge (i, k): the k-th
                                        nearest neighbor of source node i
      src_nbr_eids:  [N, K, G] int32  — flat ids of edges incident to the
                                        edge's *source* node i (sampled from
                                        row i, G=geo neighborhood size;
                                        reference ``edata['src_nbr_e_ids']``,
                                        deepinteract_utils.py:532-553)
      dst_nbr_eids:  [N, K, G] int32  — same for the destination node
                                        nbr_idx[i, k] (sampled from its row)
      node_mask:     [N]     bool     — True for real (non-pad) residues
      num_nodes:     []      int32    — number of real residues

    Deviation from the reference, by design: the reference samples a node's
    *in*-edges for these neighborhoods via a reshape that is only well-formed
    when every in-degree equals K (not true of kNN graphs); we sample the
    node's K *out*-edges (its own row) — the only fixed-degree formulation —
    which expresses the same "edges incident to the endpoint" intent.
    """

    node_feats: Any
    coords: Any
    edge_feats: Any
    nbr_idx: Any
    src_nbr_eids: Any
    dst_nbr_eids: Any
    node_mask: Any
    num_nodes: Any

    @property
    def n_padded(self) -> int:
        return self.node_feats.shape[-2]

    @property
    def knn(self) -> int:
        return self.nbr_idx.shape[-1]

    def edge_mask(self):
        """[..., N, K] mask of real edges: an edge is real iff its source
        node is real (real nodes only ever select real neighbors, and padded
        nodes self-point, so source validity implies destination validity)."""
        return jnp.broadcast_to(self.node_mask[..., :, None], self.nbr_idx.shape)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PairedComplex:
    """A two-chain complex plus supervision targets.

    ``examples`` replicates the reference's flattened (i, j, label) example
    tensor (``deepinteract_utils.py:558-582``) in padded form:
      examples:     [M, 3] int32  — (row in chain1, col in chain2, label)
      example_mask: [M]    bool   — True for real examples
    ``contact_map`` is the dense L1 x L2 0/1 target (padded).
    """

    graph1: ProteinGraph
    graph2: ProteinGraph
    examples: Any
    example_mask: Any
    contact_map: Any

    @property
    def pair_mask(self):
        """[..., N1, N2] validity mask of the interaction map."""
        return self.graph1.node_mask[..., :, None] & self.graph2.node_mask[..., None, :]


def _pad_axis0(arr: np.ndarray, target: int, fill=0) -> np.ndarray:
    pad = target - arr.shape[0]
    if pad < 0:
        raise ValueError(f"cannot pad array of length {arr.shape[0]} down to {target}")
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, widths, constant_values=fill)


def pad_graph(raw: Dict[str, np.ndarray], n_pad: int) -> ProteinGraph:
    """Pad a featurizer output dict (see ``features.featurize_chain``) to a
    static node count ``n_pad``. Padded nodes point at themselves with zeroed
    features so gathers stay in-bounds and contribute nothing under masking."""
    n = int(raw["node_feats"].shape[0])
    if n_pad < n:
        raise ValueError(f"chain of length {n} does not fit bucket {n_pad}")
    k = raw["nbr_idx"].shape[1]
    g = raw["src_nbr_eids"].shape[2]

    nbr_idx = _pad_axis0(raw["nbr_idx"].astype(np.int32), n_pad)
    if n_pad > n:
        pad_rows = np.arange(n, n_pad, dtype=np.int32)[:, None]
        nbr_idx[n:] = np.broadcast_to(pad_rows, (n_pad - n, k))  # self-pointing
    eid_fill = np.arange(n_pad, dtype=np.int32)[:, None, None] * k  # in-bounds ids
    src_eids = _pad_axis0(raw["src_nbr_eids"].astype(np.int32), n_pad)
    dst_eids = _pad_axis0(raw["dst_nbr_eids"].astype(np.int32), n_pad)
    if n_pad > n:
        src_eids[n:] = np.broadcast_to(eid_fill[n:], (n_pad - n, k, g))
        dst_eids[n:] = np.broadcast_to(eid_fill[n:], (n_pad - n, k, g))

    return ProteinGraph(
        node_feats=_pad_axis0(raw["node_feats"].astype(np.float32), n_pad),
        coords=_pad_axis0(raw["coords"].astype(np.float32), n_pad),
        edge_feats=_pad_axis0(raw["edge_feats"].astype(np.float32), n_pad),
        nbr_idx=nbr_idx,
        src_nbr_eids=src_eids,
        dst_nbr_eids=dst_eids,
        node_mask=_pad_axis0(np.ones(n, dtype=bool), n_pad),
        num_nodes=np.int32(n),
    )


def pick_bucket(n: int, buckets=constants.CHAIN_LENGTH_BUCKETS) -> int:
    """Smallest bucket that fits a chain of length ``n`` (last bucket's
    multiple if the chain exceeds every bucket — long-context tier)."""
    for b in buckets:
        if n <= b:
            return b
    top = buckets[-1]
    return ((n + top - 1) // top) * top


def stack_graphs(graphs) -> ProteinGraph:
    """Batch graphs of identical padded shape along a new leading axis."""
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *graphs)


def stack_complexes(complexes) -> PairedComplex:
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *complexes)
