"""Geometric featurization of protein chains (converter side, numpy).

Reimplements the reference's featurization semantics
(``project/utils/protein_feature_utils.py`` and
``convert_df_to_dgl_graph``, ``project/utils/deepinteract_utils.py:386-555``)
as pure numpy producing the dense ``[N, K]`` edge layout of
:mod:`deepinteract_tpu.data.graph`. This runs once per complex on CPU; the
accelerator only ever sees the resulting padded arrays.

Numerics notes (kept for parity, flagged as reference quirks):
* RBF bins are applied to *squared* CA-CA distances with D_max=20
  (``protein_feature_utils.py:82-101`` fed from
  ``torch.topk(pairwise_squared_distance(...))``, ``graph_utils.py:110``).
* Dihedral padding removes phi[0], psi[-1], omega[-1]
  (``protein_feature_utils.py:276-320``).
* Edge weights and amide angles are min-max normalized per graph
  (``deepinteract_utils.py:506,513-530``).
* The per-edge geometric neighborhood (src/dst incident-edge ids) is randomly
  subsampled at data-prep time (``deepinteract_utils.py:532-553``) — the
  sampling lives here, NOT in the model, so jit-compiled compute stays
  deterministic.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from deepinteract_tpu import constants

_EPS = 1e-7


def _normalize(v: np.ndarray, axis: int = -1, eps: float = 1e-12) -> np.ndarray:
    """Match ``torch.nn.functional.normalize``: x / max(||x||, eps)."""
    norm = np.linalg.norm(v, axis=axis, keepdims=True)
    return v / np.maximum(norm, eps)


def min_max_normalize(x: np.ndarray) -> np.ndarray:
    """Scale to [0, 1] (reference ``min_max_normalize_tensor``,
    deepinteract_utils.py:79-84). Constant input maps to 0 instead of NaN."""
    lo, hi = np.min(x), np.max(x)
    rng = hi - lo
    if rng == 0:
        return np.zeros_like(x, dtype=np.float32)
    return ((x - lo) / rng).astype(np.float32)


def knn_edges(coords: np.ndarray, k: int, self_loops: bool = True):
    """k-nearest-neighbor edges over CA coordinates.

    Returns (nbr_idx [N, k] int32 sorted by ascending distance,
    sq_dists [N, k] float32). With ``self_loops`` the first slot is the node
    itself (distance 0), matching ``dgl.knn_graph`` + squared-distance topk
    (``graph_utils.py:108-110``).
    """
    n = coords.shape[0]
    if (k if self_loops else k + 1) > n:
        raise ValueError(f"chain of length {n} cannot support knn={k} (self_loops={self_loops})")
    diff = coords[:, None, :] - coords[None, :, :]
    sq = np.sum(diff * diff, axis=-1)
    if not self_loops:
        np.fill_diagonal(sq, np.inf)
    order = np.argsort(sq, axis=1, kind="stable")[:, :k]
    return order.astype(np.int32), np.take_along_axis(sq, order, axis=1).astype(np.float32)


def dihedral_features(backbone: np.ndarray) -> np.ndarray:
    """Per-residue (cos, sin) of phi/psi/omega from N,CA,C coords.

    backbone: [N, 4, 3] (N, CA, C, O). Returns [N, 6].
    Reference: ``GeometricProteinFeatures.get_dihedrals``
    (protein_feature_utils.py:276-320), including its padding scheme that
    zeroes phi[0], psi[-1], omega[-1].
    """
    n = backbone.shape[0]
    x = backbone[:, :3, :].reshape(3 * n, 3)
    dx = x[1:] - x[:-1]
    u = _normalize(dx)
    u_2, u_1, u_0 = u[:-2], u[1:-1], u[2:]
    n_2 = _normalize(np.cross(u_2, u_1))
    n_1 = _normalize(np.cross(u_1, u_0))
    cos_d = np.clip(np.sum(n_2 * n_1, axis=-1), -1 + _EPS, 1 - _EPS)
    d = np.sign(np.sum(u_2 * n_1, axis=-1)) * np.arccos(cos_d)
    d = np.pad(d, (1, 2))
    d = d.reshape(n, 3)
    return np.concatenate([np.cos(d), np.sin(d)], axis=1).astype(np.float32)


def rbf_features(sq_dists: np.ndarray, num_rbf: int = constants.NUM_RBF) -> np.ndarray:
    """Radial basis features over (squared) distances, D in [0, 20].

    Reference: ``GeometricProteinFeatures.compute_rbfs``
    (protein_feature_utils.py:82-101); note the squared-distance input quirk.
    """
    d_mu = np.linspace(0.0, 20.0, num_rbf)
    d_sigma = 20.0 / num_rbf
    z = (sq_dists[..., None] - d_mu) / d_sigma
    return np.exp(-(z ** 2)).astype(np.float32)


def local_frames(ca: np.ndarray) -> np.ndarray:
    """Per-residue local orthogonal frame O [N, 3, 3] from backbone-adjacent
    CA unit vectors; rows (o_1, n_2, o_1 x n_2). First row and last two rows
    are zero (reference padding, protein_feature_utils.py:227-236)."""
    dx = ca[1:] - ca[:-1]
    u = _normalize(dx)
    u_2, u_1 = u[:-2], u[1:-1]
    n_2 = _normalize(np.cross(u_2, u_1))
    o_1 = _normalize(u_2 - u_1)
    frames = np.stack([o_1, n_2, np.cross(o_1, n_2)], axis=1)  # [N-3, 3, 3]
    return np.pad(frames, ((1, 2), (0, 0), (0, 0))).astype(np.float32)


def rotations_to_quaternions(r: np.ndarray) -> np.ndarray:
    """Rotation matrices [..., 3, 3] -> unit quaternions [..., 4] (x,y,z,w).

    Reference: ``convert_rotations_into_quaternions``
    (protein_feature_utils.py:104-149), including sign(0)=0 behavior.
    """
    rxx, ryy, rzz = r[..., 0, 0], r[..., 1, 1], r[..., 2, 2]
    magnitudes = 0.5 * np.sqrt(
        np.abs(1 + np.stack([rxx - ryy - rzz, -rxx + ryy - rzz, -rxx - ryy + rzz], axis=-1))
    )
    signs = np.sign(
        np.stack(
            [
                r[..., 2, 1] - r[..., 1, 2],
                r[..., 0, 2] - r[..., 2, 0],
                r[..., 1, 0] - r[..., 0, 1],
            ],
            axis=-1,
        )
    )
    xyz = signs * magnitudes
    trace = rxx + ryy + rzz
    w = np.sqrt(np.maximum(1 + trace, 0.0))[..., None] / 2.0
    q = np.concatenate([xyz, w], axis=-1)
    return _normalize(q).astype(np.float32)


def orientation_features(ca: np.ndarray, nbr_idx: np.ndarray):
    """Per-edge local-frame direction dU [N,K,3] and relative-orientation
    quaternion Q [N,K,4] (reference ``get_coarse_orientation_feats``,
    protein_feature_utils.py:201-273)."""
    frames = local_frames(ca)  # [N, 3, 3]
    x_nbr = ca[nbr_idx]  # [N, K, 3]
    o_nbr = frames[nbr_idx]  # [N, K, 3, 3]
    dx = x_nbr - ca[:, None, :]
    du = _normalize(np.einsum("nij,nkj->nki", frames, dx))
    rel_r = np.einsum("nji,nkjl->nkil", frames, o_nbr)  # O_i^T @ O_j
    quat = rotations_to_quaternions(rel_r)
    return du.astype(np.float32), quat


def amide_normal_vectors(backbone: np.ndarray, cb: Optional[np.ndarray] = None) -> np.ndarray:
    """Per-residue amide-plane normal vector [N, 3].

    Reference computes cross(CA-CB, CB-N) from real CB atoms
    (``dips_plus_utils.py:356-374``, NaN when CB is missing, e.g. glycine).
    When CB coordinates are unavailable we substitute a virtual CB placed from
    the backbone frame, which keeps the feature well-defined for every residue.
    """
    n_at, ca, c_at = backbone[:, 0], backbone[:, 1], backbone[:, 2]
    if cb is None:
        # Virtual CB via standard tetrahedral construction.
        b1 = _normalize(ca - n_at)
        b2 = _normalize(c_at - ca)
        axis = _normalize(np.cross(b1, b2))
        cb = ca - 0.58273431 * (b1 + b2) + 0.56802827 * axis
    vec1 = ca - cb
    vec2 = cb - n_at
    return np.cross(vec1, vec2).astype(np.float32)


def amide_angle_features(norm_vecs: np.ndarray, nbr_idx: np.ndarray) -> np.ndarray:
    """Min-max-normalized angle between src and dst amide normals per edge
    [N, K] (reference: deepinteract_utils.py:513-530, NaN -> 0). The angle is
    symmetric in the two endpoints."""
    v_src = np.broadcast_to(norm_vecs[:, None, :], (*nbr_idx.shape, 3))  # center i
    v_dst = norm_vecs[nbr_idx]  # neighbor
    denom = np.linalg.norm(v_dst, axis=-1) * np.linalg.norm(v_src, axis=-1)
    with np.errstate(invalid="ignore", divide="ignore"):
        cos = np.sum(v_dst * v_src, axis=-1) / denom
        angles = np.arccos(np.clip(cos, -1.0, 1.0))
    angles = np.nan_to_num(angles, nan=0.0)
    return np.nan_to_num(min_max_normalize(angles), nan=0.0)


def sample_geo_neighborhoods(nbr_idx: np.ndarray, geo_nbrhd_size: int, rng: np.random.Generator):
    """For each edge (i, k) — source/center i, destination j = nbr_idx[i, k] —
    sample flat ids of ``geo_nbrhd_size`` edges incident to i (src side) and
    to j (dst side), drawn from each node's own K-edge row.

    Reference: the shuffled incident-edge subsampling at
    ``deepinteract_utils.py:532-553`` (flat edge id of (i, k) is i*K + k);
    see ``graph.ProteinGraph`` for the documented in-edge -> out-edge
    deviation.

    Distributional note: the permutation over row i's K slots can select the
    edge's *own* slot k as one of its "neighboring" edges, and for a
    self-loop edge (j == i) the dst-side draw samples the same row as the
    src side. The reference samples from shuffled in-edge lists, where the
    same degenerate picks occur but with a different distribution; exact
    sampling parity is not a goal (this runs once, in data prep).
    """
    n, k = nbr_idx.shape
    g = geo_nbrhd_size
    # Independent slot permutations per edge, truncated to g.
    src_slots = np.argsort(rng.random((n, k, k)), axis=-1)[..., :g].astype(np.int32)
    dst_slots = np.argsort(rng.random((n, k, k)), axis=-1)[..., :g].astype(np.int32)
    src_nbr_eids = (np.arange(n, dtype=np.int32)[:, None, None]) * k + src_slots  # row of source i
    dst_nbr_eids = nbr_idx[:, :, None] * k + dst_slots  # row of destination j
    return src_nbr_eids.astype(np.int32), dst_nbr_eids.astype(np.int32)


def featurize_chain(
    backbone: np.ndarray,
    residue_feats: np.ndarray,
    knn: int = constants.KNN,
    geo_nbrhd_size: int = constants.GEO_NBRHD_SIZE,
    self_loops: bool = True,
    amide_norm_vecs: Optional[np.ndarray] = None,
    rng: Optional[np.random.Generator] = None,
) -> Dict[str, np.ndarray]:
    """Full per-chain featurization -> dict of unpadded arrays.

    Args:
      backbone: [N, 4, 3] N/CA/C/O coordinates (NaNs allowed; zero-masked as
        in the reference, deepinteract_utils.py:470-473).
      residue_feats: [N, 106] DIPS-Plus residue features (columns 7..113 of
        the node schema).

    Returns dict consumable by :func:`deepinteract_tpu.data.graph.pad_graph`.
    """
    rng = rng or np.random.default_rng(0)
    n = backbone.shape[0]
    if residue_feats.shape != (n, constants.NUM_NODE_FEATS - 7):
        raise ValueError(f"residue_feats must be [N, 106], got {residue_feats.shape}")

    backbone = np.nan_to_num(backbone, nan=0.0).astype(np.float32)
    ca = backbone[:, 1, :]

    nbr_idx, sq_dists = knn_edges(ca, knn, self_loops=self_loops)

    # Node features: [pos_enc | dihedrals(6) | DIPS-Plus(106)]
    pos_enc = min_max_normalize(np.arange(n, dtype=np.float32))[:, None]
    node_feats = np.concatenate(
        [pos_enc, dihedral_features(backbone), residue_feats.astype(np.float32)], axis=1
    )

    # Edge features: [sin(src-dst) | weight | rbf(18) | dir(3) | quat(4) | amide]
    # src = center i, dst = nbr_idx[i, k] (reference: deepinteract_utils.py:503).
    edge_pos_enc = np.sin((np.arange(n, dtype=np.int32)[:, None] - nbr_idx).astype(np.float32))
    edge_weights = min_max_normalize(sq_dists).reshape(n, knn)
    rbf = rbf_features(sq_dists)
    du, quat = orientation_features(ca, nbr_idx)
    if amide_norm_vecs is None:
        amide_norm_vecs = amide_normal_vectors(backbone)
    amide = amide_angle_features(amide_norm_vecs, nbr_idx)
    edge_feats = np.concatenate(
        [edge_pos_enc[..., None], edge_weights[..., None], rbf, du, quat, amide[..., None]],
        axis=-1,
    ).astype(np.float32)
    assert edge_feats.shape == (n, knn, constants.NUM_EDGE_FEATS)

    src_nbr_eids, dst_nbr_eids = sample_geo_neighborhoods(nbr_idx, geo_nbrhd_size, rng)

    return {
        "node_feats": node_feats,
        "coords": ca,
        "edge_feats": edge_feats,
        "nbr_idx": nbr_idx,
        "src_nbr_eids": src_nbr_eids,
        "dst_nbr_eids": dst_nbr_eids,
    }
