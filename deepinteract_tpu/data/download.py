"""Artifact download with checksum verification.

Reference equivalent: the datasets' Zenodo download + sha1 gate
(``DIPSDGLDataset.download``, dips_dgl_dataset.py:151-170) and the
published-checkpoint pointers (README.md:249-253, Zenodo record 6671582).
Network access is environment-dependent; everything here degrades to a
clear error message rather than a silent partial tree.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import tempfile
import urllib.request
from typing import Optional

# Reference-published artifacts (README.md:249-253; dataset READMEs).
KNOWN_ARTIFACTS = {
    "checkpoints": "https://zenodo.org/record/6671582",
    "dips_plus": "https://zenodo.org/record/5134732",
}


def sha1_of(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha1()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def download_and_verify(url: str, dest: str, sha1: Optional[str] = None,
                        overwrite: bool = False) -> str:
    """Fetch ``url`` into ``dest``, verifying sha1 when given (the
    reference hard-fails on checksum mismatch; so do we). Returns dest."""
    if os.path.exists(dest) and not overwrite:
        if sha1 and sha1_of(dest) != sha1:
            raise ValueError(
                f"{dest} exists but fails its sha1 check; pass overwrite=True"
            )
        return dest
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest) or ".")
    os.close(fd)
    try:
        urllib.request.urlretrieve(url, tmp)
        if sha1:
            got = sha1_of(tmp)
            if got != sha1:
                raise ValueError(f"sha1 mismatch for {url}: {got} != {sha1}")
        shutil.move(tmp, dest)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return dest
