"""Artifact download with checksum verification and transient-fault retry.

Reference equivalent: the datasets' Zenodo download + sha1 gate
(``DIPSDGLDataset.download``, dips_dgl_dataset.py:151-170) and the
published-checkpoint pointers (README.md:249-253, Zenodo record 6671582).
Network access is environment-dependent; everything here degrades to a
clear error message rather than a silent partial tree.

Fault tolerance (robustness/retry.py):

* transient failures — ``URLError`` (connection refused/reset, DNS),
  socket timeouts, truncated bodies (Content-Length mismatch), HTTP
  5xx/429 — are retried with exponential backoff + jitter *before* the
  sha1 gate ever sees the file;
* permanent failures — HTTP 4xx, and a completed download whose sha1
  does not match — hard-fail immediately with the original error (a
  checksum mismatch on a complete body means the artifact is wrong, not
  the network);
* every fetch carries an explicit socket timeout (``DI_DOWNLOAD_TIMEOUT``
  seconds, default 60) — the stock ``urlretrieve`` blocks forever on a
  stalled peer, which is how unattended dataset builds hang for days.
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import tempfile
import urllib.request
from typing import Optional
from urllib.error import ContentTooShortError, HTTPError, URLError

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import artifacts, faults
from deepinteract_tpu.robustness.retry import retry

DOWNLOAD_KIND = "download"

logger = logging.getLogger(__name__)

_FETCH_ATTEMPTS = obs_metrics.counter(
    "di_download_fetch_attempts_total",
    "Download attempts (including retried and faulted ones)")
_REFETCHES = obs_metrics.counter(
    "di_download_refetches_total",
    "Existing destinations replaced by an overwrite refetch")

# Reference-published artifacts (README.md:249-253; dataset READMEs).
KNOWN_ARTIFACTS = {
    "checkpoints": "https://zenodo.org/record/6671582",
    "dips_plus": "https://zenodo.org/record/5134732",
}

DEFAULT_TIMEOUT_SECONDS = 60.0


def sha1_of(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha1()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def _is_transient(exc: BaseException) -> bool:
    """Retry triage: HTTP 4xx is a permanent answer from the server; 5xx,
    429, and every other URLError/timeout/truncation is transient."""
    if isinstance(exc, HTTPError):
        return exc.code >= 500 or exc.code == 429
    return isinstance(exc, (URLError, ContentTooShortError, TimeoutError, OSError))


@retry(
    exceptions=(URLError, ContentTooShortError, TimeoutError, OSError),
    retryable=_is_transient,
    max_attempts=4,
    base_delay=1.0,
    max_delay=30.0,
    label="download.fetch",
)
def _fetch(url: str, tmp: str, timeout: float) -> None:
    """One streaming download attempt into ``tmp`` (truncation-checked)."""
    _FETCH_ATTEMPTS.inc()
    faults.maybe_raise(
        "download.fetch",
        lambda: URLError("injected transient network failure"),
    )
    # di: allow[artifact-write] streaming fetch into an mkstemp tmp; atomicity is the verified move below
    with urllib.request.urlopen(url, timeout=timeout) as resp, open(tmp, "wb") as f:
        shutil.copyfileobj(resp, f, length=1 << 20)
        written = f.tell()
    expected = resp.headers.get("Content-Length")
    if expected is not None and written != int(expected):
        raise ContentTooShortError(
            f"retrieved {written} of {expected} bytes from {url}", None
        )


def download_and_verify(url: str, dest: str, sha1: Optional[str] = None,
                        overwrite: bool = False,
                        timeout: Optional[float] = None) -> str:
    """Fetch ``url`` into ``dest``, verifying sha1 when given (the
    reference hard-fails on checksum mismatch; so do we). Returns dest.

    An existing ``dest`` with a failing checksum raises unless
    ``overwrite=True``, which deletes and refetches it; the replacement is
    staged in a temp file and moved into place atomically, so a crash
    mid-download never leaves a half-written ``dest``. Truncation is a
    RETRYABLE transport failure (Content-Length mismatch inside
    ``_fetch``), never a cached half-file.

    Completed downloads get a SHA-256 integrity sidecar
    (robustness/artifacts.py), so a re-run skips files it can verify on
    disk — including unchecksummed ones — and a corrupt cached file (bits
    no longer matching the sidecar) is quarantined and refetched instead
    of being trusted or crashing the build.
    """
    if os.path.exists(dest) and not overwrite:
        try:
            manifest = artifacts.verify_file(dest, kind=DOWNLOAD_KIND,
                                             require_sidecar=False)
        except artifacts.ArtifactError as exc:
            # Positive corruption against the recorded hash: quarantine
            # and fall through to a fresh fetch.
            artifacts.quarantine(dest, DOWNLOAD_KIND, str(exc))
        else:
            if manifest is None:
                # Legacy file, no sidecar: the old sha1 gate, then adopt
                # it into the sidecar regime so the NEXT re-run skips it
                # on one streamed hash.
                if sha1 and sha1_of(dest) != sha1:
                    raise ValueError(
                        f"{dest} exists but fails its sha1 check; pass "
                        "overwrite=True")
                artifacts.write_sidecar(dest, DOWNLOAD_KIND,
                                        extra={"url": url, "sha1": sha1})
                return dest
            recorded = (manifest.get("extra") or {}).get("sha1")
            if sha1 and recorded and recorded != sha1:
                raise ValueError(
                    f"{dest} exists but was recorded with sha1 {recorded}, "
                    f"not the requested {sha1}; pass overwrite=True")
            if sha1 and not recorded and sha1_of(dest) != sha1:
                raise ValueError(
                    f"{dest} exists but fails its sha1 check; pass "
                    "overwrite=True")
            return dest
    if timeout is None:
        raw = os.environ.get("DI_DOWNLOAD_TIMEOUT")
        try:
            timeout = float(raw) if raw is not None else DEFAULT_TIMEOUT_SECONDS
        except ValueError:
            # Same lenient policy as the DI_RETRY_* knobs: a typo'd env
            # var must not kill an unattended build.
            logger.warning("ignoring malformed DI_DOWNLOAD_TIMEOUT=%r", raw)
            timeout = DEFAULT_TIMEOUT_SECONDS
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest) or ".")
    os.close(fd)
    try:
        _fetch(url, tmp, timeout)
        if sha1:
            got = sha1_of(tmp)
            if got != sha1:
                raise ValueError(f"sha1 mismatch for {url}: {got} != {sha1}")
        if overwrite and os.path.exists(dest):
            _REFETCHES.inc()
            logger.info("overwrite: replacing %s (failed or forced)", dest)
        shutil.move(tmp, dest)
        # Completed + verified: record the SHA-256 so re-runs skip this
        # file after one streamed hash instead of refetching or trusting
        # it blindly.
        artifacts.write_sidecar(dest, DOWNLOAD_KIND,
                                extra={"url": url, "sha1": sha1})
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return dest
