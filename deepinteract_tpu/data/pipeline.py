"""Mesh-aware batch placement: the loader→step boundary as a pipeline stage.

Before this layer, batch *placement* was a trainer-side afterthought: the
``--device_prefetch`` hook issued a bare ``jax.device_put`` on the
loader's prefetch thread, and skipped itself whenever a mesh was active
or ``steps_per_dispatch > 1`` — exactly the scanned/sharded regime where
sustained rate matters (ROADMAP item 4; tools/sustained_train.py measured
~51% of the micro-bench scan rate with the h2d + scan-stacking on the
dispatch critical path). This module makes placement a first-class,
pluggable stage of the input pipeline:

* :class:`BatchPlacement` — the sharding-aware placement function for one
  (mesh, steps_per_dispatch) configuration. Single-device batches are
  ``jax.device_put``; mesh batches land PRE-SHARDED via per-leaf
  ``NamedSharding`` built from the same ``parallel/mesh.py`` constructors
  the sharded step functions use for ``in_shardings`` (multi-host safe:
  each host places only its local shard through
  ``make_array_from_process_local_data``).
* Scan-stacking for ``steps_per_dispatch > 1`` happens HERE — the
  ``np.stack`` of K batches plus the h2d of the [K, B, ...] stack runs on
  the placement thread, off the dispatch critical path (the FlashAttention
  discipline one level up: keep the device fed so the kernels stay the
  bottleneck).
* :func:`placed_runs` — the double-buffered background stage: placements
  run on a daemon thread with a semaphore bound, so at most ``depth``
  dispatches of device memory are ever pinned ahead of the consumer.

Telemetry: every placement records wall seconds and payload bytes in the
``di_data_h2d_seconds_total`` / ``di_data_h2d_bytes_total`` counters (and
returns them on the :class:`PlacedRun` so the Trainer's ``tele_h2d``
decomposition reflects the overlapped reality). Chaos: the ``data.place``
fault site raises (surfaced as a typed :class:`PlacementError`, never a
hang) and ``data.place_hang`` freezes the placement thread — the
wedged-input-pipeline simulation the PR-14 supervisor watchdog SIGKILLs.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from typing import Any, List, NamedTuple, Optional

import numpy as np

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import faults

_H2D_SECONDS = obs_metrics.counter(
    "di_data_h2d_seconds_total",
    "Wall seconds spent placing train batches on device by the input "
    "pipeline's placement layer (overlaps device compute when "
    "--device_prefetch is on)")
_H2D_BYTES = obs_metrics.counter(
    "di_data_h2d_bytes_total",
    "Host bytes handed to device placement by the input pipeline's "
    "placement layer")
_PLACED_DISPATCHES = obs_metrics.counter(
    "di_data_placed_dispatches_total",
    "Dispatch payloads (single batches or [K, B, ...] scan-stacks) "
    "placed by the input pipeline's placement layer",
    labelnames=("mode",))


class PlacementError(RuntimeError):
    """Typed failure of the batch-placement stage.

    Raised on the CONSUMER side (the trainer's dispatch loop) even when
    the placement itself ran on the background thread — a placement
    fault must surface as an exception at the next dispatch boundary,
    never as a silently wedged queue."""


class PlacedRun(NamedTuple):
    """One same-shape run of host batches plus its placed dispatch form.

    ``kind`` selects how the trainer dispatches it:

    * ``"per_batch"`` — ``placed`` is a list aligned with ``host``; each
      entry is one single-batch dispatch (runs shorter than the scan
      width, or ``steps_per_dispatch == 1``).
    * ``"packed"``    — ``placed`` is ``(buffers, spec)`` from
      ``training.steps.pack_tree`` over the [K, B, ...] stack (single
      device; one buffer per dtype, O(dtypes) transfers).
    * ``"stacked"``   — ``placed`` is the [K, B, ...] pytree sharded over
      the mesh (scan axis unsharded, batch axis over ``data``).

    ``h2d_s`` aligns with ``placed`` for ``per_batch`` (one float per
    batch) and holds a single float otherwise. Byte accounting lives in
    the ``di_data_h2d_bytes_total`` counter (recorded at placement time),
    not here."""

    host: List[Any]
    kind: str
    placed: Any
    h2d_s: tuple


def is_placed(tree) -> bool:
    """True when the pytree's array leaves are already device-committed
    ``jax.Array``s (placement must not run twice)."""
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    return bool(leaves) and isinstance(leaves[0], jax.Array)


def _tree_nbytes(tree) -> int:
    import jax

    return int(sum(np.asarray(l).nbytes
                   for l in jax.tree_util.tree_leaves(tree)))


def _chaos_probe(mode: str) -> None:
    """The placement-stage fault sites (robustness/faults.py): a raise
    must surface to the trainer as a typed error; a hang freezes THIS
    thread (the placement thread under ``placed_runs``) while the
    heartbeat daemon keeps beating — exactly the stale-progress
    signature the training supervisor watchdog SIGKILLs."""
    faults.maybe_raise(
        "data.place",
        lambda: PlacementError(
            f"injected data.place fault (placement mode {mode})"))
    if faults.fire("data.place_hang"):
        import logging

        logging.getLogger(__name__).error(
            "data.place_hang fault injected: placement frozen until "
            "SIGKILL (watchdog bait)")
        while True:
            time.sleep(0.25)


@dataclasses.dataclass(frozen=True)
class BatchPlacement:
    """The placement function for one dispatch configuration.

    ``transfer=False`` is the inline (no-prefetch) configuration: it
    performs exactly the host-side preparation the dispatch path always
    did (mesh batches are sharded — mandatory — while single-device
    batches stay host-resident for jit to place at dispatch), so the
    non-prefetch path is bit-for-bit the historical one. ``transfer=True``
    additionally issues the h2d eagerly so it can run off the critical
    path."""

    mesh: Any = None
    steps_per_dispatch: int = 1
    transfer: bool = True

    @property
    def mode(self) -> str:
        """``single``/``mesh`` × ``per-step``/``scanned`` — the four
        dispatch modes prefetch now engages in (the fit-start log line
        and the ``di_data_placed_dispatches_total`` label)."""
        return ("mesh" if self.mesh is not None else "single") + "/" + (
            "scanned" if self.steps_per_dispatch > 1 else "per-step")

    # -- primitives --------------------------------------------------------

    def place_batch(self, batch):
        """Place one [B, ...] batch for a single-step dispatch."""
        _chaos_probe(self.mode)
        if self.mesh is None and is_placed(batch):
            # Already committed (external device_transfer hook) and no
            # mesh to satisfy: idempotent passthrough. Mesh batches fall
            # through regardless — a hook-committed single-device array
            # must still be resharded to the step's in_shardings.
            return batch
        try:
            if self.mesh is not None:
                from deepinteract_tpu.parallel.mesh import shard_batch

                return self._timed(batch, lambda: shard_batch(batch, self.mesh))
            if not self.transfer:
                return batch  # jit places at dispatch (historical path)
            import jax

            return self._timed(batch, lambda: jax.device_put(batch))
        except PlacementError:
            raise
        except Exception as exc:
            raise PlacementError(
                f"batch placement failed (mode {self.mode}): {exc}"
            ) from exc

    def place_stacked(self, run: List[Any]):
        """Stack a full same-shape run into its one-dispatch form and
        place it: mesh → [K, B, ...] sharded over ``data`` (scan axis
        unsharded); single device → the packed upload (one buffer per
        dtype, ``training.steps.pack_tree``), device-placed when
        ``transfer``. Returns the ``PlacedRun.placed`` payload."""
        _chaos_probe(self.mode)
        from deepinteract_tpu.training.steps import (
            pack_tree,
            stack_microbatches,
        )

        try:
            stacked = stack_microbatches(run)
            if self.mesh is not None:
                from deepinteract_tpu.parallel.mesh import shard_stacked_batch

                return self._timed(
                    stacked, lambda: shard_stacked_batch(stacked, self.mesh))
            buffers, spec = pack_tree(stacked)
            if not self.transfer:
                return buffers, spec  # jit places at dispatch
            import jax

            return self._timed(buffers, lambda: jax.device_put(buffers)), spec
        except PlacementError:
            raise
        except Exception as exc:
            raise PlacementError(
                f"scan-stack placement failed (mode {self.mode}): {exc}"
            ) from exc

    def _timed(self, host_payload, place_fn):
        t0 = time.perf_counter()
        placed = place_fn()
        _H2D_SECONDS.inc(time.perf_counter() - t0)
        _H2D_BYTES.inc(_tree_nbytes(host_payload))
        _PLACED_DISPATCHES.inc(mode=self.mode)
        return placed

    # -- the run-level stage -----------------------------------------------

    def place_run(self, run: List[Any]) -> PlacedRun:
        """One same-shape run → its :class:`PlacedRun`, dispatch-shape
        aware (mirrors the trainer's run handling: runs shorter than the
        scan width dispatch per batch)."""
        k = max(1, self.steps_per_dispatch)
        if len(run) < max(k, 2):
            placed, times = [], []
            for b in run:
                t0 = time.perf_counter()
                placed.append(self.place_batch(b))
                times.append(time.perf_counter() - t0)
            return PlacedRun(host=run, kind="per_batch", placed=placed,
                             h2d_s=tuple(times))
        t0 = time.perf_counter()
        placed = self.place_stacked(run)
        dur = time.perf_counter() - t0
        kind = "stacked" if self.mesh is not None else "packed"
        return PlacedRun(host=run, kind=kind, placed=placed,
                         h2d_s=(dur,))


def placed_runs(runs, placement: BatchPlacement, depth: int):
    """Double-buffered placement stage: consume same-shape runs from
    ``runs`` on a daemon thread, place each via ``placement.place_run``,
    and yield :class:`PlacedRun`s to the dispatch loop.

    Memory bound: a semaphore slot is reserved BEFORE each placement and
    released only when the consumer asks for the NEXT item, so at most
    ``depth`` placed dispatches of device memory are pinned by the stage
    (including the one currently being dispatched). Exceptions — from the
    source iterator or the placement itself — propagate to the consumer
    at its next pull; abandoning the generator (break / GeneratorExit)
    stops the worker instead of leaving it blocked with pinned batches.
    """
    depth = max(1, int(depth))
    sem = threading.Semaphore(depth)
    q: "queue.Queue" = queue.Queue()
    done = object()
    stop = threading.Event()

    def worker():
        try:
            for run in runs:
                while not sem.acquire(timeout=0.1):
                    if stop.is_set():
                        return
                if stop.is_set():
                    return
                q.put(placement.place_run(run))
        except BaseException as exc:  # noqa: BLE001 - re-raised consumer-side
            q.put((done, exc))
            return
        q.put((done, None))

    t = threading.Thread(target=worker, daemon=True, name="di-placement")
    t.start()
    try:
        while True:
            item = q.get()
            if isinstance(item, tuple) and len(item) == 2 and item[0] is done:
                if item[1] is not None:
                    raise item[1]
                return
            yield item
            # Released only once the consumer came back for more: the
            # just-yielded dispatch still counts against the pin bound
            # while it is in flight.
            sem.release()
    finally:
        stop.set()
