"""Converter: reference processed complexes -> our ``.npz`` format.

The reference stores each complex as a pickled dict
``{'graph1': dgl.DGLGraph, 'graph2': dgl.DGLGraph, 'examples': torch.Tensor,
'complex': str}`` (``process_complex_into_dict``,
deepinteract_utils.py:924-965). Its DGL kNN graphs have fixed out-degree K
with edges grouped by source node in row-major order, so the COO edge list
maps losslessly onto our dense ``[N, K]`` layout (flat edge id i*K + k —
see ``data.graph.ProteinGraph``).

Inputs accepted per graph:
  * a real ``dgl.DGLGraph`` (if dgl is importable in the converting env), or
  * a plain schema-identical dict:
      {'num_nodes': int, 'edges': (src [E], dst [E]),
       'ndata': {'f': [N, 113], 'x': [N, 3]},
       'edata': {'f': [E, 28] (or [E, 27, 1] as the reference stores it),
                 'src_nbr_e_ids': [E, G], 'dst_nbr_e_ids': [E, G]}}
    — the form produced by dumping a DGL graph's fields to numpy anywhere
    dgl exists, so conversion itself needs no dgl.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, Tuple

import numpy as np

from deepinteract_tpu import constants
from deepinteract_tpu.data.io import save_complex_npz


def _as_numpy(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch.Tensor without importing torch
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _graph_fields(graph) -> Tuple[int, np.ndarray, np.ndarray, Dict, Dict]:
    """Extract (num_nodes, src, dst, ndata, edata) from either input form."""
    if isinstance(graph, dict):
        src, dst = graph["edges"]
        return (
            int(graph["num_nodes"]),
            _as_numpy(src),
            _as_numpy(dst),
            {k: _as_numpy(v) for k, v in graph["ndata"].items()},
            {k: _as_numpy(v) for k, v in graph["edata"].items()},
        )
    # Duck-typed dgl.DGLGraph.
    src, dst = graph.edges()
    return (
        int(graph.num_nodes()),
        _as_numpy(src),
        _as_numpy(dst),
        {k: _as_numpy(v) for k, v in graph.ndata.items()},
        {k: _as_numpy(v) for k, v in graph.edata.items()},
    )


def reference_graph_to_raw(graph) -> Dict[str, np.ndarray]:
    """One reference graph -> our unpadded raw dict (``io.GRAPH_KEYS``)."""
    n, src, dst, ndata, edata = _graph_fields(graph)
    e = src.shape[0]
    if n == 0 or e % n != 0:
        raise ValueError(f"edge count {e} is not a multiple of node count {n}")
    k = e // n

    # DGL knn_graph convention: edges grouped by source, K per node,
    # row-major flat ids (verified against deepinteract_utils.py:476).
    expected_src = np.repeat(np.arange(n, dtype=src.dtype), k)
    if not np.array_equal(src, expected_src):
        # Re-sort into row-major source-grouped order (stable keeps each
        # source's neighbor order).
        order = np.argsort(src, kind="stable")
        src, dst = src[order], dst[order]
        if not np.array_equal(src, expected_src):
            raise ValueError("graph is not a fixed out-degree kNN graph")
        edata = {key: v[order] for key, v in edata.items()}
        # Flat edge ids in neighbor-id arrays refer to the ORIGINAL edge
        # ordering; remap them.
        inv = np.empty(e, dtype=np.int64)
        inv[order] = np.arange(e)
        for key in ("src_nbr_e_ids", "dst_nbr_e_ids"):
            if key in edata:
                edata[key] = inv[edata[key].astype(np.int64)]

    edge_feats = edata["f"]
    if edge_feats.ndim == 3:  # reference stores [E, C, 1]
        edge_feats = edge_feats.squeeze(-1)
    if edge_feats.shape[-1] == constants.NUM_EDGE_FEATS - 1:
        # A 27-column variant (without the amide angle): zero-pad to 28.
        edge_feats = np.concatenate(
            [edge_feats, np.zeros((e, 1), edge_feats.dtype)], axis=-1
        )
    if edge_feats.shape[-1] != constants.NUM_EDGE_FEATS:
        raise ValueError(f"unexpected edge feature width {edge_feats.shape[-1]}")

    node_feats = ndata["f"]
    if node_feats.shape[-1] != constants.NUM_NODE_FEATS:
        raise ValueError(f"unexpected node feature width {node_feats.shape[-1]}")

    g = edata["src_nbr_e_ids"].shape[-1]
    return {
        "node_feats": node_feats.astype(np.float32),
        "coords": ndata["x"].astype(np.float32),
        "edge_feats": edge_feats.astype(np.float32).reshape(n, k, constants.NUM_EDGE_FEATS),
        "nbr_idx": dst.astype(np.int32).reshape(n, k),
        "src_nbr_eids": edata["src_nbr_e_ids"].astype(np.int32).reshape(n, k, g),
        "dst_nbr_eids": edata["dst_nbr_e_ids"].astype(np.int32).reshape(n, k, g),
    }


def reference_dict_to_npz(processed: Dict, npz_path: str) -> None:
    """Convert one loaded reference processed-complex dict and write npz."""
    raw1 = reference_graph_to_raw(processed["graph1"])
    raw2 = reference_graph_to_raw(processed["graph2"])
    examples = _as_numpy(processed["examples"]).astype(np.int32)
    save_complex_npz(npz_path, raw1, raw2, examples,
                     complex_name=str(processed.get("complex", "")))


def convert_file(dill_path: str, npz_path: str) -> None:
    """Convert one reference ``.dill`` file. Unpickling real files requires
    the ``dgl``/``torch`` of the producing environment; plain-dict pickles
    (see module docstring) load anywhere."""
    with open(dill_path, "rb") as f:
        try:
            processed = pickle.load(f)
        except ModuleNotFoundError as e:
            raise ModuleNotFoundError(
                f"{dill_path} pickles {e.name} objects; either convert in an "
                "environment with the reference's dependencies, or dump the "
                "graphs to the plain-dict form documented in "
                "deepinteract_tpu.data.convert"
            ) from e
    reference_dict_to_npz(processed, npz_path)


def convert_tree(src_root: str, dst_root: str, suffix: str = ".dill") -> int:
    """Convert every ``*.dill`` under ``src_root`` into a mirrored ``.npz``
    tree under ``dst_root``. Returns the number converted."""
    count = 0
    for dirpath, _, files in os.walk(src_root):
        for fname in files:
            if not fname.endswith(suffix):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fname), src_root)
            dst = os.path.join(dst_root, os.path.splitext(rel)[0] + ".npz")
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            convert_file(os.path.join(dirpath, fname), dst)
            count += 1
    return count
