"""Dataset ops/analysis tooling: statistics, split partitioning, leakage.

Reference equivalents (SURVEY.md §1 Lx, §2.3):
  * dataset statistics   — ``builder/collect_dataset_statistics.py`` /
    ``log_dataset_statistics.py`` (dips_plus_utils.py:686-827)
  * split partitioner    — ``builder/partition_dataset_filenames.py`` (size
    filters + random 80/20 train/test with 25% of train as val)
  * sequence-identity / leakage audit — ``check_percent_identity``
    (deepinteract_utils.py:865-921) and ``misc/check_leakage.py:37-53``
  * length audit         — ``misc/check_length.py``

All operate on the npz complex tree (``data.io``); alignment-based identity
uses a simple O(nm) Needleman-Wunsch (the reference uses Bio.pairwise2
``globalxx`` — match=1, no mismatch/gap penalties — whose score equals the
LCS length, which is exactly what ``_global_align_score`` computes).
"""

from __future__ import annotations

import os
import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepinteract_tpu import constants
from deepinteract_tpu.data.io import load_complex_npz


# ---------------------------------------------------------------------------
# Statistics
# ---------------------------------------------------------------------------

def complex_statistics(raw: Dict) -> Dict[str, float]:
    """Per-complex stats row (reference ``collect_dataset_statistics``)."""
    n1 = raw["graph1"]["node_feats"].shape[0]
    n2 = raw["graph2"]["node_feats"].shape[0]
    examples = raw["examples"]
    num_pos = int(examples[:, 2].sum())
    return {
        "num_nodes_1": n1,
        "num_nodes_2": n2,
        "num_pairs": int(examples.shape[0]),
        "num_pos_contacts": num_pos,
        "pos_rate": num_pos / max(examples.shape[0], 1),
        "fits_residue_limit": int(
            n1 <= constants.RESIDUE_COUNT_LIMIT and n2 <= constants.RESIDUE_COUNT_LIMIT
        ),
    }


def collect_statistics(npz_paths: Sequence[str], csv_out: Optional[str] = None) -> Dict:
    rows = []
    for path in npz_paths:
        row = complex_statistics(load_complex_npz(path))
        row["target"] = os.path.splitext(os.path.basename(path))[0]
        rows.append(row)
    agg = {
        "num_complexes": len(rows),
        "num_valid_pairs": sum(r["fits_residue_limit"] for r in rows),
        "total_pos_contacts": sum(r["num_pos_contacts"] for r in rows),
        "median_n1": float(np.median([r["num_nodes_1"] for r in rows])) if rows else 0.0,
        "median_n2": float(np.median([r["num_nodes_2"] for r in rows])) if rows else 0.0,
    }
    if csv_out and rows:
        cols = [c for c in rows[0] if c != "target"]
        with open(csv_out, "w") as f:
            f.write("target," + ",".join(cols) + "\n")
            for r in rows:
                f.write(r["target"] + "," + ",".join(str(r[c]) for c in cols) + "\n")
    return agg


# ---------------------------------------------------------------------------
# Split partitioning
# ---------------------------------------------------------------------------

def partition_filenames(
    names_and_lengths: Sequence[Tuple[str, int, int]],
    seed: int = 42,
    test_frac: float = 0.2,
    val_frac_of_train: float = 0.25,
    max_residues: int = constants.RESIDUE_COUNT_LIMIT,
    max_pairs: Optional[int] = None,
) -> Dict[str, List[str]]:
    """Size-filter + random split (reference
    ``builder/partition_dataset_filenames.py:44-110``: drops complexes whose
    chains exceed the residue limit or whose pair count exceeds 256^2, then
    80/20 train/test with 25% of train as val). ``max_pairs`` defaults to
    the reference's RESIDUE_COUNT_LIMIT^2 pair-area cap."""
    if max_pairs is None:
        max_pairs = constants.RESIDUE_COUNT_LIMIT ** 2
    eligible = [
        name for name, n1, n2 in names_and_lengths
        if n1 <= max_residues and n2 <= max_residues and n1 * n2 < max_pairs
    ]
    rng = random.Random(seed)
    rng.shuffle(eligible)
    n_test = int(len(eligible) * test_frac)
    test, trainval = eligible[:n_test], eligible[n_test:]
    n_val = int(len(trainval) * val_frac_of_train)
    val, train = trainval[:n_val], trainval[n_val:]
    return {"train": sorted(train), "val": sorted(val), "test": sorted(test)}


def write_split_files(root: str, splits: Dict[str, List[str]]) -> None:
    for mode, names in splits.items():
        with open(os.path.join(root, f"pairs-postprocessed-{mode}.txt"), "w") as f:
            f.write("\n".join(names) + ("\n" if names else ""))


# ---------------------------------------------------------------------------
# Sequence identity / leakage
# ---------------------------------------------------------------------------

_RES_TO_CHAR = {i: c for i, c in enumerate("WFKPDARCVTGSHLEYINMQ")}  # ALLOWABLE_RESNAMES order


def sequence_of(raw_graph: Dict) -> str:
    """1-letter sequence recovered from the residue-type one-hot block."""
    onehot = raw_graph["node_feats"][:, constants.NODE_RESNAME_ONE_HOT]
    idx = np.argmax(onehot, axis=1)
    known = onehot.sum(axis=1) > 0
    return "".join(_RES_TO_CHAR[int(i)] if k else "X" for i, k in zip(idx, known))


def _global_align_score(a: str, b: str) -> int:
    """Needleman-Wunsch with match=1, mismatch=0, gap=0 — equivalent to the
    LCS length, matching Bio.pairwise2.align.globalxx scoring used by the
    reference (deepinteract_utils.py:882-913; see module docstring)."""
    if not a or not b:
        return 0
    prev = np.zeros(len(b) + 1, dtype=np.int32)
    for ca in a:
        cur = np.zeros_like(prev)
        bs = np.frombuffer(b.encode(), dtype=np.uint8)
        match = (bs == ord(ca)).astype(np.int32)
        for j in range(1, len(b) + 1):
            cur[j] = max(prev[j], cur[j - 1], prev[j - 1] + match[j - 1])
        prev = cur
    return int(prev[-1])


def percent_identity(seq_a: str, seq_b: str) -> float:
    """Reference convention: alignment score / min(len_a, len_b)
    (check_percent_identity, deepinteract_utils.py:899-913)."""
    denom = min(len(seq_a), len(seq_b))
    if denom == 0:
        return 0.0
    return _global_align_score(seq_a, seq_b) / denom


def check_leakage(
    candidate_paths: Sequence[str],
    test_paths: Sequence[str],
    threshold: float = 0.3,
) -> List[Tuple[str, str, float]]:
    """Flag candidate complexes whose either chain exceeds ``threshold``
    identity with any test-set chain (reference misc/check_leakage.py:37-53,
    30% CD-HIT-style cutoff)."""
    test_seqs = []
    for path in test_paths:
        raw = load_complex_npz(path)
        test_seqs.append((os.path.basename(path), sequence_of(raw["graph1"])))
        test_seqs.append((os.path.basename(path), sequence_of(raw["graph2"])))
    leaks = []
    for path in candidate_paths:
        raw = load_complex_npz(path)
        for chain in (sequence_of(raw["graph1"]), sequence_of(raw["graph2"])):
            for test_name, test_seq in test_seqs:
                pid = percent_identity(chain, test_seq)
                if pid > threshold:
                    leaks.append((os.path.basename(path), test_name, pid))
                    break
            else:
                continue
            break
    return leaks


def length_audit(npz_paths: Sequence[str]) -> Dict[str, float]:
    """Chain-length distribution summary (reference misc/check_length.py)."""
    lengths = []
    for path in npz_paths:
        raw = load_complex_npz(path)
        lengths.append(raw["graph1"]["node_feats"].shape[0])
        lengths.append(raw["graph2"]["node_feats"].shape[0])
    arr = np.asarray(lengths) if lengths else np.zeros(1)
    return {
        "min": float(arr.min()),
        "median": float(np.median(arr)),
        "mean": float(arr.mean()),
        "max": float(arr.max()),
        "over_limit_frac": float((arr > constants.RESIDUE_COUNT_LIMIT).mean()),
    }
