"""Data substrate: statically-shaped residue graphs, featurization, datasets."""

from deepinteract_tpu.data.graph import ProteinGraph, PairedComplex, pad_graph, stack_graphs  # noqa: F401
