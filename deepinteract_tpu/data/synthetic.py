"""Synthetic protein-complex generator for tests and benchmarks.

The reference has no software test suite (SURVEY.md §4); our tests run on
synthetic-but-realistic complexes: a 3.8 Å-step self-avoiding-ish CA walk
with ideal backbone geometry, DIPS-Plus-like residue features, and contact
labels from an 8 Å inter-chain CA distance cutoff.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from deepinteract_tpu import constants
from deepinteract_tpu.data import features as F
from deepinteract_tpu.data.graph import PairedComplex, ProteinGraph, pad_graph, pick_bucket


def random_backbone(n: int, rng: np.random.Generator, origin=None) -> np.ndarray:
    """[N, 4, 3] N/CA/C/O coords along a smooth random CA trace."""
    steps = rng.normal(size=(n, 3))
    # Smooth the walk so it locally resembles secondary structure.
    for axis in range(3):
        steps[:, axis] = np.convolve(steps[:, axis], np.ones(4) / 4.0, mode="same")
    steps = steps / np.maximum(np.linalg.norm(steps, axis=1, keepdims=True), 1e-9) * 3.8
    ca = np.cumsum(steps, axis=0)
    if origin is not None:
        ca = ca - ca.mean(axis=0) + origin
    # Ideal-ish offsets for N, C, O around each CA in a wobbly local frame.
    t = np.arange(n)[:, None]
    wob = np.stack([np.sin(t * 1.7), np.cos(t * 1.3), np.sin(t * 0.9 + 1.0)], axis=-1)[:, 0, :]
    wob = wob / np.maximum(np.linalg.norm(wob, axis=1, keepdims=True), 1e-9)
    n_at = ca - 1.46 * wob
    c_at = ca + 1.52 * np.roll(wob, 1, axis=0)
    o_at = c_at + 1.23 * wob
    return np.stack([n_at, ca, c_at, o_at], axis=1).astype(np.float32)


def random_residue_feats(n: int, rng: np.random.Generator) -> np.ndarray:
    """[N, 106] DIPS-Plus-like residue features matching the node schema."""
    feats = np.zeros((n, constants.NUM_NODE_FEATS - 7), dtype=np.float32)
    off = 7  # schema offsets below are absolute; subtract node prefix

    def sl(s):  # absolute slice -> local
        return slice(s.start - off, s.stop - off)

    resname = rng.integers(0, 20, size=n)
    feats[np.arange(n), sl(constants.NODE_RESNAME_ONE_HOT).start + resname] = 1.0
    ss = rng.integers(0, 8, size=n)
    feats[np.arange(n), sl(constants.NODE_SS_ONE_HOT).start + ss] = 1.0
    feats[:, constants.NODE_RSA - off] = rng.random(n)
    feats[:, constants.NODE_RD - off] = rng.random(n)
    feats[:, sl(constants.NODE_PROTRUSION)] = rng.random((n, 6))
    hsaac = rng.random((n, constants.HSAAC_DIM))
    feats[:, sl(constants.NODE_HSAAC)] = hsaac / hsaac.sum(axis=1, keepdims=True)
    feats[:, constants.NODE_CN - off] = rng.random(n)
    feats[:, sl(constants.NODE_SEQUENCE_FEATS)] = rng.random((n, constants.NUM_SEQUENCE_FEATS))
    return feats


def random_chain_graph(
    n: int,
    rng: np.random.Generator,
    n_pad: Optional[int] = None,
    knn: int = constants.KNN,
    geo_nbrhd_size: int = constants.GEO_NBRHD_SIZE,
    origin=None,
) -> tuple[ProteinGraph, np.ndarray]:
    """Returns (padded graph, backbone [N, 4, 3])."""
    backbone = random_backbone(n, rng, origin=origin)
    raw = F.featurize_chain(
        backbone, random_residue_feats(n, rng), knn=knn, geo_nbrhd_size=geo_nbrhd_size, rng=rng
    )
    return pad_graph(raw, n_pad or pick_bucket(n)), backbone


def random_complex(
    n1: int,
    n2: int,
    rng: Optional[np.random.Generator] = None,
    n_pad1: Optional[int] = None,
    n_pad2: Optional[int] = None,
    knn: int = constants.KNN,
    geo_nbrhd_size: int = constants.GEO_NBRHD_SIZE,
    contact_cutoff: float = 8.0,
) -> PairedComplex:
    """Generate a two-chain complex with geometric contact labels."""
    rng = rng or np.random.default_rng(0)
    g1, bb1 = random_chain_graph(n1, rng, n_pad1, knn, geo_nbrhd_size, origin=np.zeros(3))
    # Place chain 2 adjacent so a genuine interface exists.
    g2, bb2 = random_chain_graph(n2, rng, n_pad2, knn, geo_nbrhd_size, origin=np.array([10.0, 0.0, 0.0]))

    ca1, ca2 = bb1[:, 1, :], bb2[:, 1, :]
    dists = np.linalg.norm(ca1[:, None, :] - ca2[None, :, :], axis=-1)
    contact = (dists < contact_cutoff).astype(np.int32)

    p1, p2 = g1.n_padded, g2.n_padded
    contact_map = np.zeros((p1, p2), dtype=np.int32)
    contact_map[:n1, :n2] = contact

    # Flattened (i, j, label) examples over all real pairs, padded
    # (reference example tensor: deepinteract_utils.py:558-582).
    ii, jj = np.meshgrid(np.arange(n1), np.arange(n2), indexing="ij")
    examples = np.stack([ii.ravel(), jj.ravel(), contact[:n1, :n2].ravel()], axis=1).astype(np.int32)
    m_pad = p1 * p2
    example_mask = np.zeros(m_pad, dtype=bool)
    example_mask[: examples.shape[0]] = True
    examples_padded = np.zeros((m_pad, 3), dtype=np.int32)
    examples_padded[: examples.shape[0]] = examples

    return PairedComplex(
        graph1=g1,
        graph2=g2,
        examples=examples_padded,
        example_mask=example_mask,
        contact_map=contact_map,
    )


def random_raw_complex(n1: int, n2: int, rng: np.random.Generator,
                       knn: int = constants.KNN,
                       geo_nbrhd_size: int = constants.GEO_NBRHD_SIZE,
                       contact_cutoff: float = 8.0) -> dict:
    """Un-padded raw complex dict (``{"graph1", "graph2", "examples"}``)
    in the dataset-protocol shape ``data/loader.InMemoryDataset``
    consumes — the loader-facing twin of :func:`random_complex`, for
    input-pipeline benchmarks/tests whose batches must flow through the
    REAL loader path (bucketing, padding, prefetch, placement)."""
    raws, cas = [], []
    for n, origin in ((n1, np.zeros(3)), (n2, np.array([10.0, 0.0, 0.0]))):
        bb = random_backbone(n, rng, origin=origin)
        raws.append(F.featurize_chain(
            bb, random_residue_feats(n, rng), knn=knn,
            geo_nbrhd_size=geo_nbrhd_size, rng=rng))
        cas.append(bb[:, 1, :])
    d = np.linalg.norm(cas[0][:, None] - cas[1][None, :], axis=-1)
    contact = (d < contact_cutoff).astype(np.int32)
    ii, jj = np.meshgrid(np.arange(n1), np.arange(n2), indexing="ij")
    examples = np.stack([ii.ravel(), jj.ravel(), contact.ravel()],
                        axis=1).astype(np.int32)
    return {"graph1": raws[0], "graph2": raws[1], "examples": examples}


def write_tiny_npz_dataset(root: str, n_complexes: int = 5,
                           n1: int = 24, n2: int = 21, seed: int = 0,
                           knn: int = 6, geo_nbrhd_size: int = 2) -> None:
    """Materialize a tiny on-disk DIPS-style dataset (processed/ npz tree
    + split files) that ``cli.train --dips_root`` consumes directly.

    The ONE builder the multi-host integration tests, the supervised
    self-healing chaos tests, and bench's ``recovery`` section share —
    same shapes, same seed discipline, so their subprocess train runs
    stay deterministic and mutually comparable. All ``n_complexes``
    complexes land in the train split; val/test reuse the first one."""
    import os

    from deepinteract_tpu.data.io import save_complex_npz

    processed = os.path.join(root, "processed")
    os.makedirs(processed, exist_ok=True)
    rng = np.random.default_rng(seed)
    names = []
    for i in range(n_complexes):
        raw = random_raw_complex(n1, n2, rng, knn=knn,
                                 geo_nbrhd_size=geo_nbrhd_size)
        name = f"c{i}.npz"
        save_complex_npz(os.path.join(processed, name), raw["graph1"],
                         raw["graph2"], raw["examples"],
                         complex_name=f"c{i}")
        names.append(name)
    for mode, sel in (("train", names), ("val", names[:1]),
                      ("test", names[:1])):
        # di: allow[artifact-write] regenerable synthetic split fixture
        with open(os.path.join(root, f"pairs-postprocessed-{mode}.txt"),
                  "w") as f:
            f.write("\n".join(sel) + "\n")
