"""On-disk complex format: one compressed ``.npz`` per complex.

TPU-native replacement for the reference's pickled DGL-graph dicts
(``process_complex_into_dict``, deepinteract_utils.py:924-965): plain numpy
arrays keyed by chain, loadable with zero framework dependencies, padded to
shape buckets only at load time so one file serves every bucket policy.

Schema (unpadded):
  g{1,2}_node_feats [N,113], g{1,2}_coords [N,3], g{1,2}_edge_feats [N,K,28],
  g{1,2}_nbr_idx [N,K], g{1,2}_src_nbr_eids / _dst_nbr_eids [N,K,G],
  examples [M,3] (i, j, label over ALL chain1 x chain2 pairs, reference
  ``build_examples_tensor`` deepinteract_utils.py:558-582),
  complex_name (str).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from deepinteract_tpu.data.graph import PairedComplex, pad_graph, pick_bucket

GRAPH_KEYS = ("node_feats", "coords", "edge_feats", "nbr_idx", "src_nbr_eids", "dst_nbr_eids")


def save_complex_npz(
    path: str,
    raw1: Dict[str, np.ndarray],
    raw2: Dict[str, np.ndarray],
    examples: np.ndarray,
    complex_name: str = "",
) -> None:
    payload = {}
    for prefix, raw in (("g1", raw1), ("g2", raw2)):
        for key in GRAPH_KEYS:
            payload[f"{prefix}_{key}"] = np.asarray(raw[key])
    payload["examples"] = np.asarray(examples, dtype=np.int32)
    payload["complex_name"] = np.asarray(complex_name)
    np.savez_compressed(path, **payload)


def load_complex_npz(path_or_file) -> Dict:
    """Load a complex from a path OR a binary file-like object (np.load
    accepts both — the serving layer feeds npz uploads through a BytesIO).
    ``complex_name`` is optional on read: every in-repo writer emits it,
    but third-party uploads may not."""
    with np.load(path_or_file, allow_pickle=False) as z:
        raw1 = {key: z[f"g1_{key}"] for key in GRAPH_KEYS}
        raw2 = {key: z[f"g2_{key}"] for key in GRAPH_KEYS}
        return {
            "graph1": raw1,
            "graph2": raw2,
            "examples": z["examples"],
            "complex_name": (str(z["complex_name"])
                             if "complex_name" in z else ""),
        }


def examples_to_contact_map(examples: np.ndarray, n1: int, n2: int) -> np.ndarray:
    """Dense 0/1 [n1, n2] map from the flattened (i, j, label) example list
    (inverse of the reference's ``build_examples_matrix_using_multi_indexing``)."""
    m = np.zeros((n1, n2), dtype=np.int32)
    m[examples[:, 0], examples[:, 1]] = examples[:, 2]
    return m


def to_paired_complex(
    raw: Dict,
    n_pad1: Optional[int] = None,
    n_pad2: Optional[int] = None,
    input_indep: bool = False,
) -> PairedComplex:
    """Pad a loaded complex into model-ready arrays.

    ``input_indep`` zeroes all node/edge input features — the reference's
    input-independence scientific control (``zero_out_complex_features``,
    deepinteract_utils.py:968-974).
    """
    raw1, raw2 = raw["graph1"], raw["graph2"]
    if input_indep:
        raw1 = dict(raw1, node_feats=np.zeros_like(raw1["node_feats"]),
                    edge_feats=np.zeros_like(raw1["edge_feats"]))
        raw2 = dict(raw2, node_feats=np.zeros_like(raw2["node_feats"]),
                    edge_feats=np.zeros_like(raw2["edge_feats"]))
    n1 = raw1["node_feats"].shape[0]
    n2 = raw2["node_feats"].shape[0]
    p1 = n_pad1 or pick_bucket(n1)
    p2 = n_pad2 or pick_bucket(n2)
    g1 = pad_graph(raw1, p1)
    g2 = pad_graph(raw2, p2)

    examples = np.asarray(raw["examples"], dtype=np.int32)
    contact_map = np.zeros((p1, p2), dtype=np.int32)
    contact_map[:n1, :n2] = examples_to_contact_map(examples, n1, n2)

    m_pad = p1 * p2
    examples_padded = np.zeros((m_pad, 3), dtype=np.int32)
    example_mask = np.zeros(m_pad, dtype=bool)
    examples_padded[: examples.shape[0]] = examples
    example_mask[: examples.shape[0]] = True

    return PairedComplex(
        graph1=g1,
        graph2=g2,
        examples=examples_padded,
        example_mask=example_mask,
        contact_map=contact_map,
    )


def complex_lengths(raw: Dict) -> Tuple[int, int]:
    return raw["graph1"]["node_feats"].shape[0], raw["graph2"]["node_feats"].shape[0]


def complex_lengths_from_file(path: str) -> Tuple[int, int]:
    """(n1, n2) read from npy headers only — no array decompression.

    Bucket planning and builder resume scan whole dataset trees for
    lengths; loading every array to read two shapes would turn those
    scans into full-dataset deserialization.
    """
    import zipfile

    header_readers = {
        (1, 0): np.lib.format.read_array_header_1_0,
        (2, 0): np.lib.format.read_array_header_2_0,
    }
    with zipfile.ZipFile(path) as z:
        out = []
        for member in ("g1_node_feats.npy", "g2_node_feats.npy"):
            with z.open(member) as f:
                version = np.lib.format.read_magic(f)
                reader = header_readers.get(tuple(version))
                if reader is None:  # unknown npy version: load the array
                    f2 = z.open(member)
                    out.append(int(np.lib.format.read_array(f2).shape[0]))
                    continue
                shape, _, _ = reader(f)
                out.append(int(shape[0]))
    return out[0], out[1]
