"""Pre-padded per-bucket memmap pack: batch assembly as mmap + stack.

The per-item host path (npz decompress -> pad -> re-layout in
``to_paired_complex``) runs on the data-loading core and was measured as a
main contributor to the sustained-training gap (3.1 c/s sustained vs ~7.5
predicted from device step times, BASELINE.md r4; VERDICT r4 item 3): the
reference hides the equivalent cost behind a dozen DataLoader worker
processes (``num_dataloader_workers``, project/utils/
deepinteract_utils.py:1070-1099), which a one-core host cannot.

A pack stores every complex ALREADY PADDED to its shape bucket, one
``.npy`` per pytree leaf per bucket, written once by :func:`pack_dataset`.
Batch assembly then is ``np.stack`` over rows of ``np.load(...,
mmap_mode='r')`` arrays — no decompression, no padding, no re-layout, and
the OS page cache absorbs re-reads across epochs. ``BucketedLoader``
detects a :class:`PackedDataset` by its ``padded_batch`` method and uses
the pack's stored buckets for planning, so batches are bit-identical to
the unpacked path (same ``to_paired_complex`` output, stacked).

Storage cost: pad ratio x raw size (a p128-bucket complex stores its full
128-row layout). That trade is the point — disk for host CPU.
"""

from __future__ import annotations

import json
import os
from collections import defaultdict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from deepinteract_tpu.robustness import artifacts

INDEX_NAME = "pack_index.json"
_PACK_VERSION = 1


def _treedef():
    """Flattening structure of a PairedComplex (registered dataclasses
    flatten in field order, so this is stable across processes)."""
    import jax

    from deepinteract_tpu.data.graph import PairedComplex, ProteinGraph

    dummy_graph = ProteinGraph(*([0] * 8))
    dummy = PairedComplex(dummy_graph, dummy_graph, 0, 0, 0)
    return jax.tree_util.tree_structure(dummy)


def _bucket_key(bucket: Tuple[int, int]) -> str:
    return f"{bucket[0]}x{bucket[1]}"


def _leaf_path(out_dir: str, bucket: Tuple[int, int], leaf_idx: int) -> str:
    return os.path.join(out_dir, f"bucket_{_bucket_key(bucket)}_leaf{leaf_idx}.npy")


def pack_dataset(dataset, out_dir: str, item_bucket_fn,
                 signature: str = "") -> str:
    """Write ``dataset`` as a pre-padded pack under ``out_dir``.

    ``item_bucket_fn(n1, n2) -> (b1, b2)`` decides each complex's bucket —
    pass the owning loader's ``_item_bucket`` so pack-time buckets match
    plan-time buckets (diagonal/max-bucket modes included). ``signature``
    should encode the bucket-fn flags (and anything else that changes pack
    content): an existing index is reused ONLY when version, signature,
    item count AND the per-item length list all match — a pack built
    under different flags or over changed data is rebuilt, not silently
    served stale.
    """
    import jax

    from deepinteract_tpu.data.io import to_paired_complex

    index_path = os.path.join(out_dir, INDEX_NAME)
    lengths = list(dataset.lengths())
    if os.path.exists(index_path):
        with open(index_path) as fh:
            existing = json.load(fh)
        if (existing.get("version") == _PACK_VERSION
                and existing.get("signature", "") == signature
                and existing.get("num_items") == len(lengths)
                and existing.get("lengths")
                == [list(map(int, ln)) for ln in lengths]):
            return out_dir
    os.makedirs(out_dir, exist_ok=True)

    groups: Dict[Tuple[int, int], List[int]] = defaultdict(list)
    for idx, (n1, n2) in enumerate(lengths):
        groups[tuple(item_bucket_fn(n1, n2))].append(idx)

    index = {
        "version": _PACK_VERSION,
        "signature": signature,
        "num_items": len(lengths),
        "lengths": [list(map(int, ln)) for ln in lengths],
        "targets": [str(dataset.target_of(i)) for i in range(len(lengths))],
        "buckets": {},
    }
    for bucket, idxs in sorted(groups.items()):
        writers = None
        for row, idx in enumerate(idxs):
            raw = dataset[idx]
            pc = to_paired_complex(
                raw, n_pad1=bucket[0], n_pad2=bucket[1],
                input_indep=raw.get("input_indep", False),
            )
            leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(pc)]
            if writers is None:
                writers = [
                    np.lib.format.open_memmap(
                        _leaf_path(out_dir, bucket, i), mode="w+",
                        dtype=leaf.dtype, shape=(len(idxs),) + leaf.shape,
                    )
                    for i, leaf in enumerate(leaves)
                ]
            for w, leaf in zip(writers, leaves):
                w[row] = leaf
        for w in writers:
            w.flush()
        index["buckets"][_bucket_key(bucket)] = {
            "bucket": list(bucket),
            "indices": idxs,
            "num_leaves": len(writers),
        }
    artifacts.atomic_write(index_path, json.dumps(index))
    return out_dir


class PackedDataset:
    """Loader-facing view of a pack directory.

    Implements the dataset protocol pieces ``BucketedLoader`` consumes
    (``lengths``/``target_of``/``__len__``) plus the fast-path methods the
    loader prefers when present: ``bucket_of(idx)`` (plan with pack-time
    buckets) and ``padded_batch(indices, bucket)`` (mmap + stack).
    """

    def __init__(self, pack_dir: str):
        self.pack_dir = pack_dir
        with open(os.path.join(pack_dir, INDEX_NAME)) as fh:
            self._index = json.load(fh)
        if self._index.get("version") != _PACK_VERSION:
            raise ValueError(
                f"pack version {self._index.get('version')} != {_PACK_VERSION}"
            )
        self._lengths = [tuple(ln) for ln in self._index["lengths"]]
        self._targets = list(self._index["targets"])
        # idx -> (bucket, row-in-bucket)
        self._where: Dict[int, Tuple[Tuple[int, int], int]] = {}
        for info in self._index["buckets"].values():
            bucket = tuple(info["bucket"])
            for row, idx in enumerate(info["indices"]):
                self._where[idx] = (bucket, row)
        self._mmaps: Dict[Tuple[int, int], List[np.ndarray]] = {}
        self._td = _treedef()

    def __len__(self) -> int:
        return self._index["num_items"]

    def lengths(self) -> List[tuple]:
        return list(self._lengths)

    def target_of(self, idx: int) -> str:
        return self._targets[idx]

    def bucket_of(self, idx: int) -> Tuple[int, int]:
        return self._where[idx][0]

    def _bucket_mmaps(self, bucket: Tuple[int, int]) -> List[np.ndarray]:
        if bucket not in self._mmaps:
            n = self._index["buckets"][_bucket_key(bucket)]["num_leaves"]
            self._mmaps[bucket] = [
                np.load(_leaf_path(self.pack_dir, bucket, i), mmap_mode="r")
                for i in range(n)
            ]
        return self._mmaps[bucket]

    def padded_batch(self, indices: Sequence[int], bucket: Tuple[int, int]):
        """Stacked ``PairedComplex`` batch for ``indices`` (all in
        ``bucket``) — equivalent to per-item ``to_paired_complex`` +
        ``stack_complexes`` by construction of the pack."""
        import jax

        bucket = tuple(bucket)
        rows = []
        for idx in indices:
            b, row = self._where[idx]
            if b != bucket:
                raise ValueError(
                    f"item {idx} packed for bucket {b}, requested {bucket} — "
                    "loader bucket rules must match pack-time rules"
                )
            rows.append(row)
        mmaps = self._bucket_mmaps(bucket)
        leaves = [np.stack([mm[r] for r in rows]) for mm in mmaps]
        return jax.tree_util.tree_unflatten(self._td, leaves)

    def __getitem__(self, idx: int):
        raise TypeError(
            "PackedDataset items are pre-padded; iterate through "
            "BucketedLoader (padded_batch), not per-item raw dicts"
        )
