"""Bucketing batch loader: shape-stable batches for XLA.

Replaces the reference's DataLoader + ``dgl_picp_collate``
(deepinteract_utils.py:61-67). DGL concatenates variable-size graphs; XLA
wants a handful of static shapes, so complexes are grouped by their
(bucket1, bucket2) padded chain lengths (``pick_bucket`` over
``constants.CHAIN_LENGTH_BUCKETS``) and only same-bucket complexes batch
together — each distinct bucket pair compiles once, then every epoch reuses
the executable.

For data parallelism, ``batch_size`` should be a multiple of the mesh's
data-axis size; ``drop_remainder=True`` (train) keeps every step full and
shape-stable, while eval keeps remainders as smaller (still bucketed)
batches.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from deepinteract_tpu.data.graph import PairedComplex, pick_bucket, stack_complexes
from deepinteract_tpu.data.io import to_paired_complex
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import faults

logger = logging.getLogger(__name__)

_BATCHES = obs_metrics.counter(
    "di_data_batches_total", "Batches assembled by the bucketed loader")
_SKIPPED = obs_metrics.counter(
    "di_data_skipped_batches_total",
    "Batches dropped by the corrupt-complex skip budget")
_DEVICE_PREFETCHED = obs_metrics.counter(
    "di_data_device_prefetched_batches_total",
    "Batches whose h2d transfer was issued on the loader's prefetch thread")


def make_bucket_fn(pad_to_max_bucket: bool = False,
                   diagonal_buckets: bool = False):
    """(n1, n2) -> (bucket1, bucket2) under the loader's bucketing flags —
    shared by ``BucketedLoader`` planning and pack-time bucketing
    (``data.packed.pack_dataset``) so the two can never disagree."""
    def bucket_fn(n1: int, n2: int) -> Tuple[int, int]:
        if pad_to_max_bucket:
            from deepinteract_tpu import constants

            top = constants.CHAIN_LENGTH_BUCKETS[-1]
            return (max(pick_bucket(n1), top), max(pick_bucket(n2), top))
        if diagonal_buckets:
            b = max(pick_bucket(n1), pick_bucket(n2))
            return (b, b)
        return (pick_bucket(n1), pick_bucket(n2))
    return bucket_fn


class BucketedLoader:
    """Iterable of stacked ``PairedComplex`` batches.

    Conforms to the Trainer's DataSource protocol: calling the loader with
    an epoch number returns a fresh (re-shuffled) iterator; iterating the
    object directly uses epoch 0 ordering.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_remainder: bool = False,
        seed: int = 42,
        pad_to_max_bucket: bool = False,
        prefetch: int = 2,
        shard: Optional[Tuple[int, int]] = None,
        dispatch_run: int = 1,
        diagonal_buckets: bool = False,
        skip_budget: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_remainder = drop_remainder
        self.seed = seed
        self.pad_to_max_bucket = pad_to_max_bucket
        # Shuffle granularity: with dispatch_run > 1 the epoch plan keeps
        # runs of up to this many consecutive SAME-bucket batches together
        # and shuffles at run granularity instead of batch granularity.
        # The Trainer's scanned K-step dispatch only engages on runs of >=
        # K same-shape batches (training/loop.py:_shape_runs); a fully
        # interleaved shuffle makes expected run length ~#buckets/(#buckets
        # -1) and silently degrades every step to the un-amortized
        # single-dispatch path (measured: 2.5x epoch slowdown on a mixed
        # 128/256 corpus, tools/sustained_train.py r4). Deviation from the
        # reference's unconstrained shuffle, by design: complexes are still
        # shuffled within buckets and run order is shuffled every epoch.
        self.dispatch_run = max(1, dispatch_run)
        # Diagonal bucketing (VERDICT r4 item 6): pad BOTH chains to the
        # larger chain's bucket, so only (b, b) shape pairs occur. An
        # L-bucket corpus then compiles at most L shape-pair executable
        # sets instead of L^2 (measured: the r4 sustained run's first
        # epoch spent 12-22 min compiling up to 16 (bucket1, bucket2)
        # combinations x {step, scan, eval, scan-eval}), and same-shape
        # runs get longer, so more steps ride the scanned dispatch. Cost:
        # extra pad FLOPs for asymmetric pairs (the pair map grows from
        # b1 x b2 to b^2) — worth it whenever compile tax or run
        # fragmentation dominates, i.e. real mixed-length corpora.
        self.diagonal_buckets = diagonal_buckets
        # Batches ready ahead of the consumer on a background thread
        # (npz load + pad + stack overlap device compute; 0 disables).
        self.prefetch = prefetch
        # (host_index, host_count): coordinated multi-host sharding. Every
        # host plans GLOBAL batches of batch_size*host_count over the FULL
        # dataset with identical seeds, then loads only its own
        # batch_size-slice of each — so step counts and bucket shapes agree
        # across hosts by construction (a per-host split of the *file list*
        # would give hosts different bucket distributions and deadlock the
        # global collectives on the first divergent batch shape).
        self.shard = shard
        if shard is not None:
            assert 0 <= shard[0] < shard[1], shard
        # Corrupt-complex tolerance: up to this many BATCHES per epoch may
        # be skipped (logged, counted) when an item fails to load/pad,
        # instead of one bad npz killing a multi-hour epoch; over budget
        # the original error is re-raised (a corrupt *dataset* must still
        # be loud). The whole batch is dropped, not just the item — a
        # shrunken batch would change shapes and break bucketed compile
        # reuse. 0 disables (fail-fast, the previous behavior).
        # Multi-host (shard set, real multi-process runtime): every drop
        # decision is host-0-broadcast through the coordination KV store
        # (parallel/multihost.agree_any_flag), so ALL hosts skip
        # identical batches — a host-local skip would desynchronize step
        # counts and deadlock the global collectives mid-epoch.
        self.skip_budget = max(0, skip_budget)
        # Cursor ledger (--save_every_steps resume): cumulative skips
        # recorded at yield time, keyed by consumed-batch ordinal. Written
        # on the prefetch thread, read for settled ordinals only.
        self._skips_at: Dict[int, int] = {}
        # Per-produce serial for the multi-host agreement keys: the
        # coordination KV store is write-once per key, and the same epoch
        # is legitimately produced more than once (cli.train's example
        # fetch, then the real epoch) — hosts call _produce in the same
        # order, so the serial stays aligned across the mesh.
        self._agree_serial = 0
        # Optional per-batch hook: a callable applied to each assembled
        # batch ON THE PREFETCH THREAD (``_produce`` runs inside
        # ``_prefetched``'s worker when prefetch > 0) — e.g. a placement
        # fn so a transfer overlaps the consumer's device compute.
        # The Trainer no longer installs anything here: its
        # --device_prefetch placement (sharding-aware, scan-stack-aware,
        # all four dispatch modes) rides the data/pipeline.py placement
        # stage DOWNSTREAM of this queue instead, where same-shape runs
        # can be grouped before the h2d. The hook stays for external
        # consumers that want batches transformed at assembly time.
        self.device_transfer = None
        self._bucket_fn = None  # built once on first _item_bucket call
        # Bucket planning reads every header once, up front.
        self._buckets = self._plan()

    def _item_bucket(self, n1: int, n2: int) -> Tuple[int, int]:
        if self._bucket_fn is None:
            self._bucket_fn = make_bucket_fn(
                self.pad_to_max_bucket, self.diagonal_buckets)
        return self._bucket_fn(n1, n2)

    def _plan(self) -> Dict[Tuple[int, int], List[int]]:
        buckets: Dict[Tuple[int, int], List[int]] = defaultdict(list)
        # A PackedDataset fixed each item's bucket at pack time; planning
        # with its stored buckets keeps plan and pack consistent even if
        # this loader's bucket flags differ from pack-time flags.
        bucket_of = getattr(self.dataset, "bucket_of", None)
        for idx, (n1, n2) in enumerate(self.dataset.lengths()):
            key = (tuple(bucket_of(idx)) if bucket_of is not None
                   else self._item_bucket(n1, n2))
            buckets[key].append(idx)
        return dict(buckets)

    def _global_batch_size(self) -> int:
        return self.batch_size * (self.shard[1] if self.shard else 1)

    def num_batches(self) -> int:
        gb = self._global_batch_size()
        total = 0
        for indices in self._buckets.values():
            if self.drop_remainder:
                total += len(indices) // gb
            else:
                total += (len(indices) + gb - 1) // gb
        return total

    def _epoch_plan(self, epoch: int) -> List[Tuple[Tuple[int, int], List[int]]]:
        """Global plan: identical on every host (seeded shuffles only)."""
        gb = self._global_batch_size()
        plan = []
        rng = random.Random(self.seed + epoch) if self.shuffle else None
        for bucket, indices in sorted(self._buckets.items()):
            idxs = list(indices)
            if rng:
                rng.shuffle(idxs)
            for i in range(0, len(idxs), gb):
                chunk = idxs[i : i + gb]
                if len(chunk) < gb:
                    if self.drop_remainder:
                        continue
                    if self.shard:
                        # Wrap within the bucket (DistributedSampler
                        # padding) so every host's slice stays full.
                        k = 0
                        while len(chunk) < gb:
                            chunk.append(idxs[k % len(idxs)])
                            k += 1
                plan.append((bucket, chunk))
        if rng:
            if self.dispatch_run > 1:
                # Run-granular shuffle: split each bucket's (contiguous)
                # batches into runs of dispatch_run, shuffle the runs.
                runs = []
                i = 0
                while i < len(plan):
                    j = i
                    while (j < len(plan) and plan[j][0] == plan[i][0]
                           and j - i < self.dispatch_run):
                        j += 1
                    runs.append(plan[i:j])
                    i = j
                rng.shuffle(runs)
                plan = [entry for run in runs for entry in run]
            else:
                rng.shuffle(plan)  # interleave buckets across the epoch
        return plan

    def _host_slice(self, chunk: List[int]) -> List[int]:
        if self.shard is None:
            return chunk
        start = self.shard[0] * self.batch_size
        return chunk[start : start + self.batch_size]

    def _skip_agreement(self):
        """Multi-host drop coordination: None for a lone process (local
        decisions), else a callable returning the host-0-broadcast
        verdict "any host failed to load this plan entry" (parallel/
        multihost.agree_any_flag — host-side KV, prefetch-thread-safe).
        Only armed alongside a skip budget: with budget 0 a failure
        raises everywhere anyway, so batches never desync."""
        if self.shard is None or self.skip_budget <= 0:
            return None
        import jax

        from deepinteract_tpu.parallel import multihost

        if jax.process_count() <= 1:
            return None  # simulated shard in a single process (tests)
        if not multihost.can_agree():
            # A REAL mesh without the coordination client must fail loud:
            # host-local drop decisions would silently desync step counts
            # and deadlock the next collective — exactly the failure mode
            # the coordinated protocol exists to prevent.
            raise RuntimeError(
                "multi-host skip_budget needs the jax coordination "
                "client (jax.distributed.initialize ran, and this jax "
                "version exposes distributed.global_state.client); set "
                "skip_budget=0 or fix the runtime instead of risking a "
                "cross-host batch desync")
        self._agree_serial += 1
        serial = self._agree_serial

        def agree(epoch: int, plan_pos: int, local_fail: bool) -> bool:
            return multihost.agree_any_flag(
                f"di_loader_skip/{self.seed}/{serial}/{epoch}/{plan_pos}",
                local_fail)

        return agree

    def skips_before(self, batches_consumed: int) -> int:
        """Cumulative skip-budget drops before the given consumed-batch
        ordinal of the current epoch — the Trainer's resume-cursor
        ledger (training/loop.py midsave)."""
        if batches_consumed <= 0:
            return 0
        return int(self._skips_at.get(int(batches_consumed), 0))

    def _produce(self, epoch: int, with_targets: bool,
                 start_batch: int = 0, skips_used: int = 0) -> Iterator:
        padded_batch = getattr(self.dataset, "padded_batch", None)
        skips_left = max(0, self.skip_budget - max(0, skips_used))
        # Mid-epoch resume cursor: the first start_batch + skips_used
        # plan entries were already paid (yielded or dropped) before the
        # checkpoint — skip them WITHOUT loading (the plan is
        # deterministic per (seed, epoch), so position alone suffices).
        already_paid = max(0, start_batch) + max(0, skips_used)
        produced = max(0, start_batch)
        cum_skips = max(0, skips_used)
        self._skips_at = {}
        agree = self._skip_agreement()
        for plan_pos, ((b1, b2), chunk) in enumerate(self._epoch_plan(epoch)):
            if plan_pos < already_paid:
                continue
            chunk = self._host_slice(chunk)
            batch = targets = None
            local_exc: Optional[Exception] = None
            try:
                faults.maybe_raise(
                    "loader.batch",
                    lambda: ValueError("injected corrupt complex"),
                )
                if padded_batch is not None:
                    # Packed fast path (data/packed.py): mmap rows + stack
                    # — no npz decompress, no padding work.
                    batch = padded_batch(chunk, (b1, b2))
                    targets = [self.dataset.target_of(i) for i in chunk]
                else:
                    complexes, targets = [], []
                    for idx in chunk:
                        raw = self.dataset[idx]
                        complexes.append(
                            to_paired_complex(
                                raw, n_pad1=b1, n_pad2=b2,
                                input_indep=raw.get("input_indep", False),
                            )
                        )
                        targets.append(raw.get("target", str(idx)))
                    batch = stack_complexes(complexes)
            except Exception as exc:
                if skips_left <= 0 and agree is None:
                    raise
                local_exc = exc
            # The drop decision: local failure alone (single host), or
            # the host-0-broadcast any-host-failed verdict — so a mesh
            # skips IDENTICAL batches and step counts stay aligned.
            drop = (agree(epoch, plan_pos, local_exc is not None)
                    if agree is not None else local_exc is not None)
            if drop:
                if skips_left <= 0:
                    if local_exc is not None:
                        raise local_exc
                    raise RuntimeError(
                        f"a peer host failed to load batch (bucket "
                        f"{b1}x{b2}, plan entry {plan_pos}) with the "
                        "skip budget exhausted")
                skips_left -= 1
                cum_skips += 1
                _SKIPPED.inc()
                logger.warning(
                    "skipping corrupt batch (bucket %sx%s, items %s): %s "
                    "— %d skip(s) left this epoch",
                    b1, b2, chunk,
                    local_exc if local_exc is not None
                    else "peer-host load failure (coordinated drop)",
                    skips_left,
                )
                continue
            if local_exc is not None:
                # Defensive: agree() said keep but this host failed —
                # unreachable under the any-host-failed OR, but a wrong
                # verdict must raise loudly, never yield a None batch.
                raise local_exc
            _BATCHES.inc()
            if self.device_transfer is not None:
                # jax.device_put is async: issuing it here starts the h2d
                # copy on the transfer engine while the consumer is still
                # busy with the previous dispatch.
                batch = self.device_transfer(batch)
                _DEVICE_PREFETCHED.inc()
            produced += 1
            self._skips_at[produced] = cum_skips
            yield (batch, targets) if with_targets else batch

    def iter_epoch(self, epoch: int = 0, with_targets: bool = False,
                   start_batch: int = 0, skips_used: int = 0) -> Iterator:
        if self.prefetch <= 0:
            yield from self._produce(epoch, with_targets,
                                     start_batch, skips_used)
            return
        yield from _prefetched(
            self._produce(epoch, with_targets, start_batch, skips_used),
            self.prefetch)

    def targets(self) -> List[str]:
        """Target names in epoch-0 iteration order (for eval CSV export)."""
        out = []
        for _, chunk in self._epoch_plan(0):
            out.extend(self.dataset.target_of(i) for i in self._host_slice(chunk))
        return out

    def __call__(self, epoch: int) -> Iterator[PairedComplex]:
        return self.iter_epoch(epoch)

    def __iter__(self) -> Iterator[PairedComplex]:
        return self.iter_epoch(0)


def _prefetched(source: Iterator, depth: int) -> Iterator:
    """Run ``source`` on a daemon thread, keeping up to ``depth`` items
    ready. Exceptions propagate to the consumer. When the consumer abandons
    the iterator early (break / GeneratorExit — e.g. taking one batch for
    viz logging), the ``finally`` sets a stop flag the worker polls, so the
    thread exits instead of blocking forever on a full queue with pinned
    batches."""
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    done = object()
    stop = threading.Event()

    def put_guarded(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in source:
                if not put_guarded(item):
                    return
        except BaseException as exc:  # noqa: BLE001 - re-raised on consumer side
            put_guarded((done, exc))
            return
        put_guarded((done, None))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if isinstance(item, tuple) and len(item) == 2 and item[0] is done:
                if item[1] is not None:
                    raise item[1]
                return
            yield item
    finally:
        stop.set()


class InMemoryDataset:
    """Adapter giving a list of raw complex dicts the dataset protocol
    (tests, synthetic data, and single-complex prediction)."""

    def __init__(self, raws: Sequence[Dict], targets: Optional[Sequence[str]] = None):
        self.raws = list(raws)
        self._targets = list(targets) if targets else [f"complex_{i}" for i in range(len(raws))]

    def __len__(self):
        return len(self.raws)

    def __getitem__(self, idx):
        raw = dict(self.raws[idx])
        raw.setdefault("input_indep", False)
        raw["target"] = self._targets[idx]
        return raw

    def target_of(self, idx):
        return self._targets[idx]

    def lengths(self):
        return [
            (r["graph1"]["node_feats"].shape[0], r["graph2"]["node_feats"].shape[0])
            for r in self.raws
        ]
