"""Feature schema, size limits, and shared constants.

Mirrors the reference contract in
``project/utils/deepinteract_constants.py:10-116`` (limits, FEATURE_INDICES)
so converted data is bit-compatible, while adding TPU-side padding/bucketing
constants that have no reference equivalent.

Note on edge feature dimensionality: the reference stores 28 edge feature
columns (indices 0..27, with the amide angle at index 27 — see
``FEATURE_INDICES['edge_amide_angles']``) even though its dataset property
``num_edge_features`` reports 27 (`dips_dgl_dataset.py:269-271`, an
off-by-one never consumed anywhere). We make the true width explicit.
"""

# ---------------------------------------------------------------------------
# Size limits (reference: deepinteract_constants.py:10-13)
# ---------------------------------------------------------------------------
ATOM_COUNT_LIMIT = 2048
RESIDUE_COUNT_LIMIT = 256
NODE_COUNT_LIMIT = 2304
KNN = 20
GEO_NBRHD_SIZE = 2  # reference default: lit_model_predict.py:63, db5_dgl_dataset.py:70

# ---------------------------------------------------------------------------
# Node feature layout: 113 columns (reference: deepinteract_constants.py:99-116
# and convert_df_to_dgl_graph, deepinteract_utils.py:493-500)
# ---------------------------------------------------------------------------
NUM_NODE_FEATS = 113

NODE_POS_ENC = 0                    # min-max-normalized node index
NODE_GEO_FEATS = slice(1, 7)        # cos/sin of (phi, psi, omega) dihedrals
NODE_DIPS_FEATS = slice(7, 113)     # DIPS-Plus residue features, layout below

# DIPS-Plus residue feature sub-layout within columns 7..113
# (reference: FEAT_COLS/ALLOWABLE_FEATS, deepinteract_constants.py:64-96)
NODE_RESNAME_ONE_HOT = slice(7, 27)     # 20-way residue type
NODE_SS_ONE_HOT = slice(27, 35)         # 8-state DSSP secondary structure
NODE_RSA = 35                           # relative solvent accessibility
NODE_RD = 36                            # residue depth (MSMS)
NODE_PROTRUSION = slice(37, 43)         # 6 PSAIA protrusion-index stats
NODE_HSAAC = slice(43, 85)              # 42-dim half-sphere AA composition
NODE_CN = 85                            # coordination number
NODE_SEQUENCE_FEATS = slice(86, 113)    # 27 profile-HMM emission/transition probs

# ---------------------------------------------------------------------------
# Edge feature layout: 28 columns (reference: deepinteract_utils.py:503-531)
# ---------------------------------------------------------------------------
NUM_EDGE_FEATS = 28

EDGE_POS_ENC = 0                    # sin(src_idx - dst_idx)
EDGE_WEIGHT = 1                     # min-max-normalized squared CA-CA distance
EDGE_DIST_FEATS = slice(2, 20)      # 18 RBF bins over squared distances
EDGE_DIR_FEATS = slice(20, 23)      # unit direction to neighbor in local frame
EDGE_ORIENT_FEATS = slice(23, 27)   # relative-rotation quaternion
EDGE_AMIDE_ANGLE = 27               # min-max-normalized amide-plane angle

NUM_RBF = 18
NUM_DIST_FEATS = 18
NUM_DIR_FEATS = 3
NUM_ORIENT_FEATS = 4
NUM_AMIDE_FEATS = 1

# Number of raw "edge message" channels fed to the edge initializer
# (pos enc + edge weight; reference: deepinteract_modules.py:1354-1356).
NUM_EDGE_MESSAGE_FEATS = 2

NUM_CLASSES = 2

# ---------------------------------------------------------------------------
# Feature-generation constants shared with the data pipeline
# (reference: deepinteract_constants.py:37-62)
# ---------------------------------------------------------------------------
PSAIA_COLUMNS = ["avg_cx", "s_avg_cx", "s_ch_avg_cx", "s_ch_s_avg_cx", "max_cx", "min_cx"]
AMINO_ACIDS = "ACDEFGHIKLMNPQRSTVWY-"
HSAAC_DIM = 42
NUM_ALLOWABLE_NANS = 5
NUM_SEQUENCE_FEATS = 27  # 20 emission + 7 transition profile-HMM probabilities

ALLOWABLE_RESNAMES = [
    "TRP", "PHE", "LYS", "PRO", "ASP", "ALA", "ARG", "CYS", "VAL", "THR",
    "GLY", "SER", "HIS", "LEU", "GLU", "TYR", "ILE", "ASN", "MET", "GLN",
]
ALLOWABLE_SS = ["H", "B", "E", "G", "I", "T", "S", "-"]

D3TO1 = {
    "CYS": "C", "ASP": "D", "SER": "S", "GLN": "Q", "LYS": "K",
    "ILE": "I", "PRO": "P", "THR": "T", "PHE": "F", "ASN": "N",
    "GLY": "G", "HIS": "H", "LEU": "L", "ARG": "R", "TRP": "W",
    "ALA": "A", "VAL": "V", "GLU": "E", "TYR": "Y", "MET": "M",
}

# ---------------------------------------------------------------------------
# TPU-side padding buckets (no reference equivalent; XLA needs static shapes).
# Chains are padded up to the smallest bucket that fits; each bucket compiles
# once. 256 matches RESIDUE_COUNT_LIMIT, the reference's training regime.
# ---------------------------------------------------------------------------
CHAIN_LENGTH_BUCKETS = (64, 128, 192, 256)
PAIR_MAP_TILE = 256  # tile edge for the blockwise long-context decoder
