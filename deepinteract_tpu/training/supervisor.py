"""Self-healing training: supervise, watch, and restart the train loop.

Serving got supervision in PR 13 (``serving/fleet.py``); training — the
workload that runs for days — still died like a script: a crash re-paid
the epoch only when a human reran it, and a wedged collective hung
forever behind a fresh-looking process. This module brings the fleet
discipline to ``cli/train.py``:

* **spawn** — the training command line runs as a child process with
  ``--heartbeat_seconds`` forced on (argparse last-occurrence-wins, the
  fleet pattern), so liveness is observable from the first poll;
* **watch** — a poll loop checks process liveness and the child's
  heartbeat through the ONE shared staleness check
  (:func:`deepinteract_tpu.obs.heartbeat.read_heartbeat` — the same
  helper the fleet supervisor and ``cli/fsck.py`` use). The beat thread
  is a daemon that keeps the file fresh even when the step loop is
  stuck, so the HANG signal is ``last_progress_ts`` staleness
  (``hang_timeout_s``), not file age: a live child whose progress stamp
  stopped advancing past the per-spawn ``start_grace_s`` (import +
  restore + compile make no step progress) is a wedged collective and
  gets SIGKILLed into the normal restart path. A child whose heartbeat
  FILE goes stale (beat thread died) or never appears past the grace is
  treated the same;
* **restart** — a crashed or killed child respawns with PR-1 jittered
  exponential backoff into ``--resume`` (exact mid-epoch resume when the
  run used ``--save_every_steps``, epoch-boundary otherwise). The
  injected fault plan (``DI_FAULTS``) is stripped from restarted
  children: a plan describes one incarnation's faults, and replaying it
  would re-kill every resume at the same call count;
* **circuit-break** — more than ``circuit_max_restarts`` restarts inside
  ``circuit_window_s`` opens the breaker: a poisoned run (bad flag,
  corrupt shard, diverged optimization) must not crash-loop forever.
  The supervisor stops, reports ``circuit_open`` and exits nonzero;
* **exit honestly** — child exit 0 (finished, or cleanly preempted by a
  forwarded SIGTERM) is supervisor exit 0; a circuit-open or
  unstartable child is nonzero. ``cli/train.py`` prints
  :meth:`TrainingSupervisor.contract` — the machine-readable
  ``train_supervise/v1`` record (tools/check_cli_contract.py) — as the
  FINAL stdout line.

Every transition persists atomically to
``<state_dir>/train_supervisor_state.json`` via
``robustness/artifacts.atomic_write`` — an operator (or ``cli/fsck.py``)
reading mid-crash never sees torn JSON, and the chaos tests find the
child pid there.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs.heartbeat import HeartbeatStatus, read_heartbeat
from deepinteract_tpu.robustness import artifacts
from deepinteract_tpu.robustness.retry import compute_delay

logger = logging.getLogger(__name__)

_RESTARTS = obs_metrics.counter(
    "di_train_supervisor_restarts_total",
    "Training children respawned by the supervisor", labelnames=("cause",))
_HANG_KILLS = obs_metrics.counter(
    "di_train_supervisor_hang_kills_total",
    "Live-but-hung training children (stale heartbeat progress) "
    "SIGKILLed for restart")
_CIRCUIT_OPEN = obs_metrics.gauge(
    "di_train_supervisor_circuit_open",
    "1 while the training restart circuit breaker is open")

STATE_BASENAME = "train_supervisor_state.json"

# Supervisor-only flags (cli/args.py "self-healing supervision" group):
# stripped from the child command line — the child is a plain cli.train.
# (flag, takes_value).
SUPERVISOR_FLAGS = (
    ("--supervise", False),
    ("--watch_interval_s", True),
    ("--hang_timeout_s", True),
    ("--start_grace_s", True),
    ("--train_restart_backoff_s", True),
    ("--train_circuit_max_restarts", True),
    ("--train_circuit_window_s", True),
)

# Child command factory: (resume, attempt) -> argv. cli/train.py builds
# the real one; tests inject stubs (the fleet cmd_fn pattern).
CmdFn = Callable[[bool, int], List[str]]


def strip_supervisor_flags(argv: List[str]) -> List[str]:
    """The child's argv: the operator's command line minus the
    supervisor-only knobs (the child must not recurse into supervisor
    mode, and cli.train does not know the watch flags)."""
    flags = dict(SUPERVISOR_FLAGS)
    out: List[str] = []
    skip_value = False
    for tok in argv:
        if skip_value:
            skip_value = False
            continue
        name, eq, _val = tok.partition("=")
        if name in flags:
            skip_value = flags[name] and not eq
            continue
        out.append(tok)
    return out


def train_child_cmd_fn(child_argv: List[str],
                       heartbeat_seconds: float) -> CmdFn:
    """The real cli.train child factory: the stripped operator argv with
    ``--heartbeat_seconds`` forced on (argparse last-occurrence-wins — a
    supervised child without a beat would be unwatchable) and
    ``--resume`` appended on every restart so the child lands on the
    newest checkpoint/cursor."""

    def cmd_fn(resume: bool, attempt: int) -> List[str]:
        cmd = [sys.executable, "-m", "deepinteract_tpu.cli.train"]
        cmd += list(child_argv)
        cmd += ["--heartbeat_seconds", str(heartbeat_seconds)]
        if resume:
            cmd += ["--resume"]
        return cmd

    return cmd_fn


@dataclasses.dataclass(frozen=True)
class SuperviseConfig:
    """Watchdog policy (CLI surface: cli/args.py self-healing group)."""

    heartbeat_path: str
    state_dir: str
    # Forced onto the child (train_child_cmd_fn).
    heartbeat_seconds: float = 5.0
    poll_interval_s: float = 1.0
    # Heartbeat FILE staleness bound (beat thread died / host FS gone).
    # <= 0: derived as 6x heartbeat_seconds.
    heartbeat_max_age_s: float = 0.0
    # Progress staleness bound — the wedged-collective detector. The
    # beat file stays fresh while the step loop is stuck, so the hang
    # signal is last_progress_ts (training/loop.py ticks it on train
    # steps, eval dispatches, and checkpoint boundaries).
    hang_timeout_s: float = 600.0
    # Per-(re)spawn grace before hang/no-heartbeat verdicts: import +
    # checkpoint restore + XLA compile legitimately make no progress.
    start_grace_s: float = 900.0
    restart_backoff_s: float = 1.0
    restart_backoff_max_s: float = 60.0
    circuit_max_restarts: int = 5
    circuit_window_s: float = 3600.0
    # Restarted children spawn WITHOUT the DI_FAULTS plan: a fault plan
    # describes one incarnation; replaying it would re-kill every resume
    # at the same call count (chaos tests rely on this to converge).
    clear_fault_plan_on_restart: bool = True
    # SIGTERM-forward drain grace before the SIGKILL fallback.
    drain_timeout_s: float = 120.0


class TrainingSupervisor:
    """Run one training child under watchdog supervision (module
    docstring). Single-threaded by design: one child, one poll loop —
    the fleet's monitor-thread machinery would buy nothing here."""

    def __init__(self, cmd_fn: CmdFn, cfg: SuperviseConfig,
                 env: Optional[Dict[str, str]] = None,
                 log: Callable[[str], None] = None):
        self._cmd_fn = cmd_fn
        self.cfg = cfg
        self._env = dict(env if env is not None else os.environ)
        # cli/train.py passes print (the operator console); the default
        # keeps library use print-free (no-print rule).
        self._log = log or logger.warning
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.hang_kills = 0
        self.crashes = 0
        self.spawns = 0
        self.circuit_open = False
        self.preempted = False
        self.child_exit_code: Optional[int] = None
        self.state = "idle"
        self._restart_times: deque = deque()
        self._backoff_attempt = 0
        self._spawned_at = 0.0
        self._stopping = False
        os.makedirs(cfg.state_dir, exist_ok=True)
        self.state_path = os.path.join(os.path.abspath(cfg.state_dir),
                                       STATE_BASENAME)

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self, resume: bool) -> bool:
        cmd = self._cmd_fn(resume, self.spawns)
        env = dict(self._env)
        if resume and self.cfg.clear_fault_plan_on_restart:
            env.pop("DI_FAULTS", None)
        # The previous incarnation's heartbeat must not outlive it: a
        # leftover fresh-looking file would mask a child that hung
        # before its first beat (the fleet discipline).
        try:
            os.unlink(self.cfg.heartbeat_path)
        except OSError:
            pass
        try:
            # stdout/stderr are INHERITED: the training log is the
            # operator's console either way, and the supervisor's final
            # contract line prints after the child exited.
            self.proc = subprocess.Popen(cmd, env=env)
        except OSError as exc:
            self._log(f"train-supervisor: spawning the child failed: {exc}")
            self.proc = None
            return False
        self.spawns += 1
        self._spawned_at = time.monotonic()
        self.state = "running"
        self._persist()
        return True

    def _hb_max_age(self) -> float:
        if self.cfg.heartbeat_max_age_s > 0:
            return self.cfg.heartbeat_max_age_s
        return 6.0 * max(0.1, self.cfg.heartbeat_seconds)

    def _child_heartbeat(self) -> HeartbeatStatus:
        """The beat OUR child wrote. The configured path is the expected
        file, but on auto-detected multi-host topologies the child's
        process index (and so its ``heartbeat_p<i>.json`` name) is only
        knowable after jax initializes IN the child — so when the
        configured file was not written by our child (the beat payload
        carries ``host: "hostname:pid"``), the sibling heartbeat files
        next to it are scanned for the one whose writer IS the child
        pid. A stale file left by a previous incarnation (old pid) can
        therefore never be mistaken for the live child's beat, and a
        healthy child on host N>0 is never judged by host 0's file."""
        max_age = self._hb_max_age()
        pid_tag = (f":{self.proc.pid}" if self.proc is not None else None)

        def written_by_child(status: HeartbeatStatus) -> bool:
            host = (status.payload or {}).get("host")
            return (pid_tag is not None and isinstance(host, str)
                    and host.endswith(pid_tag))

        primary = read_heartbeat(self.cfg.heartbeat_path, max_age)
        if primary.status == "missing" or written_by_child(primary):
            return primary
        hb_dir = os.path.dirname(self.cfg.heartbeat_path) or "."
        try:
            names = sorted(os.listdir(hb_dir))
        except OSError:
            names = []
        for name in names:
            if not (name.startswith("heartbeat")
                    and name.endswith(".json")):
                continue
            status = read_heartbeat(os.path.join(hb_dir, name), max_age)
            if written_by_child(status):
                return status
        if primary.payload is not None and "host" not in primary.payload:
            # Pid-less beats (foreign writers, minimal tests): the
            # configured path is the best available signal.
            return primary
        # Nothing our child wrote yet: indistinguishable from a child
        # that has not started beating — the start grace covers it.
        return HeartbeatStatus("missing", None, None)

    def _watch_alive(self) -> None:
        """One liveness verdict for a live child; SIGKILLs a wedged one
        (the crash path then restarts it). The per-spawn start grace
        covers the no-progress-yet startup window (import + restore +
        compile); once the beat carries a step/epoch field the child has
        demonstrably trained, and the hang verdict applies immediately —
        a mid-epoch wedge must not hide behind a generous grace."""
        since_spawn = time.monotonic() - self._spawned_at
        in_grace = since_spawn <= self.cfg.start_grace_s
        hb = self._child_heartbeat()
        reason = None
        if hb.status == "missing":
            if in_grace:
                return
            reason = (f"no heartbeat {since_spawn:.0f}s after spawn "
                      "(hung before the beat thread started)")
        elif hb.status == "stale":
            if in_grace:
                return
            reason = (f"heartbeat file stale ({hb.age_s:.0f}s old): beat "
                      "thread dead while the process lives")
        elif self.cfg.hang_timeout_s > 0 and hb.payload is not None:
            started = isinstance(hb.payload.get("step"), int) \
                or isinstance(hb.payload.get("epoch"), int)
            last = hb.payload.get("last_progress_ts")
            if (started or not in_grace) and isinstance(last, (int, float)):
                idle = time.time() - float(last)
                if idle > self.cfg.hang_timeout_s:
                    reason = (f"no step/eval/checkpoint progress for "
                              f"{idle:.0f}s (> hang_timeout_s="
                              f"{self.cfg.hang_timeout_s:.0f}) — wedged "
                              "collective signature")
        if reason is None:
            return
        self.hang_kills += 1
        _HANG_KILLS.inc()
        self.state = "hang_killing"
        self._log(f"train-supervisor: child pid {self.proc.pid} is live "
                  f"but wedged ({reason}) — SIGKILL for restart")
        self._persist()
        try:
            self.proc.kill()
        except OSError:
            pass

    def _schedule_restart(self, rc: Optional[int], cause: str) -> bool:
        """Circuit bookkeeping + backoff sleep; False = breaker open."""
        now = time.monotonic()
        self._restart_times.append(now)
        while (self._restart_times
               and now - self._restart_times[0] > self.cfg.circuit_window_s):
            self._restart_times.popleft()
        if len(self._restart_times) >= self.cfg.circuit_max_restarts:
            self.circuit_open = True
            _CIRCUIT_OPEN.set(1.0)
            self.state = "circuit_open"
            self._log(
                f"train-supervisor: {len(self._restart_times)} restarts "
                f"inside {self.cfg.circuit_window_s:.0f}s — circuit OPEN, "
                "not restarting (inspect the run, then rerun --supervise)")
            self._persist()
            return False
        delay = compute_delay(self._backoff_attempt,
                              self.cfg.restart_backoff_s,
                              self.cfg.restart_backoff_max_s)
        self._backoff_attempt += 1
        self.restarts += 1
        _RESTARTS.inc(cause=cause)
        self.state = "backoff"
        self._log(f"train-supervisor: child exited rc={rc} ({cause}); "
                  f"restarting into --resume in {delay:.1f}s "
                  f"(restart #{self.restarts})")
        self._persist()
        time.sleep(delay)
        return True

    def run(self) -> int:
        """Supervise until the child finishes cleanly, the circuit opens,
        or a forwarded SIGTERM/SIGINT drains it. Returns the honest exit
        code (preempted/finished = 0); the CLI front end prints
        :meth:`contract` as the FINAL stdout line afterwards."""
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except (ValueError, OSError):  # non-main thread (tests)
                pass
        rc: Optional[int] = None
        try:
            if not self._spawn(resume=self._initial_resume()):
                # An unspawnable command is a crash on attempt 0: give
                # the restart path (and its circuit) the decision.
                if not self._schedule_restart(None, "spawn_failure"):
                    return self._finish(2)
            while True:
                if self.proc is None:
                    if self._stopping:
                        # Preemption landed during a restart backoff (no
                        # child alive): spawning a fresh child now would
                        # ignore the drain and train past the preemption
                        # deadline. Exit 0 preempted with nothing to
                        # drain; the scheduler reruns --supervise later.
                        self.state = "preempted"
                        self._persist()
                        return self._finish(0)
                    if not self._spawn(resume=True):
                        if not self._schedule_restart(None, "spawn_failure"):
                            return self._finish(2)
                        continue
                rc = self.proc.poll()
                if rc is None:
                    if not self._stopping:
                        self._watch_alive()
                    time.sleep(self.cfg.poll_interval_s)
                    continue
                self.child_exit_code = rc
                if rc == 0:
                    self.state = "finished"
                    self.preempted = self.preempted or self._stopping
                    self._persist()
                    return self._finish(0)
                if self._stopping:
                    # The drain raced a crash; honest nonzero.
                    self.state = "crashed"
                    self._persist()
                    return self._finish(rc)
                was_hang = self.state == "hang_killing"
                if not was_hang:
                    self.crashes += 1
                cause = "hang" if was_hang else "crash"
                if not self._schedule_restart(rc, cause):
                    return self._finish(3)
                self.proc = None
        finally:
            for sig, handler in prev_handlers.items():
                try:
                    signal.signal(sig, handler)
                except (ValueError, OSError):
                    pass

    def _initial_resume(self) -> bool:
        # The first spawn honors the operator's own --resume (already in
        # the child argv); cmd_fn(resume=False) must not append another.
        return False

    def _on_signal(self, signum, frame) -> None:
        """Preemption: forward SIGTERM to the child (its PR-1 guard
        drains the checkpoint and exits 0) and stop supervising. The
        poll loop sees the clean exit; a child ignoring the signal past
        drain_timeout_s is SIGKILLed by _finish's safety net."""
        self._stopping = True
        self.preempted = True
        self.state = "draining"
        self._log(f"train-supervisor: signal {signum} — forwarding "
                  "SIGTERM to the child (preemption drain)")
        self._persist()
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass

    def _finish(self, code: int) -> int:
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.wait(timeout=self.cfg.drain_timeout_s)
            except subprocess.TimeoutExpired:
                self._log("train-supervisor: child ignored the drain — "
                          "SIGKILL")
                try:
                    self.proc.kill()
                    self.proc.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
            if self.child_exit_code is None:
                self.child_exit_code = self.proc.poll()
        self._persist()
        return code

    # -- reporting ---------------------------------------------------------

    def contract(self) -> Dict[str, Any]:
        """The ``train_supervise/v1`` record (kind registered in
        tools/check_cli_contract.py). ``ok`` means the run ended the way
        an unsupervised healthy run would have: child exit 0 and no open
        circuit — restarts along the way do not tarnish it (recovering
        is the point), but they are all counted here."""
        ok = self.child_exit_code == 0 and not self.circuit_open
        return {
            "schema": "train_supervise/v1",
            "metric": "train_supervised_restarts",
            "value": float(self.restarts),
            "unit": "restarts",
            "ok": bool(ok),
            "restarts": int(self.restarts),
            "hang_kills": int(self.hang_kills),
            "crashes": int(self.crashes),
            "spawns": int(self.spawns),
            "circuit_open": bool(self.circuit_open),
            "preempted": bool(self.preempted),
            "child_exit_code": self.child_exit_code,
            "state": self.state,
            "state_path": self.state_path,
            "heartbeat_path": self.cfg.heartbeat_path,
        }

    def _persist(self) -> None:
        state = {
            "updated_ts": time.time(),
            "state": self.state,
            "child_pid": (self.proc.pid if self.proc is not None
                          else None),
            "spawns": self.spawns,
            "restarts": self.restarts,
            "hang_kills": self.hang_kills,
            "crashes": self.crashes,
            "circuit_open": self.circuit_open,
            "preempted": self.preempted,
            "child_exit_code": self.child_exit_code,
            "heartbeat_path": self.cfg.heartbeat_path,
        }
        try:
            artifacts.atomic_write(self.state_path,
                                   json.dumps(state, sort_keys=True),
                                   fsync=False)
        except OSError as exc:
            # A full disk must not take down supervision itself.
            logger.error("train-supervisor: persisting %s failed: %s",
                         self.state_path, exc)
