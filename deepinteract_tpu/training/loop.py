"""The training loop: epochs, validation, early stopping, checkpoints.

Replaces the reference's PyTorch Lightning ``Trainer`` configuration
(``lit_model_train.py:139-183``) with a compact functional loop:

* EarlyStopping on the tracked metric, patience 5, min_delta 5e-6, mode
  'min' iff the name contains 'ce' (``lit_model_train.py:140-143``,
  ``deepinteract_utils.py:1075,1094-1096``).
* Orbax checkpoints: top-3 by tracked metric + last (:144-151).
* Per-epoch validation producing the reference's metric suite with median
  aggregation (``deepinteract_modules.py:1915-2016``).
* Fine-tune mode: restore params from a checkpoint and freeze the
  interaction decoder (``deepinteract_modules.py:1546-1557``).
* Optional mesh: the same loop drives a GSPMD-sharded step (data-parallel
  over complexes) — the DDP equivalent, SURVEY.md §2.6.

Data protocol: ``train_data``/``val_data`` are callables ``epoch ->
iterable[PairedComplex]`` (reshuffle per epoch) or plain sequences. Every
batch must already be padded/bucketed (see ``data.loader``).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from deepinteract_tpu.data.graph import PairedComplex
from deepinteract_tpu.models.model import DeepInteract
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs import spans as obs_spans
from deepinteract_tpu.parallel.multihost import (
    assert_same_across_hosts,
    host_local_array,
    is_primary_host,
)
from deepinteract_tpu.robustness import artifacts, faults
from deepinteract_tpu.robustness.guards import (
    NonFiniteTrainingError,
    dump_diagnostics,
    summarize_batch,
)
from deepinteract_tpu.robustness.preemption import PreemptionGuard, TrainingPreempted
from deepinteract_tpu.training import metrics as M
from deepinteract_tpu.training.checkpoint import (
    Checkpointer,
    CheckpointConfig,
    decode_position,
    metric_mode,
)
from deepinteract_tpu.training.optim import OptimConfig
from deepinteract_tpu.training.steps import TrainState, create_train_state, eval_step, train_step

DataSource = Union[Sequence[PairedComplex], Callable[[int], Iterable[PairedComplex]]]

# Host-side training telemetry (obs/metrics.py): recorded from the metric
# fetch path, never inside a jitted function — the trace-count and
# scan-parity tests pin that no new device syncs ride along.
_STEPS_TOTAL = obs_metrics.counter(
    "di_train_steps_total", "Train steps whose metrics reached the host")
_SKIPPED_TOTAL = obs_metrics.counter(
    "di_train_skipped_steps_total",
    "Optimizer updates skipped by the non-finite guard")
_NONFINITE_ABORTS = obs_metrics.counter(
    "di_train_nonfinite_aborts_total",
    "Runs aborted after max_bad_steps consecutive non-finite steps")
_EPOCHS_TOTAL = obs_metrics.counter(
    "di_train_epochs_total", "Completed training epochs")


@dataclasses.dataclass(frozen=True)
class LoopConfig:
    num_epochs: int = 50  # reference --num_epochs default (deepinteract_utils.py:1093)
    metric_to_track: str = "val_ce"  # (deepinteract_utils.py:1094-1096)
    patience: int = 5  # EarlyStopping patience (lit_model_train.py:140-143)
    min_delta: float = 5e-6
    ckpt_dir: Optional[str] = None
    save_top_k: int = 3
    seed: int = 42  # pl.seed_everything(42) analog (deepinteract_utils.py:1118-1122)
    weight_classes: bool = False
    pos_prob_threshold: float = 0.5
    log_every: int = 100
    max_time_seconds: Optional[float] = None  # --max_hours/--max_minutes analog
    # Stochastic weight averaging (reference --stochastic_weight_avg ->
    # Lightning StochasticWeightAveraging, lit_model_train.py:157-159):
    # average params over epochs from swa_epoch_start on; the averaged
    # weights replace the trained ones when the loop ends.
    swa: bool = False
    swa_epoch_start: float = 0.8
    # Log predicted vs true contact-map images to the metric writer every N
    # epochs (0 = off) — the reference's viz branch
    # (deepinteract_modules.py:1808-1884, images at :1850-1881).
    viz_every_n_epochs: int = 0
    # Scan this many train steps per device dispatch (lax.scan). Host
    # dispatch cost scales with result-buffer count (~25 ms for the full
    # state tree through the TPU tunnel), so amortizing it K-fold is the
    # single biggest single-chip train-throughput lever. 1 = classic
    # step-per-dispatch. Consecutive same-shape batches are grouped; odd
    # remainders fall back to single steps.
    steps_per_dispatch: int = 1
    # Same amortization for evaluation: scan K eval forwards per dispatch
    # (consecutive same-shape val batches). At batch 1 the host round-trip
    # dominates a DIPS-scale val epoch (3,548 complexes); 1 disables.
    eval_batches_per_dispatch: int = 8
    # Non-finite step guard (robustness/guards.py): steps whose loss or
    # gradients are not finite skip the optimizer update on device
    # (lax.cond, no host sync) instead of poisoning the weights; the
    # consecutive-skip counter rides the TrainState and the step metrics.
    # Finite steps compute identical math, so this is safe to leave on.
    nonfinite_guard: bool = True
    # Abort the run (NonFiniteTrainingError + diagnostic dump) once this
    # many CONSECUTIVE steps were skipped — a sustained stream of bad
    # steps means diverged optimization or a corrupt shard, not noise.
    max_bad_steps: int = 10
    # Install SIGTERM/SIGINT handlers around fit (robustness/
    # preemption.py): on preemption the loop stops at the next dispatch
    # boundary, drains the last/ checkpoint, and raises
    # TrainingPreempted; rerunning with resume=True reproduces the
    # uninterrupted run (epoch-boundary checkpoint granularity).
    preemption_guard: bool = True
    # Where non-finite abort diagnostics are written (None: ckpt_dir,
    # falling back to the working directory).
    diagnostics_dir: Optional[str] = None
    # Intra-epoch checkpoint cadence (0 disables): every N optimizer
    # steps the state is saved to the checkpoint's mid/ root with the
    # exact resume position encoded in the step number, and the loader
    # cursor (loss ledger, skip-budget ledger) rides the
    # trainer_state.json sidecar — so a crash / kill -9 / watchdog
    # SIGKILL mid-epoch re-pays at most N steps on --resume instead of
    # the whole epoch (exact-parity-tested). Saves are synchronous and
    # happen at dispatch boundaries; multi-host runs save on host 0 and
    # broadcast the resume decision like every other checkpoint read.
    save_every_steps: int = 0
    # Overlap the per-epoch checkpoint save with the next epoch's
    # training: the state is snapshotted on-device (one HBM copy, safe
    # under donated mesh steps) and a single worker thread fetches + runs
    # the orbax save while training continues. Through a remote-dispatch
    # transport the fetch alone measured 15-24 s/epoch (91 s before the
    # packed fetch) — 10-43% of a steady sustained epoch. False restores
    # the synchronous save (saves are always drained before fit returns
    # either way). If the snapshot's transient second params+opt_state
    # copy exhausts device memory, the loop logs a downgrade and falls
    # back to synchronous saves instead of failing the run.
    async_checkpoint: bool = True
    # -- telemetry (obs/) --------------------------------------------------
    # Write phase-span events (epoch -> step -> {data_wait, h2d,
    # device_step} plus checkpoint/eval) to <ckpt_dir>/obs/events.jsonl.
    # Only engages when a run dir exists (ckpt_dir set, primary host) and
    # no sink was configured explicitly; the span machinery itself is
    # always on (it feeds the step-time decomposition) and costs two
    # perf_counter calls per phase.
    span_log: bool = True
    # Write a liveness heartbeat JSON (<ckpt_dir>/obs/heartbeat.json, host
    # id + current span path + last-progress step/timestamp) every this
    # many seconds; 0 disables. The multi-host "which host is stuck, and
    # where" primitive — each host writes its own file.
    heartbeat_seconds: float = 0.0
    # Capture a jax.profiler trace of train dispatches [1, 1+profile_steps)
    # of the first epoch into profile_dir (dispatch 0 is skipped: it is
    # dominated by compile). Spans emit TraceAnnotation/
    # StepTraceAnnotation while the capture runs, so the trace comes out
    # phase-labeled. None disables.
    profile_dir: Optional[str] = None
    profile_steps: int = 3
    # -- input pipeline ----------------------------------------------------
    # Run batch placement double-buffered on the input pipeline's
    # placement thread (data/pipeline.py): the sharding-aware h2d — and,
    # for steps_per_dispatch > 1, the np.stack + pack of the [K, B, ...]
    # scan-stack — overlaps the previous dispatch's device_step instead
    # of serializing before each dispatch. Engages in ALL four dispatch
    # modes (single/mesh × per-step/scanned): mesh batches land
    # pre-sharded via the same NamedSharding constructors the sharded
    # steps use for in_shardings (multi-host: each host places only its
    # local shard), and at most the loader's `prefetch` depth of
    # dispatches is pinned in device memory. Numerically a no-op
    # (parity-tested bit-equal against the inline path); tele_h2d then
    # counts overlapped placement-thread seconds, tele_data_wait the
    # residual critical-path stall. Off by default.
    device_prefetch: bool = False
    # -- autotuning (tuning/) ---------------------------------------------
    # With autotune on and a store path set, the Trainer resolves the
    # tuned scan_k (steps_per_dispatch) for tuning_bucket = (batch, pad)
    # at startup and logs the full adopted tuple. Model-side knobs (remat,
    # scan_chunks, Pallas blocks) must be applied BEFORE the model is
    # constructed — cli/train.py does that through the same
    # tuning.consume resolution, so the two can never disagree.
    autotune: bool = False
    tuning_store: Optional[str] = None
    tuning_bucket: Optional[tuple] = None  # (batch, pad)


class EarlyStopping:
    """Reference semantics: stop after ``patience`` consecutive epochs
    without at least ``min_delta`` improvement."""

    def __init__(self, mode: str, patience: int, min_delta: float):
        self.mode = mode
        self.patience = patience
        self.min_delta = min_delta
        self.best = math.inf if mode == "min" else -math.inf
        self.stale_epochs = 0

    def update(self, value: float) -> bool:
        """Returns True if training should stop (Lightning: stop once
        ``wait_count >= patience``).

        Non-finite metrics are explicit, not incidental: NaN *and* ±inf
        count against patience and never improve ``best`` — without the
        guard a -inf val_ce (mode 'min') would latch as an unbeatable
        best and disable early stopping for the rest of the run."""
        if not math.isfinite(value):
            self.stale_epochs += 1
            return self.stale_epochs >= self.patience
        improved = (
            value < self.best - self.min_delta
            if self.mode == "min"
            else value > self.best + self.min_delta
        )
        if improved:
            self.best = value
            self.stale_epochs = 0
        else:
            self.stale_epochs += 1
        return self.stale_epochs >= self.patience


def _iter_data(data: DataSource, epoch: int) -> Iterable[PairedComplex]:
    return data(epoch) if callable(data) else data


def _shape_runs(items: Iterable[PairedComplex], k: int):
    """Group consecutive same-shape batches into runs of up to ``k`` for
    scanned dispatch (shape key = tuple of pytree leaf shapes). Runs
    shorter than ``k`` (remainders, shape changes, or ``k == 1``) are
    dispatched per-batch by the callers — a fresh odd-length scan would
    compile minutes to run once."""
    buffer: List[PairedComplex] = []
    buffer_key = None
    for item in items:
        key = tuple(
            getattr(l, "shape", ()) for l in jax.tree_util.tree_leaves(item)
        )
        if buffer and key != buffer_key:
            yield buffer
            buffer = []
        buffer_key = key
        buffer.append(item)
        if len(buffer) == k:
            yield buffer
            buffer = []
    if buffer:
        yield buffer


class Trainer:
    """Drives train/val epochs over jitted steps.

    With ``mesh`` set, steps run GSPMD-sharded (state replicated, batch
    split over the data axis); otherwise plain ``jax.jit`` on the default
    device. The jit cache keys on batch shapes, so bucketed loaders reuse
    a handful of compiled executables.
    """

    def __init__(
        self,
        model: DeepInteract,
        loop_cfg: LoopConfig = LoopConfig(),
        optim_cfg: Optional[OptimConfig] = None,
        mesh=None,
        log_fn: Callable[[str], None] = print,
        metric_writer=None,
    ):
        self.model = model
        self.cfg = loop_cfg
        self.optim_cfg = optim_cfg or OptimConfig()
        self.mesh = mesh
        self.log = log_fn
        self.metric_writer = metric_writer
        # Autotune resolution at startup (tuning/consume.py): the tuned
        # scan_k replaces steps_per_dispatch before the step functions
        # below are built, and the FULL adopted tuple is logged — the
        # model-side knobs were applied by the caller through the same
        # resolution path, so the log line describes the whole config.
        self.adopted_tuning = None
        if loop_cfg.autotune and loop_cfg.tuning_store and loop_cfg.tuning_bucket:
            from deepinteract_tpu.tuning import consume
            from deepinteract_tpu.tuning.space import bucket_key

            batch, pad = loop_cfg.tuning_bucket
            adopted = consume.lookup_path(
                loop_cfg.tuning_store, model.cfg, batch, pad)
            if adopted is not None:
                self.cfg = loop_cfg = consume.adopt_loop_config(
                    loop_cfg, adopted)
                self.adopted_tuning = adopted
                self.log(
                    f"autotune: adopted ({adopted.summary()}) for bucket "
                    f"{bucket_key(batch, pad)} from {loop_cfg.tuning_store}")
            else:
                self.log(
                    f"autotune: no tuning-store entry for bucket "
                    f"{bucket_key(batch, pad)} in {loop_cfg.tuning_store}; "
                    "keeping default configs")
        # Epoch scalars route through a fan-out writer so the telemetry
        # registry always mirrors whatever external sink (wandb/TB) is
        # configured — identical call sequence for that sink either way.
        from deepinteract_tpu.training.wandb_logger import (
            FanoutWriter,
            RegistryWriter,
        )

        self._scalar_writer = FanoutWriter([metric_writer, RegistryWriter()])
        self._heartbeat = None
        # --profile_dir state: capture profile_steps dispatches starting at
        # the run's SECOND train dispatch (the first is compile-dominated).
        # The dispatch counter is run-global, not per-epoch, so one-
        # dispatch-per-epoch runs still open the window at epoch 1.
        self._profile_active = False
        self._profile_started = False
        self._profile_done = loop_cfg.profile_dir is None
        self._profile_remaining = 0
        self._dispatch_count = 0
        # Active PreemptionGuard while fit() runs (robustness/preemption
        # .py); _run_train_epoch and evaluate poll it at dispatch
        # boundaries. None outside fit or when preemption_guard is off.
        self._preempt: Optional[PreemptionGuard] = None
        # Input-pipeline placement stage (data/pipeline.py), configured
        # per-fit by _install_device_prefetch: the inline placement, the
        # transfer-eager one for the prefetch thread, and the bound on
        # pinned dispatches (0 = prefetch off, placement inline).
        self._placement = None
        self._prefetch_placement = None
        self._prefetch_depth = 0
        guard = loop_cfg.nonfinite_guard
        from deepinteract_tpu.training.steps import multi_eval_step, multi_train_step

        if mesh is not None:
            from deepinteract_tpu.parallel.train import (
                make_sharded_eval_step,
                make_sharded_multi_eval_step,
                make_sharded_multi_step,
                make_sharded_train_step,
            )

            # donate=True: the Trainer threads one live state through the
            # epoch (state = step(state, ...)), so XLA may reuse the old
            # state's HBM in place — without it every mesh step pays a full
            # state copy. Anything needing the pre-step state (tests
            # comparing against a kept reference) builds its own step with
            # donate=False.
            self._train_step = make_sharded_train_step(
                mesh, weight_classes=loop_cfg.weight_classes, donate=True,
                guard=guard,
            )
            self._multi_step = make_sharded_multi_step(
                mesh, weight_classes=loop_cfg.weight_classes, donate=True,
                guard=guard,
            )
            self._eval_step = make_sharded_eval_step(mesh, weight_classes=loop_cfg.weight_classes)
            self._multi_eval = make_sharded_multi_eval_step(
                mesh, weight_classes=loop_cfg.weight_classes
            )
        else:
            self._train_step = jax.jit(
                lambda s, b: train_step(s, b, weight_classes=loop_cfg.weight_classes,
                                        guard=guard)
            )
            # Single-device multi-step/eval dispatches take the PACKED
            # upload: the stacked batch arrives as one buffer per dtype
            # (see steps.pack_tree) so argument placement costs O(dtypes)
            # transport round trips instead of O(leaves) — measured ~13%
            # of sustained flagship throughput through the axon tunnel.
            # Same math: unpack_tree's static slices/reshapes fold into
            # the consumers.
            from deepinteract_tpu.training.steps import unpack_tree

            self._multi_step_packed = jax.jit(
                lambda s, bufs, spec: multi_train_step(
                    s, unpack_tree(bufs, spec),
                    weight_classes=loop_cfg.weight_classes, guard=guard),
                static_argnums=2,
            )
            self._eval_step = jax.jit(
                lambda s, b: eval_step(s, b, weight_classes=loop_cfg.weight_classes)
            )
            self._multi_eval_packed = jax.jit(
                lambda s, bufs, spec: multi_eval_step(
                    s, unpack_tree(bufs, spec),
                    weight_classes=loop_cfg.weight_classes),
                static_argnums=2,
            )

    def _check_preempt(self, epoch_boundary: bool = False) -> None:
        """Cooperative preemption poll.

        Single-process: every dispatch boundary. Multi-host: ONLY at epoch
        boundaries, through an all-gather of the local flag, so every host
        sees the same answer and raises together — a host-local raise
        (signals rarely reach all hosts, and never simultaneously) would
        strand the peers in the next collective. Same host-agreement
        discipline as the non-finite abort."""
        if self._preempt is None:
            return
        if jax.process_count() <= 1:
            self._preempt.check()
            return
        if not epoch_boundary:
            return
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(self._preempt.requested))
        if bool(np.any(flags)):
            if not self._preempt.requested:
                self._preempt.request("preemption requested on a peer host")
            self._preempt.check()

    # -- state construction ------------------------------------------------

    def init_state(
        self,
        example: PairedComplex,
        fine_tune_from: Optional[str] = None,
    ) -> TrainState:
        state = create_train_state(
            self.model,
            example,
            seed=self.cfg.seed,
            optim_cfg=self.optim_cfg,
            # Fine-tune freezes the interaction decoder (reference
            # deepinteract_modules.py:1546-1557).
            frozen_prefixes=("decoder",) if fine_tune_from else (),
        )
        if fine_tune_from:
            ckpt = Checkpointer(CheckpointConfig(directory=fine_tune_from))
            tree = state_template(state)
            target = {"params": tree["params"], "batch_stats": tree["batch_stats"]}
            restored = ckpt.restore(target, which="best", partial=True)
            ckpt.close()
            state = state.replace(
                params=restored["params"], batch_stats=restored["batch_stats"]
            )
        if self.mesh is not None:
            from deepinteract_tpu.parallel.mesh import replicate

            state = replicate(state, self.mesh)
        return state

    # -- evaluation --------------------------------------------------------

    def evaluate(
        self,
        state: TrainState,
        val_data: DataSource,
        stage: str = "val",
        targets: Optional[List[str]] = None,
        csv_path: Optional[str] = None,
    ) -> Dict[str, float]:
        """Eval pass producing the reference metric suite (median over
        complexes; ``stage`` picks the L convention).

        Dispatch batching: consecutive same-shape batches are stacked and
        scanned K-per-dispatch (LoopConfig.eval_batches_per_dispatch, the
        eval twin of the train path's scanned dispatch) — at batch 1 the
        ~25 ms host round-trip otherwise dominates a DIPS-scale val epoch.
        """
        per_complex: List[Dict[str, float]] = []
        used_targets: List[str] = []
        idx = 0

        def consume(host_batch, probs, logits):
            """Per-complex metrics from one batch's host-local outputs."""
            nonlocal idx
            expected = np.asarray(host_batch.contact_map).shape[:3]
            if tuple(probs.shape[:3]) != expected:
                raise ValueError(
                    f"eval outputs {probs.shape} do not cover the local "
                    f"batch {expected}: an output axis is sharded across "
                    "hosts; use a within-host pair sharding for eval"
                )
            for b in range(probs.shape[0]):
                n1 = int(np.asarray(host_batch.graph1.num_nodes)[b])
                n2 = int(np.asarray(host_batch.graph2.num_nodes)[b])
                examples = np.asarray(host_batch.examples)[b]
                mask = np.asarray(host_batch.example_mask)[b]
                pos_probs, labels = M.gather_pair_predictions(probs[b], examples, mask)
                ce = _complex_ce(logits[b], examples, mask)
                per_complex.append(
                    M.complex_metrics(
                        pos_probs, labels, n1, n2, stage=stage,
                        threshold=self.cfg.pos_prob_threshold, ce=ce,
                    )
                )
                used_targets.append(targets[idx] if targets else f"complex_{idx}")
                idx += 1

        # Multi-host note: every host feeds the same complexes, so this
        # host's local shard of the global outputs is exactly what
        # host_batch holds — metrics come out identical on all hosts. That
        # agreement is a *correctness precondition* (divergent metrics feed
        # EarlyStopping, and disagreeing hosts deadlock on the next
        # collective), so it is asserted on the first batch rather than
        # left to convention in cli/train.py.
        first_checked = False

        def check_host_agreement(host_batch):
            nonlocal first_checked
            if first_checked or jax.process_count() <= 1:
                return
            first_checked = True
            cm = np.asarray(host_batch.contact_map)
            # Include the host's total val-batch count when the source
            # exposes it (ADVICE r4 item 3): hosts with identical first
            # batches but different loader LENGTHS would otherwise pass
            # this assert and then deadlock silently — the short host
            # exits the loop while the others block in a collective.
            # BucketedLoader sizes itself via num_batches(); plain sized
            # iterables via len(); unsized callables fall back to the
            # first-batch check only.
            sizer = getattr(val_data, "num_batches", None)
            try:
                n_batches = float(sizer() if callable(sizer)
                                  else len(val_data))  # type: ignore[arg-type]
            except TypeError:
                n_batches = -1.0  # unsized source; first-batch check only
            fingerprint = [
                float(np.asarray(host_batch.graph1.num_nodes).sum()),
                float(np.asarray(host_batch.graph2.num_nodes).sum()),
                float(cm.shape[0]), float(cm.shape[1]), float(cm.shape[2]),
                float(cm.sum()), n_batches,
            ]
            assert_same_across_hosts(
                fingerprint,
                fail_message=(
                    "evaluate: hosts fed different first val batches or "
                    "val-loader lengths — the val loader must be identical "
                    "(unsharded) on every host"
                ),
            )

        k = max(1, self.cfg.eval_batches_per_dispatch)
        for run in _shape_runs(_iter_data(val_data, 0), k):
            self._check_preempt()
            if self._heartbeat is not None:
                # Eval dispatches are forward progress too: without this
                # tick a long val epoch would read as a hung step loop to
                # the supervisor watchdog (training/supervisor.py).
                self._heartbeat.progress(phase=f"eval:{stage}")
            if run:
                check_host_agreement(run[0])
            if len(run) < max(k, 2):
                for hb in run:
                    padded, real_b = self._pad_to_mesh(hb)
                    out = self._eval_step(state, self._device_batch(padded))
                    consume(hb, host_local_array(out["probs"])[:real_b],
                            host_local_array(out["logits"])[:real_b])
            else:
                from deepinteract_tpu.training.steps import (
                    pack_tree,
                    stack_microbatches,
                )

                if self.mesh is None:
                    # Packed upload: one buffer per dtype (see fit()).
                    buffers, spec = pack_tree(stack_microbatches(run))
                    out = self._multi_eval_packed(state, buffers, spec)
                    real_b = None
                else:
                    # Same-shape runs share one batch size, so one pad
                    # amount covers the whole [K, B, ...] stack.
                    padded_run = []
                    real_b = None
                    for hb in run:
                        padded, real_b = self._pad_to_mesh(hb)
                        padded_run.append(padded)
                    out = self._multi_eval(
                        state,
                        self._device_stacked(stack_microbatches(padded_run)))
                probs = host_local_array(out["probs"])
                logits = host_local_array(out["logits"])
                for j, hb in enumerate(run):
                    consume(hb, probs[j][:real_b], logits[j][:real_b])
        agg = M.aggregate_median(per_complex)
        agg = {f"{stage}_{k}" if not k.startswith("med_") else f"med_{stage}_{k[4:]}": v
               for k, v in agg.items()}
        if csv_path:
            M.write_topk_csv(per_complex, used_targets, csv_path)
        return agg

    # -- training ----------------------------------------------------------

    def fit(
        self,
        state: TrainState,
        train_data: DataSource,
        val_data: Optional[DataSource] = None,
        num_epochs: Optional[int] = None,
        resume: bool = False,
    ):
        """Run the epoch loop. Returns (state, history: list of per-epoch
        metric dicts)."""
        cfg = self.cfg
        # Rank-0 checkpoint semantics (Lightning callbacks run on rank 0;
        # our state is fully replicated, so the primary host's numpy copy
        # is the complete checkpoint).
        ckpt = Checkpointer(
            CheckpointConfig(
                directory=cfg.ckpt_dir,
                metric_to_track=cfg.metric_to_track,
                save_top_k=cfg.save_top_k,
            )
        ) if (cfg.ckpt_dir and is_primary_host()) else None

        stopper = EarlyStopping(
            metric_mode(cfg.metric_to_track), cfg.patience, cfg.min_delta
        )
        start_epoch = 0
        # Mid-epoch resume cursor (--save_every_steps): the position comes
        # from the restored step number alone (training/checkpoint.py
        # decode_position — crash-window-free); the sidecar cursor merely
        # enriches it with the partial epoch's loss ledger and the loader
        # skip-budget ledger so the resumed epoch's logged metrics match
        # the uninterrupted run exactly.
        start_batch = 0
        resume_skips = 0
        resume_skipped_steps = 0
        resume_losses: List[float] = []
        if resume:
            if ckpt is not None and ckpt.has_restorable():
                state = _restore_into(
                    state, ckpt.restore(state_template(state), which="mid"))
                # The step the restore ACTUALLY loaded: the last-good
                # fallback (training/checkpoint.py) may have quarantined
                # a corrupt newest step and walked back, and the epoch
                # counter must follow the restored state, not the
                # pre-quarantine directory listing.
                restored_step = ckpt.last_restored_step
                start_epoch, start_batch = decode_position(
                    ckpt.last_restored_which,
                    int(restored_step if restored_step is not None
                        else ckpt.latest_step()))
                # EarlyStopping bookkeeping rides a JSON sidecar next to
                # the orbax roots: a preemption-resume must not reset
                # patience/best, or the resumed run would stop later than
                # the uninterrupted one. The orbax step counter stays the
                # source of truth — a sidecar whose epoch disagrees (crash
                # between save and sidecar write) is ignored.
                sidecar = _read_sidecar(cfg.ckpt_dir)
                if sidecar and int(sidecar.get("epoch", -1)) == start_epoch:
                    stopper.best = float(sidecar["stopper_best"])
                    stopper.stale_epochs = int(sidecar["stopper_stale"])
                if start_batch:
                    cur = (sidecar or {}).get("cursor") or {}
                    if (int(cur.get("epoch", -1)) == start_epoch
                            and int(cur.get("batch_index", -1))
                            == start_batch):
                        resume_losses = [float(x)
                                         for x in cur.get("loss_ledger", [])]
                        resume_skips = int(cur.get("skips_used", 0))
                        resume_skipped_steps = int(
                            cur.get("skipped_steps", 0))
                    else:
                        self.log(
                            "mid-epoch resume: trainer_state.json cursor "
                            "does not match the restored checkpoint "
                            "(killed between save and sidecar write?); "
                            "position is exact but the interrupted "
                            "epoch's logged train_loss covers only the "
                            "re-run batches")
                    self.log(f"resumed from epoch {start_epoch}, "
                             f"batch {start_batch}")
                else:
                    self.log(f"resumed from epoch {start_epoch}")
            if jax.process_count() > 1:
                # Only the primary host holds the Checkpointer; every other
                # host must receive the restored state, epoch/batch cursor,
                # and stopper bookkeeping, or the hosts would train
                # different weights over different epoch ranges / disagree
                # on the early-stop epoch (split-brain + collective
                # deadlock at the end). The scalars go first on their own:
                # a fresh start (no checkpoint) then skips broadcasting the
                # full state tree.
                from jax.experimental import multihost_utils

                vec = multihost_utils.broadcast_one_to_all(np.asarray(
                    [float(start_epoch), stopper.best,
                     float(stopper.stale_epochs), float(start_batch),
                     float(resume_skips), float(resume_skipped_steps),
                     float(len(resume_losses))], dtype=np.float64))
                start_epoch = int(vec[0])
                stopper.best = float(vec[1])
                stopper.stale_epochs = int(vec[2])
                start_batch = int(vec[3])
                resume_skips = int(vec[4])
                resume_skipped_steps = int(vec[5])
                n_ledger = int(vec[6])
                if n_ledger:
                    # The partial epoch's loss ledger (variable length, so
                    # it cannot ride the fixed vec): non-primary hosts
                    # contribute a same-shape placeholder and adopt host
                    # 0's values — their epoch line must match its.
                    ledger = np.zeros((n_ledger,), dtype=np.float64)
                    if resume_losses:
                        ledger[:] = np.asarray(resume_losses,
                                               dtype=np.float64)
                    ledger = multihost_utils.broadcast_one_to_all(ledger)
                    resume_losses = [float(x) for x in ledger]
                if start_epoch > 0 or start_batch > 0:
                    tree = multihost_utils.broadcast_one_to_all(
                        state_to_tree(state))
                    state = _restore_into(
                        state, jax.tree_util.tree_map(np.asarray, tree))

        self._install_device_prefetch(train_data)

        history: List[Dict[str, float]] = []
        epochs = num_epochs if num_epochs is not None else cfg.num_epochs
        t_start = time.time()
        stop = False
        swa_params = None
        swa_count = 0
        swa_first_epoch = int(math.ceil(cfg.swa_epoch_start * epochs))

        # Async checkpoint machinery (LoopConfig.async_checkpoint): one
        # worker thread; at most one save in flight (backpressure via
        # .result(), which also re-raises worker exceptions in the loop).
        saver = None
        pending = None
        snapshot = None
        # Single-process only: the snapshot jit would be a collective
        # dispatch on globally-sharded arrays, and only checkpointing
        # hosts would issue it — a deadlock. Multi-host keeps the sync
        # save (no tunnel round trips to hide there anyway).
        if (ckpt is not None and cfg.async_checkpoint
                and jax.process_count() == 1):
            from concurrent.futures import ThreadPoolExecutor

            saver = ThreadPoolExecutor(max_workers=1,
                                       thread_name_prefix="ckpt-save")
            # Device-side copy: the worker must not read the live state's
            # buffers (mesh steps donate them, invalidating the old ones
            # at the next dispatch); jit without aliasing yields fresh
            # HBM buffers in one dispatch.
            snapshot = jax.jit(
                lambda t: jax.tree_util.tree_map(jnp.copy, t))

        def submit_save(step_no: int, st: TrainState, metrics: dict) -> None:
            nonlocal pending, saver, snapshot
            if saver is None:
                ckpt.save(step_no, state_to_tree(st), metrics)
                return
            if pending is not None:
                pending.result()
            # The on-device snapshot holds a TRANSIENT second params +
            # opt_state copy. A config sized to the chip without that
            # headroom hits RESOURCE_EXHAUSTED here — which must downgrade
            # to the synchronous save path (no extra copy), not OOM-kill a
            # run that fits otherwise. block_until_ready forces the
            # allocation to surface at this try (async dispatch would
            # defer it to the worker's fetch next epoch).
            try:
                faults.maybe_raise(
                    "checkpoint.snapshot",
                    lambda: RuntimeError(
                        "RESOURCE_EXHAUSTED: injected snapshot OOM"))
                tree = snapshot(_state_dict(st))
                jax.block_until_ready(tree)
            except Exception as exc:
                if not _is_resource_exhausted(exc):
                    raise
                self.log(
                    "async checkpoint snapshot exhausted device memory "
                    f"({str(exc).splitlines()[0][:160]}); downgrading to "
                    "synchronous saves for the rest of the run"
                )
                saver.shutdown(wait=True)
                saver = None
                snapshot = None
                ckpt.save(step_no, state_to_tree(st), metrics)
                return
            pending = saver.submit(
                lambda tr=tree, sn=step_no, me=dict(metrics):
                    ckpt.save(sn, _fetch_tree(tr), me))

        def _drain_pending() -> None:
            # Mid-epoch saves and the async boundary saver share the orbax
            # managers; the in-flight boundary save must land first (two
            # concurrent saves on one manager race its retention pass).
            nonlocal pending
            if pending is not None:
                pending.result()
                pending = None

        # Telemetry plumbing (obs/): span JSONL under the run dir, plus the
        # optional liveness heartbeat. Both are host-side only, and both
        # start HERE — immediately before the try/finally that tears them
        # down — so a failed resume/saver setup above cannot leak a live
        # heartbeat thread (a fresh-looking file for a dead run) or an
        # open sink. A sink this fit auto-configures is ALSO closed by
        # this fit (own_span_sink), so a second fit in the same process
        # opens its own run's log instead of appending to the first's; an
        # explicitly pre-configured sink is left untouched.
        own_span_sink = False
        if (cfg.span_log and cfg.ckpt_dir and is_primary_host()
                and not obs_spans.configured()):
            obs_spans.configure(
                os.path.join(cfg.ckpt_dir, "obs", "events.jsonl"))
            own_span_sink = True
        if cfg.heartbeat_seconds > 0:
            from deepinteract_tpu.obs.heartbeat import Heartbeat

            hb_dir = cfg.ckpt_dir or cfg.diagnostics_dir or "."
            self._heartbeat = Heartbeat(
                os.path.join(hb_dir, "obs",
                             f"heartbeat_p{jax.process_index()}.json"),
                interval_s=cfg.heartbeat_seconds,
                process_index=jax.process_index(),
                process_count=jax.process_count(),
            ).start()
        # Cooperative preemption (robustness/preemption.py): entered
        # manually (not `with`) to keep the epoch loop's indentation; the
        # finally below always restores the previous signal handlers.
        preempt = PreemptionGuard(log=self.log) if cfg.preemption_guard else None
        self._preempt = preempt
        if preempt is not None:
            preempt.__enter__()
        abort_exc = None
        epoch_span = None
        try:
          for epoch in range(start_epoch, epochs):
            self._check_preempt(epoch_boundary=True)
            # Managed manually (not `with`) to keep the epoch body's
            # indentation; Span.__exit__ is idempotent, and the finally
            # below closes it on every abnormal exit path.
            epoch_span = obs_spans.span("epoch", epoch=epoch)
            epoch_span.__enter__()
            t_epoch = time.time()
            # Resuming mid-epoch: the interrupted epoch re-enters with the
            # cursor — already-paid batches' losses prefill the ledger so
            # the epoch line matches the uninterrupted run, and the loader
            # restarts at the exact next batch.
            resuming_here = epoch == start_epoch and start_batch > 0
            train_losses = list(resume_losses) if resuming_here else []
            epoch_stats: Dict[str, float] = {}
            if resuming_here:
                epoch_stats["skipped_steps"] = resume_skipped_steps
            midsave = None
            if ckpt is not None and cfg.save_every_steps > 0:
                midsave = self._make_midsave(
                    ckpt, epoch, stopper, train_losses, epoch_stats,
                    train_data,
                    base_skips=resume_skips if resuming_here else 0,
                    drain_pending=lambda: _drain_pending())
            state = self._run_train_epoch(
                state, train_data, epoch, train_losses, epoch_stats,
                start_batch=start_batch if resuming_here else 0,
                skips_used=resume_skips if resuming_here else 0,
                save_fn=midsave)
            t_train_done = time.time()
            if cfg.nonfinite_guard:
                # Guarded epochs: skipped (non-finite) steps contributed
                # no update — exclude their NaN losses from the epoch mean
                # instead of letting one bad batch blank the whole metric.
                finite = [float(l) for l in train_losses
                          if math.isfinite(float(l))]
                train_loss = float(np.mean(finite)) if finite else float("nan")
            else:
                train_loss = (float(np.mean([float(l) for l in train_losses]))
                              if train_losses else float("nan"))
            epoch_metrics: Dict[str, float] = {
                "epoch": epoch,
                "train_loss": train_loss,
                # Per-phase wall split for attributing sustained-
                # throughput overhead (the remainder between epoch
                # boundaries — checkpoint save, SWA snapshot, viz — is
                # epoch-over-epoch wall minus these phases).
                "train_seconds": t_train_done - t_epoch,
            }
            if cfg.nonfinite_guard:
                epoch_metrics["train_skipped_steps"] = float(
                    epoch_stats.get("skipped_steps", 0))
            if val_data is not None:
                with obs_spans.span("eval", epoch=epoch):
                    epoch_metrics.update(
                        self.evaluate(state, val_data, stage="val"))
                epoch_metrics["val_eval_seconds"] = time.time() - t_train_done
                if (
                    cfg.viz_every_n_epochs
                    and (epoch + 1) % cfg.viz_every_n_epochs == 0
                    and (self.metric_writer is not None
                         or jax.process_count() > 1)
                ):
                    # Multi-host: the viz eval step is a global collective,
                    # so writer-less hosts must still execute it; only the
                    # image writes are rank-0.
                    self._log_viz_images(state, val_data, epoch)
            # After val/viz so it covers the phases above (it used to be
            # computed alongside train_seconds, making the two identical).
            epoch_metrics["epoch_seconds"] = time.time() - t_epoch
            history.append(epoch_metrics)
            self._write_metrics(epoch, epoch_metrics)
            phase = f"train_s={epoch_metrics['train_seconds']:.1f}"
            if "val_eval_seconds" in epoch_metrics:
                phase += f" val_s={epoch_metrics['val_eval_seconds']:.1f}"
            self.log(
                f"epoch {epoch}: train_loss={epoch_metrics['train_loss']:.4f} "
                f"{phase} "
                + " ".join(
                    f"{k}={v:.4f}" for k, v in epoch_metrics.items()
                    if k.startswith(("val_", "med_val_"))
                    and k != "val_eval_seconds" and isinstance(v, float)
                    and not math.isnan(v)
                )
            )

            if cfg.swa and epoch >= swa_first_epoch:
                # Packed fetch where single-process (the per-leaf path
                # costs one transport round trip per param leaf).
                p = _fetch_tree(state.params)
                if swa_params is None:
                    swa_params, swa_count = p, 1
                else:
                    swa_count += 1
                    swa_params = jax.tree_util.tree_map(
                        lambda a, b: a + (b - a) / swa_count, swa_params, p
                    )

            ckpt_seconds = 0.0
            if ckpt is not None:
                with obs_spans.span("checkpoint", epoch=epoch) as ckpt_span:
                    submit_save(epoch + 1, state, epoch_metrics)
                ckpt_seconds = ckpt_span.dur_s
            if self._heartbeat is not None:
                # Boundary work (checkpoint drain, stopper bookkeeping)
                # is progress for watchdog purposes.
                self._heartbeat.progress(phase="epoch_boundary",
                                         epoch=epoch)

            # Per-epoch step-time decomposition: where the wall clock went
            # (host-side timers only — data_wait/h2d/device come from
            # _run_train_epoch via epoch_stats, checkpoint is the blocking
            # part of the save above). Logged, kept in history, and
            # persisted in the trainer_state.json sidecar as `telemetry`.
            telemetry = self._epoch_telemetry(
                epoch_stats, ckpt_seconds,
                eval_s=epoch_metrics.get("val_eval_seconds", 0.0),
                epoch_s=time.time() - t_epoch)
            epoch_metrics.update(telemetry)
            _EPOCHS_TOTAL.inc()
            self.log(
                f"epoch {epoch} telemetry: "
                f"data_wait={telemetry['tele_data_wait_frac']:.1%} "
                f"device={telemetry['tele_device_frac']:.1%} "
                f"checkpoint={telemetry['tele_checkpoint_frac']:.1%} "
                f"eval={telemetry['tele_eval_frac']:.1%}"
            )

            tracked = epoch_metrics.get(cfg.metric_to_track, float("nan"))
            if val_data is not None and stopper.update(tracked):
                self.log(
                    f"early stop at epoch {epoch}: no {cfg.metric_to_track} improvement "
                    f"in {cfg.patience} epochs (best {stopper.best:.6f})"
                )
                stop = True
            if ckpt is not None:
                # After stopper.update so a resume restores the counters
                # as of this epoch boundary (see the resume block above).
                _write_sidecar(cfg.ckpt_dir, {
                    "epoch": epoch + 1,
                    "stopper_best": stopper.best,
                    "stopper_stale": stopper.stale_epochs,
                    "telemetry": telemetry,
                })
            if cfg.max_time_seconds and (time.time() - t_start) > cfg.max_time_seconds:
                self.log("max_time reached; stopping")
                stop = True
            epoch_span.__exit__(None, None, None)
            if stop:
                break

        except (TrainingPreempted, NonFiniteTrainingError) as exc:
            # Re-raised AFTER the drain below so the in-flight save (the
            # checkpoint a preempted run resumes from) hits disk first.
            abort_exc = exc
        finally:
            # Drain the in-flight save even when the loop raises: its
            # failure must not be swallowed, and the executor must not
            # outlive fit. A drain error during exception unwind is
            # chained, not masking.
            try:
                if pending is not None:
                    pending.result()
                    pending = None
            finally:
                if saver is not None:
                    saver.shutdown(wait=True)
                if preempt is not None:
                    preempt.__exit__(None, None, None)
                self._preempt = None
                self._stop_profile()
                if epoch_span is not None:
                    epoch_span.__exit__(None, None, None)
                if own_span_sink:
                    obs_spans.close()
                if self._heartbeat is not None:
                    self._heartbeat.stop()
                    self._heartbeat = None

        if abort_exc is not None:
            if ckpt is not None:
                ckpt.close()
            if isinstance(abort_exc, TrainingPreempted):
                self.log(
                    f"preempted ({abort_exc}): last/ checkpoint flushed at "
                    f"the last completed epoch — rerun with resume=True to "
                    "continue"
                )
            raise abort_exc

        if cfg.swa and swa_params is not None:
            self.log(f"SWA: averaged {swa_count} epoch snapshot(s) into final params")
            if self.mesh is not None:
                # Mesh runs: bare device_put would commit the averaged
                # params to one local device and clash with mesh-sharded
                # batches in the stats refresh below (multi-host would mix
                # host-local params with global batch arrays). Re-replicate
                # over the mesh like the initial state placement.
                from deepinteract_tpu.parallel.mesh import replicate

                state = state.replace(params=replicate(swa_params, self.mesh))
            else:
                # di: allow[loader-boundary] params tree, not a batch: single-device SWA weights need a plain placement, and the stats refresh below re-jits anyway
                state = state.replace(params=jax.device_put(swa_params))
            # Batch-norm statistics were accumulated for the last-epoch
            # weights; refresh them for the averaged weights (Lightning's
            # StochasticWeightAveraging does the same BN-update pass).
            state = self._refresh_batch_stats(state, train_data)
            if ckpt is not None and history:
                # Persist the SWA weights so cli.test/predict load what the
                # reported metrics were computed with.
                ckpt.save(history[-1]["epoch"] + 2, state_to_tree(state),
                          history[-1])
        if ckpt is not None:
            ckpt.close()
        return state, history

    # -- internals ---------------------------------------------------------

    def _install_device_prefetch(self, train_data: DataSource) -> None:
        """Configure the input pipeline's placement stage
        (data/pipeline.py) for this fit and log the adopted mode once.

        Placement is a first-class pipeline stage in every dispatch mode
        (single/mesh × per-step/scanned). Without device_prefetch it
        runs inline at the dispatch site — bit-for-bit the historical
        path. With device_prefetch it runs double-buffered on the
        placement thread: sharding-aware h2d (per-leaf NamedSharding
        from the trainer's mesh, so batches land pre-sharded and each
        host places only its local shard) plus the [K, B, ...]
        scan-stacking for scanned dispatch, bounded to at most the
        loader's ``prefetch`` depth of pinned dispatches."""
        from deepinteract_tpu.data.pipeline import BatchPlacement

        k = max(1, self.cfg.steps_per_dispatch)
        self._placement = BatchPlacement(
            mesh=self.mesh, steps_per_dispatch=k, transfer=False)
        depth = 0
        if self.cfg.device_prefetch:
            # The pin bound IS the source's read-ahead depth, but in
            # DISPATCHES: under scanned dispatch each pinned payload is
            # a [K, B, ...] stack, so the working set is up to
            # prefetch*K batches (documented in README/--help; lower the
            # loader's prefetch on memory-tight configs). A loader with
            # prefetch=0 disabled buffering deliberately (memory cap),
            # so placement must stay inline there — fabricating a depth
            # would pin device memory the operator said not to.
            # Sources without a read-ahead knob (plain sequences) get
            # the classic double buffer.
            depth_attr = getattr(train_data, "prefetch", None)
            depth = 2 if depth_attr is None else max(0, int(depth_attr))
            if depth == 0:
                self.log(
                    "device_prefetch requested but the data source's "
                    "prefetch depth is 0 (read-ahead disabled) — "
                    "placement stays inline; raise the loader's "
                    "prefetch to enable double-buffering")
            else:
                self._prefetch_placement = BatchPlacement(
                    mesh=self.mesh, steps_per_dispatch=k, transfer=True)
        self._prefetch_depth = depth
        if depth:
            extra = (" (multi-host: each host places its local shard)"
                     if self.mesh is not None and jax.process_count() > 1
                     else "")
            self.log(
                f"input pipeline: placement mode {self._placement.mode}, "
                f"double-buffered on the placement thread (depth {depth})"
                f"{extra}")
        else:
            why = ("source prefetch depth 0" if self.cfg.device_prefetch
                   else "device_prefetch off")
            self.log(f"input pipeline: placement mode "
                     f"{self._placement.mode}, inline ({why})")

    @staticmethod
    def _epoch_telemetry(epoch_stats: Dict[str, float], ckpt_s: float,
                         eval_s: float, epoch_s: float) -> Dict[str, float]:
        """Flat float dict (history/metric-writer friendly): absolute
        seconds per phase plus fractions of the epoch wall. The phases are
        not exhaustive (SWA/viz/logging live in the remainder), so the
        fractions answer "what dominates", not "what sums to one"."""
        wall = max(epoch_s, 1e-9)
        data_s = float(epoch_stats.get("data_wait_s", 0.0))
        h2d_s = float(epoch_stats.get("h2d_s", 0.0))
        device_s = float(epoch_stats.get("device_s", 0.0))
        # h2d semantics under --device_prefetch: placement ran on the
        # pipeline's placement thread, so tele_h2d counts OVERLAPPED
        # seconds (it can legitimately exceed the critical-path share);
        # the residual input stall is tele_data_wait.
        return {
            "tele_data_wait_s": data_s,
            "tele_h2d_s": h2d_s,
            "tele_device_s": device_s,
            "tele_checkpoint_s": float(ckpt_s),
            "tele_eval_s": float(eval_s),
            "tele_data_wait_frac": data_s / wall,
            "tele_h2d_frac": h2d_s / wall,
            "tele_device_frac": device_s / wall,
            "tele_checkpoint_frac": float(ckpt_s) / wall,
            "tele_eval_frac": float(eval_s) / wall,
        }

    def _profile_tick(self) -> None:
        """--profile_dir window control, called before every train
        dispatch: start the jax.profiler capture at the run's second
        dispatch (the first is compile-dominated) and stop it after
        LoopConfig.profile_steps dispatches. Span profiler annotations are
        enabled for the window, so the trace comes out phase-labeled."""
        if self._profile_done:
            return
        if not self._profile_active:
            if self._dispatch_count >= 1:
                jax.profiler.start_trace(self.cfg.profile_dir)
                obs_spans.set_profiler_annotations(True)
                self._profile_active = True
                self._profile_started = True
                self._profile_remaining = max(1, self.cfg.profile_steps)
                self.log(
                    f"profiling {self._profile_remaining} train dispatch(es) "
                    f"into {self.cfg.profile_dir}")
            return
        self._profile_remaining -= 1
        if self._profile_remaining <= 0:
            self._stop_profile()

    def _stop_profile(self) -> None:
        """Idempotent capture stop (also the fit-end/abort safety net, so
        a short run never leaves a trace capture dangling). A completed
        window is immediately attributed: per-dispatch device time from
        the captured trace lands in the ``di_train_profile_*`` gauges and
        the log, so the operator gets the first-order answer ("how much
        of the step is device time, and which op leads") without leaving
        the training console."""
        if self._profile_active:
            obs_spans.set_profiler_annotations(False)
            jax.profiler.stop_trace()
            self._profile_active = False
            self._attribute_profile()
        if (self.cfg.profile_dir and not self._profile_started
                and not self._profile_done):
            self.log(
                f"profile_dir={self.cfg.profile_dir}: the run ended before "
                "its second train dispatch — nothing was captured")
        self._profile_done = True

    def _attribute_profile(self) -> None:
        """Parse the just-captured --profile_dir trace into the device-
        time gauges (best-effort: an exporter-format surprise must never
        take down the run that just finished profiling)."""
        try:
            from deepinteract_tpu.obs import attribution as obs_attr
            from deepinteract_tpu.obs import device as obs_device

            trace = obs_device.load_profile(self.cfg.profile_dir)
            agg = obs_attr.aggregate_ops(trace, top_n=3)
            phases = obs_attr.attribute_phases(trace)["phases"]
            dev_step = next((p for p in phases if p["name"] == "device_step"),
                            None)
            dispatches = (dev_step["instances"] if dev_step
                          else max(1, self.cfg.profile_steps))
            per_dispatch_ms = (dev_step["device_ms"] / dispatches
                               if dev_step else
                               agg["total_device_ms"] / dispatches)
            obs_metrics.gauge(
                "di_train_profile_device_seconds_per_dispatch",
                "Measured device time per train dispatch over the last "
                "--profile_dir window").set(per_dispatch_ms / 1e3)
            obs_metrics.gauge(
                "di_train_profile_device_total_seconds",
                "Total device time inside the last --profile_dir "
                "window").set(agg["total_device_ms"] / 1e3)
            top = ", ".join(
                f"{op['name']} {op['total_ms']:.2f}ms ({op['share']:.0%})"
                for op in agg["top_ops"][:3])
            self.log(
                f"profile attribution: {agg['total_device_ms']:.2f} ms "
                f"device time over {dispatches} dispatch(es) "
                f"({per_dispatch_ms:.2f} ms/dispatch); top ops: {top}; "
                f"full report: python -m deepinteract_tpu.cli.attribute "
                f"--profile_dir {self.cfg.profile_dir}")
        except Exception as exc:  # noqa: BLE001 - advisory only
            self.log(f"profile attribution skipped: {exc}")

    def _make_midsave(self, ckpt, epoch: int, stopper, train_losses: list,
                      epoch_stats: Dict[str, float], train_data,
                      base_skips: int, drain_pending):
        """Build the intra-epoch cadence-save hook (--save_every_steps):
        an orbax mid/ step whose number encodes the exact resume position
        plus the trainer_state.json cursor (loss ledger, loader
        skip-budget ledger) — everything a --resume needs to land on the
        next batch with parity-exact epoch metrics. Host 0 only (the
        caller gates on ckpt); no collective runs here, so hosts that
        skip it stay aligned."""
        cfg = self.cfg
        skips_fn = getattr(train_data, "skips_before", None)

        def midsave(st: TrainState, batches_done: int) -> None:
            drain_pending()
            with obs_spans.span("midepoch_checkpoint", epoch=epoch,
                                batch=batches_done):
                ckpt.save_midepoch(epoch, batches_done, state_to_tree(st))
                ckpt.wait()
                skips = (int(skips_fn(batches_done))
                         if callable(skips_fn) else base_skips)
                _write_sidecar(cfg.ckpt_dir, {
                    "epoch": epoch,
                    "stopper_best": stopper.best,
                    "stopper_stale": stopper.stale_epochs,
                    "cursor": {
                        "epoch": epoch,
                        "batch_index": int(batches_done),
                        "opt_step": int(np.asarray(
                            host_local_array(st.step))),
                        "seed": cfg.seed,
                        "skips_used": skips,
                        "skipped_steps": int(
                            epoch_stats.get("skipped_steps", 0)),
                        "loss_ledger": [float(l) for l in train_losses],
                    },
                })
            if self._heartbeat is not None:
                self._heartbeat.progress(phase="midepoch_checkpoint",
                                         epoch=epoch)

        return midsave

    def _run_train_epoch(self, state: TrainState, train_data: DataSource,
                         epoch: int, train_losses: list,
                         epoch_stats: Optional[Dict[str, float]] = None,
                         start_batch: int = 0, skips_used: int = 0,
                         save_fn=None) -> TrainState:
        """One epoch of train steps, grouping consecutive same-shape batches
        into K-step scanned dispatches (LoopConfig.steps_per_dispatch).

        Robustness duties (all off the hot path):
        * polls the PreemptionGuard between dispatches;
        * applies the ``train.nan_batch`` / ``train.sigterm`` fault-
          injection probes per batch (no-ops without a fault plan);
        * tracks the guarded step's skip counters and aborts with a
          diagnostic dump once ``max_bad_steps`` CONSECUTIVE steps were
          skipped. With scanned dispatch + double-buffered metric fetch
          the abort lands up to one dispatch late — acceptable, since the
          guard already prevented every bad update on device.
        """
        cfg = self.cfg
        k = max(1, cfg.steps_per_dispatch)
        # Mid-epoch resume: numbering continues from the cursor so logs,
        # ledger indices, and the cadence counter line up with the
        # uninterrupted run.
        step_idx = start_batch
        dispatched = start_batch
        since_save = 0
        stats = epoch_stats if epoch_stats is not None else {}
        stats.setdefault("skipped_steps", 0)
        # Phase accumulators for the epoch's step-time decomposition
        # (host wall clock only; dispatch is async, so "device_s" counts
        # time the HOST spent dispatching + blocked fetching metrics —
        # exactly the existing differenced protocol, no new syncs).
        stats.setdefault("data_wait_s", 0.0)
        stats.setdefault("h2d_s", 0.0)
        stats.setdefault("device_s", 0.0)
        # Abort-diagnostics context: a short host-side metric history plus
        # the last two dispatched runs' host batches (summarized lazily —
        # only on abort — so steady state pays just two references).
        recent_metrics: collections.deque = collections.deque(maxlen=32)
        recent_runs: collections.deque = collections.deque(maxlen=2)

        def abort_nonfinite(consecutive: int):
            # Host agreement is BY CONSTRUCTION, not by collective: the
            # guard branches on the pmean/GSPMD-replicated loss and grad
            # norm, and the bad_steps counter lives in the replicated
            # TrainState, so every host reads the same value and reaches
            # this abort at the same step. No cross-host check belongs
            # here — a collective on an abort path only the aborting
            # host(s) execute would itself deadlock the survivors.
            payload = {
                "epoch": epoch,
                "step": step_idx,
                "consecutive_bad_steps": consecutive,
                "max_bad_steps": cfg.max_bad_steps,
                "recent_metrics": [
                    {"loss": l, "grad_norm": g} for l, g in recent_metrics
                ],
                "recent_batches": [
                    summarize_batch(b) for run in recent_runs for b in run
                ],
            }
            path = None
            if is_primary_host():
                path = dump_diagnostics(
                    cfg.diagnostics_dir or cfg.ckpt_dir or ".", payload)
            _NONFINITE_ABORTS.inc()
            raise NonFiniteTrainingError(
                f"aborting: {consecutive} consecutive non-finite train steps "
                f"(epoch {epoch}, step {step_idx}, max_bad_steps="
                f"{cfg.max_bad_steps})"
                + (f"; diagnostics: {path}" if path else ""),
                diagnostics_path=path,
            )

        def log_step(metrics):
            nonlocal step_idx
            step_idx += 1
            _STEPS_TOTAL.inc()
            if self._heartbeat is not None:
                self._heartbeat.progress(step=step_idx, epoch=epoch)
            # host_local_array: multi-host losses are replicated global
            # arrays that plain float() cannot read.
            loss = float(host_local_array(metrics["loss"]))
            train_losses.append(loss)
            grad_norm = float(host_local_array(metrics["grad_norm"]))
            recent_metrics.append((loss, grad_norm))
            if "bad_step" in metrics:
                if float(host_local_array(metrics["bad_step"])) > 0:
                    stats["skipped_steps"] += 1
                    _SKIPPED_TOTAL.inc()
                    self.log(
                        f"epoch {epoch} step {step_idx}: non-finite "
                        f"loss/grads (loss={loss}) — optimizer update "
                        f"skipped ({stats['skipped_steps']} this epoch)"
                    )
                consecutive = int(float(host_local_array(metrics["bad_steps"])))
                # `consecutive > 0`: a healthy step resets the counter to
                # 0, which must never trip the abort even under a
                # (nonsensical but accepted) max_bad_steps <= 0.
                if consecutive > 0 and consecutive >= cfg.max_bad_steps:
                    abort_nonfinite(consecutive)
            if cfg.log_every and step_idx % cfg.log_every == 0:
                self.log(
                    f"epoch {epoch} step {step_idx}: "
                    f"loss={train_losses[-1]:.4f} "
                    f"grad_norm={grad_norm:.4f}"
                )

        # Double-buffered metric fetch (VERDICT r4 item 3): the host fetch
        # of a dispatch's stacked metrics blocks until the device finishes,
        # so fetching IMMEDIATELY after dispatch serializes host work
        # (loading + stacking the next run) behind device compute. Instead
        # the fetch of dispatch N is deferred until dispatch N+1 has been
        # submitted — jit dispatch is async, so stacking run N+1 then
        # overlaps the device executing run N, and by the time N's metrics
        # are read they are already resident.
        pending = None  # (stacked device metrics, run length)

        def flush(entry):
            stacked, n = entry
            # ONE host fetch per metric leaf per dispatch: per-step
            # slicing of the device array (m[j] then float()) costs a
            # device round trip PER MICROBATCH, which at K=8 through a
            # remote-device tunnel dominates the logging path
            # (measured, tools/sustained_train.py r4).
            t0 = time.perf_counter()
            stacked_host = {
                k: np.asarray(host_local_array(v))
                for k, v in stacked.items()
            }
            # The fetch blocks until the dispatch's device work is done,
            # so it belongs to the device share of the decomposition.
            stats["device_s"] += time.perf_counter() - t0
            for j in range(n):
                log_step({k: v[j] for k, v in stacked_host.items()})

        def instrumented(items):
            """Per-batch fault probes (robustness/faults.py): free when no
            plan is configured. The sigterm probe only *requests*
            preemption — the raise happens at the next dispatch boundary,
            exactly like a real signal. ``training.step_crash`` is the
            hard-crash site (process dies with a traceback, nonzero exit);
            ``training.hang`` freezes the step loop forever — the wedged-
            collective simulation only the supervisor watchdog's SIGKILL
            ends (training/supervisor.py)."""
            for b in items:
                if faults.fire("train.sigterm") and self._preempt is not None:
                    self._preempt.request("injected SIGTERM (fault plan)")
                if faults.fire("training.step_crash"):
                    raise RuntimeError(
                        "injected training.step_crash fault (chaos plan)")
                if faults.fire("training.hang"):
                    _simulate_hang(self.log)
                yield faults.maybe_poison("train.nan_batch", b)

        def maybe_midsave(current_state) -> None:
            """Cadence trigger, called after every dispatch: flush the
            double-buffered metrics first so the cursor's loss ledger
            covers every batch the saved state contains."""
            nonlocal pending, since_save
            if save_fn is None or not 0 < cfg.save_every_steps <= since_save:
                return
            if pending is not None:
                flush(pending)
                pending = None
            save_fn(current_state, dispatched)
            since_save = 0

        def epoch_source():
            """The epoch's batch stream, honoring a mid-epoch cursor.
            A cursor-aware loader (BucketedLoader.iter_epoch) skips the
            already-paid plan entries without loading them; any other
            DataSource degrades to load-and-drop — slower, same batches."""
            if not start_batch and not skips_used:
                return _iter_data(train_data, epoch)
            iter_ep = getattr(train_data, "iter_epoch", None)
            if callable(iter_ep):
                try:
                    return iter_ep(epoch, start_batch=start_batch,
                                   skips_used=skips_used)
                except TypeError:
                    pass  # pre-cursor source with an iter_epoch of its own
            src = iter(_iter_data(train_data, epoch))
            for _ in range(start_batch):
                next(src, None)
            return src

        # The loader→step boundary (data/pipeline.py): same-shape runs go
        # through the BatchPlacement stage. With device_prefetch the
        # placement — sharding-aware h2d plus the [K, B, ...]
        # scan-stacking for scanned dispatch — runs double-buffered on
        # the placement thread, bounded to at most `prefetch` pinned
        # dispatches; without it the IDENTICAL placement runs inline at
        # the dispatch site (the historical path, bit-for-bit).
        #
        # data_wait: host wall time blocked pulling the next same-shape
        # (possibly pre-placed) run — the input-bound-loop detector.
        # Measured around the iterator's next() because the wait happens
        # inside generator suspension where a `with` cannot reach; each
        # wait is also emitted as a leaf span event. h2d counts placement
        # seconds wherever they ran: on the placement thread they overlap
        # device compute and the critical-path stall shows up (only) in
        # data_wait.
        if self._placement is None:  # _run_train_epoch outside fit (tests)
            self._install_device_prefetch(train_data)
        placement = self._placement
        overlap = self._prefetch_depth > 0
        source = _shape_runs(instrumented(epoch_source()), k)
        if overlap:
            from deepinteract_tpu.data.pipeline import placed_runs

            run_iter = iter(placed_runs(source, self._prefetch_placement,
                                        self._prefetch_depth))
        else:
            run_iter = iter(source)
        while True:
            t_wait = time.perf_counter()
            item = next(run_iter, None)
            waited = time.perf_counter() - t_wait
            stats["data_wait_s"] += waited
            if item is None:
                break
            pr = item if overlap else None  # PlacedRun | host run list
            run = pr.host if pr is not None else item
            obs_spans.emit("data_wait", waited, n=len(run))
            self._check_preempt()
            recent_runs.append(run)
            # The per-batch-vs-stacked decision belongs to the placement
            # layer: a PlacedRun says which form it holds (pr.kind); only
            # the inline path derives it locally, with the same rule
            # place_run applies.
            per_batch = (pr.kind == "per_batch" if pr is not None
                         else len(run) < max(k, 2))
            if per_batch:
                if pending is not None:
                    flush(pending)
                    pending = None
                for j, hb in enumerate(run):
                    # Each batch here is its OWN device dispatch, so the
                    # profile window and step numbering advance per batch
                    # (the scanned branch advances once per scan).
                    self._profile_tick()
                    with obs_spans.span("step",
                                        step_num=self._dispatch_count):
                        if pr is not None:
                            batch = pr.placed[j]
                            h2d_s = pr.h2d_s[j]
                            obs_spans.emit("h2d", h2d_s)
                        else:
                            with obs_spans.span("h2d") as h2d_span:
                                batch = placement.place_batch(hb)
                            h2d_s = h2d_span.dur_s
                        with obs_spans.span("device_step") as dev_span:
                            state, metrics = self._train_step(state, batch)
                            log_step(metrics)
                    stats["h2d_s"] += h2d_s
                    stats["device_s"] += dev_span.dur_s
                    self._dispatch_count += 1
                    dispatched += 1
                    since_save += 1
                    maybe_midsave(state)
            else:
                # ONE placement per dispatch: the full run stacks to
                # [K, B, ...] — mesh runs land pre-sharded (multi-host:
                # global arrays from this host's local slice), single
                # device takes the packed upload (one buffer per dtype).
                self._profile_tick()
                with obs_spans.span("step", step_num=self._dispatch_count,
                                    n=len(run)):
                    if pr is not None:
                        placed = pr.placed
                        h2d_s = pr.h2d_s[0]
                        obs_spans.emit("h2d", h2d_s, n=len(run))
                    else:
                        with obs_spans.span("h2d") as h2d_span:
                            placed = placement.place_stacked(run)
                        h2d_s = h2d_span.dur_s
                    with obs_spans.span("device_step") as dev_span:
                        if self.mesh is None:
                            buffers, spec = placed
                            state, stacked = self._multi_step_packed(
                                state, buffers, spec)
                        else:
                            state, stacked = self._multi_step(state, placed)
                stats["h2d_s"] += h2d_s
                stats["device_s"] += dev_span.dur_s
                if pending is not None:
                    flush(pending)  # N-1's fetch, after N's async dispatch
                pending = (stacked, len(run))
                self._dispatch_count += 1
                dispatched += len(run)
                since_save += len(run)
                maybe_midsave(state)
        if pending is not None:
            flush(pending)
        return state

    def _pad_to_mesh(self, host_batch: PairedComplex):
        """Pad an EVAL batch's leading axis up to mesh divisibility by
        repeating the last complex, returning ``(padded, real_b)``.

        A val/test split whose (global) batch does not divide the mesh's
        data axis — the canonical case is a 1-complex split on a 4-way
        mesh — must pad, not crash in ``device_put``. Callers slice the
        step outputs back to ``real_b`` before metrics, so the clones
        never contaminate the reported numbers. Train batches stay the
        loader's contract (data/pipeline.py sizes them to the mesh);
        this affordance is eval-only."""
        if self.mesh is None:
            return host_batch, None
        from deepinteract_tpu.parallel.mesh import DATA_AXIS

        data_size = int(self.mesh.shape.get(DATA_AXIS, 1))
        real_b = int(np.shape(jax.tree_util.tree_leaves(host_batch)[0])[0])
        procs = jax.process_count()
        target = real_b
        while (target * procs) % data_size != 0:
            target += 1
        if target == real_b:
            return host_batch, real_b
        pad = target - real_b
        padded = jax.tree_util.tree_map(
            lambda x: np.concatenate(
                [np.asarray(x),
                 np.repeat(np.asarray(x)[-1:], pad, axis=0)], axis=0),
            host_batch)
        return padded, real_b

    def _device_batch(self, batch: PairedComplex) -> PairedComplex:
        if self.mesh is not None:
            from deepinteract_tpu.parallel.mesh import shard_batch

            return shard_batch(batch, self.mesh)
        return batch

    def _device_stacked(self, stacked: PairedComplex) -> PairedComplex:
        """Place a [K, B, ...] scan-stack (multi-host: global arrays from
        this host's local slice; single-process mesh/jit handles placement
        from in_shardings, but explicit placement keeps one path)."""
        if self.mesh is not None:
            from deepinteract_tpu.parallel.mesh import shard_stacked_batch

            return shard_stacked_batch(stacked, self.mesh)
        return stacked

    def _refresh_batch_stats(self, state: TrainState, train_data: DataSource) -> TrainState:
        """One forward pass over the training data in train mode, updating
        only batch statistics (no gradients)."""
        import functools

        @functools.partial(jax.jit, static_argnums=())
        def stats_step(s, batch):
            _, mutated = s.apply_fn(
                {"params": s.params, "batch_stats": s.batch_stats},
                batch.graph1, batch.graph2, train=True,
                rngs={"dropout": s.dropout_rng},
                mutable=["batch_stats"],
            )
            return s.replace(batch_stats=mutated["batch_stats"])

        for batch in _iter_data(train_data, 0):
            state = stats_step(state, self._device_batch(batch))
        return state

    def _log_viz_images(self, state: TrainState, val_data: DataSource, epoch: int):
        """Predicted-probability and ground-truth contact maps of the first
        validation complex as TensorBoard images (reference viz epochs,
        deepinteract_modules.py:1850-1881)."""
        host_batch = next(iter(_iter_data(val_data, 0)), None)
        if host_batch is None:
            return
        padded, real_b = self._pad_to_mesh(host_batch)
        batch = self._device_batch(padded)
        out = self._eval_step(state, batch)
        if self.metric_writer is None:
            return  # non-primary host: participated in the collective only
        probs_full = host_local_array(out["probs"])[:real_b]
        expected = np.asarray(host_batch.contact_map).shape[:3]
        if tuple(probs_full.shape[:3]) != expected:
            raise ValueError(
                f"viz eval outputs {probs_full.shape} do not cover the local "
                f"batch {expected}: an output axis is sharded across hosts"
            )
        probs = probs_full[0, ..., -1]  # [L1, L2] positive class
        n1 = int(np.asarray(host_batch.graph1.num_nodes)[0])
        n2 = int(np.asarray(host_batch.graph2.num_nodes)[0])
        pred = (probs[:n1, :n2, None] * 255).astype(np.uint8)
        true = (np.asarray(host_batch.contact_map)[0, :n1, :n2, None] * 255).astype(np.uint8)
        self.metric_writer.add_image("val_predicted_contact_probs", pred, epoch,
                                     dataformats="HWC")
        self.metric_writer.add_image("val_true_contacts", true, epoch,
                                     dataformats="HWC")

    def _write_metrics(self, epoch: int, metrics: Dict[str, float]) -> None:
        # Fan-out: the configured writer (if any) plus the registry sink,
        # so /metrics-style exposition of a co-resident process sees the
        # last epoch's scalars with zero extra configuration.
        for k, v in metrics.items():
            if isinstance(v, (int, float)) and not math.isnan(float(v)):
                self._scalar_writer.add_scalar(k, float(v), epoch)


def _simulate_hang(log) -> None:
    """``training.hang`` chaos site: freeze the step loop forever — the
    wedged-collective simulation. The heartbeat thread (a daemon) keeps
    the file fresh while ``last_progress_ts`` goes stale, which is
    exactly the signature the supervisor watchdog SIGKILLs on; nothing
    else ends this loop, faithfully to a stuck all-reduce. Sleeps in
    short slices so a debugger still sees a responsive-looking process."""
    log("training.hang fault injected: step loop frozen until SIGKILL "
        "(watchdog bait)")
    while True:
        time.sleep(0.25)


def _is_resource_exhausted(exc: Exception) -> bool:
    """Device-memory exhaustion signatures across jax/XLA versions and
    backends (XlaRuntimeError carries 'RESOURCE_EXHAUSTED: ...'; PJRT CPU/
    GPU allocators phrase it 'Out of memory' / 'Failed to allocate')."""
    msg = str(exc)
    lowered = msg.lower()
    return ("RESOURCE_EXHAUSTED" in msg
            or "resource exhausted" in lowered
            or "out of memory" in lowered
            or "failed to allocate" in lowered)


def _complex_ce(logits: np.ndarray, examples: np.ndarray, mask: np.ndarray) -> float:
    """Per-complex CE over its example list (the reference's per-step
    ``self.loss_fn(sampled_logits, labels)``)."""
    ex = examples[mask]
    sel = logits[ex[:, 0], ex[:, 1]]  # [M, 2]
    sel = sel - sel.max(axis=-1, keepdims=True)
    logp = sel - np.log(np.sum(np.exp(sel), axis=-1, keepdims=True))
    return float(-np.mean(logp[np.arange(len(ex)), ex[:, 2]]))


# Module-level so jax.jit's cache (keyed on function identity + arg
# shapes) persists across checkpoint fetches — a per-call lambda would
# re-trace and re-compile the ~900-input concat every epoch.
@jax.jit
def _packed_concat(*xs):
    return jnp.concatenate([jnp.ravel(x) for x in xs])


def _packed_device_get(tree):
    """Fetch a device pytree to host numpy in O(dtypes) transfers instead
    of O(leaves).

    Through a remote-dispatch transport (the axon tunnel) every
    device->host fetch pays a fixed round trip, so per-leaf
    ``device_get`` over a ~900-leaf train state costs ~90 s/epoch
    (measured — it was the dominant sustained-training overhead, 43% of
    steady-state epoch wall). Packing: one jitted ravel+concat per dtype
    group on device, a single fetch of each packed buffer, then split and
    reshape on the host. Numerically a no-op (pure reshape/concat of the
    same values)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    by_dtype: Dict[Any, list] = {}
    out: list = [None] * len(leaves)
    for idx, leaf in enumerate(leaves):
        if isinstance(leaf, jax.Array):
            by_dtype.setdefault(leaf.dtype, []).append(idx)
        else:
            # Host scalars/arrays (e.g. a python-int step): no transfer
            # to amortize, and jnp coercion would change their dtype.
            out[idx] = np.asarray(jax.device_get(leaf))
    for dtype, idxs in by_dtype.items():
        if len(idxs) == 1:
            out[idxs[0]] = np.asarray(jax.device_get(leaves[idxs[0]]))
            continue
        group = [leaves[i] for i in idxs]
        packed = _packed_concat(*group)
        flat = np.asarray(jax.device_get(packed))
        offset = 0
        for i, leaf in zip(idxs, group):
            n = int(np.prod(np.shape(leaf), dtype=np.int64)) if np.shape(leaf) else 1
            out[i] = flat[offset : offset + n].reshape(np.shape(leaf))
            offset += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _state_dict(state: TrainState):
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
        "batch_stats": state.batch_stats,
        "dropout_rng": state.dropout_rng,
    }


def _fetch_tree(tree):
    """Device tree -> host numpy tree. Single-process runs take the packed
    fetch (one transfer per dtype — see :func:`_packed_device_get`);
    multi-host keeps the per-leaf path, whose host_local_array handles
    sharded layouts (and production multi-host has no tunnel round trip
    to amortize)."""
    if jax.process_count() == 1:
        return _packed_device_get(tree)
    return jax.tree_util.tree_map(host_local_array, tree)


def state_to_tree(state: TrainState):
    """Checkpoint payload: the array-valued fields of the TrainState as a
    plain dict (orbax-friendly; ``apply_fn``/``tx`` are code, not state),
    fetched to host numpy. Multi-host replicated arrays come back as this
    host's full local copy (host_local_array), so saving from the primary
    host needs no cross-process coordination."""
    return _fetch_tree(_state_dict(state))


def state_template(state: TrainState):
    """Abstract shape/dtype tree for checkpoint RESTORE targets.

    Restore only needs the tree's structure and leaf shapes/dtypes —
    building the template via :func:`state_to_tree` paid a full
    device->host fetch (plus the packed-concat compile) whose values
    orbax then discarded. ShapeDtypeStructs carry the same information
    with zero transfers."""
    def absify(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        arr = np.asarray(x)
        return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

    return jax.tree_util.tree_map(absify, _state_dict(state))


def _sidecar_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, "trainer_state.json")


def _write_sidecar(ckpt_dir: str, payload: Dict[str, Any]) -> None:
    """Persist loop-level bookkeeping (EarlyStopping best/patience) that
    lives outside the TrainState pytree — atomic write + integrity
    sidecar (robustness/artifacts.py) so a preemption mid-write leaves
    the previous epoch's intact and a later resume can verify what it
    adopts. ``json`` round-trips ±inf (the fresh-stopper ``best``)
    natively."""
    artifacts.atomic_write_artifact(
        _sidecar_path(ckpt_dir), json.dumps(payload), "trainer-state")


def _read_sidecar(ckpt_dir: str) -> Optional[Dict[str, Any]]:
    """None when absent OR corrupt — the orbax step counter is the source
    of truth and a resume without stopper bookkeeping merely resets
    patience (recoverable); a corrupt file is quarantined so the loss is
    loud, counted, and auditable, never silent."""
    path = _sidecar_path(ckpt_dir)
    if not os.path.exists(path):
        return None
    try:
        raw = artifacts.verify_read(path, kind="trainer-state",
                                    require_sidecar=False)
        return json.loads(raw.decode("utf-8"))
    except (artifacts.ArtifactError, UnicodeDecodeError, ValueError) as exc:
        artifacts.quarantine(path, "trainer-state", str(exc))
        return None
    except OSError:
        return None


def _restore_into(state: TrainState, restored) -> TrainState:
    return state.replace(
        step=restored["step"],
        params=restored["params"],
        opt_state=restored["opt_state"],
        batch_stats=restored["batch_stats"],
        dropout_rng=restored["dropout_rng"],
    )
