"""Training subsystem: objective, optimizer, train state/steps, metrics."""

from deepinteract_tpu.training.objective import contact_loss  # noqa: F401
from deepinteract_tpu.training.optim import make_optimizer, OptimConfig  # noqa: F401
from deepinteract_tpu.training.steps import TrainState, create_train_state, train_step, eval_step  # noqa: F401
