"""Contact-prediction objective.

The reference gathers per-pair logits at flattened (i, j) example indices and
applies ``CrossEntropyLoss`` with optional class weights [1, 5]
(``LitGINI.training_step``, deepinteract_modules.py:1770-1799). Its example
tensor enumerates *all* L1 x L2 pairs (``build_examples_tensor``,
deepinteract_utils.py:558-582; the pn-ratio downsampling call is commented
out at :1772), so the loss is exactly a dense masked cross entropy over the
pair map — which is the TPU-native formulation used here. An explicit
example-gather variant is provided for sampled-example workflows.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Reference class weights (deepinteract_modules.py:1781-1787).
DEFAULT_CLASS_WEIGHTS = (1.0, 5.0)


def contact_loss(
    logits: jnp.ndarray,
    contact_map: jnp.ndarray,
    pair_mask: jnp.ndarray,
    weight_classes: bool = False,
    class_weights: Tuple[float, float] = DEFAULT_CLASS_WEIGHTS,
) -> jnp.ndarray:
    """Masked mean cross entropy over the dense pair map.

    logits: [B, L1, L2, 2]; contact_map: [B, L1, L2] int; pair_mask: bool.
    Matches torch ``CrossEntropyLoss`` (mean over examples; with
    ``weight_classes``, weighted mean with per-class weights).
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, contact_map[..., None], axis=-1)[..., 0]
    mask = pair_mask.astype(logits.dtype)
    if weight_classes:
        w = jnp.asarray(class_weights, logits.dtype)[contact_map]
    else:
        w = jnp.ones_like(ll)
    w = w * mask
    return -jnp.sum(w * ll) / jnp.maximum(jnp.sum(w), 1.0)


def example_gather_loss(
    logits: jnp.ndarray,
    examples: jnp.ndarray,
    example_mask: jnp.ndarray,
    weight_classes: bool = False,
    class_weights: Tuple[float, float] = DEFAULT_CLASS_WEIGHTS,
) -> jnp.ndarray:
    """Cross entropy over sampled (i, j, label) examples — the reference's
    flat-index gather form (deepinteract_modules.py:1774-1777).

    logits: [B, L1, L2, 2]; examples: [B, M, 3] int32; example_mask: [B, M].
    """
    i, j, labels = examples[..., 0], examples[..., 1], examples[..., 2]
    batch_ix = jnp.arange(logits.shape[0])[:, None]
    picked = logits[batch_ix, i, j]  # [B, M, 2]
    logp = jax.nn.log_softmax(picked, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    mask = example_mask.astype(logits.dtype)
    if weight_classes:
        w = jnp.asarray(class_weights, logits.dtype)[labels] * mask
    else:
        w = mask
    return -jnp.sum(w * ll) / jnp.maximum(jnp.sum(w), 1.0)


def downsample_examples(
    examples: jnp.ndarray,
    example_mask: jnp.ndarray,
    pn_ratio: float,
    rng: jax.Array,
) -> jnp.ndarray:
    """Static-shape variant of the reference's negative-pair downsampling
    (``LitGINI.downsample_examples``, deepinteract_modules.py:1747-1754):
    keeps all positives and re-weights/masks negatives so that the expected
    kept count is num_pos / pn_ratio, via random thresholding."""
    labels = examples[..., 2]
    pos = (labels == 1) & example_mask
    neg = (labels == 0) & example_mask
    num_pos = jnp.sum(pos, axis=-1, keepdims=True).astype(jnp.float32)
    num_neg = jnp.maximum(jnp.sum(neg, axis=-1, keepdims=True).astype(jnp.float32), 1.0)
    keep_prob = jnp.clip((num_pos / pn_ratio) / num_neg, 0.0, 1.0)
    u = jax.random.uniform(rng, labels.shape)
    keep_neg = neg & (u < keep_prob)
    return pos | keep_neg
