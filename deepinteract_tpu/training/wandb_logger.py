"""Weights & Biases metric writer — the reference's default logger.

The reference constructs a ``WandbLogger`` unless ``--offline``
(``lit_model_train.py:169-177``) and logs scalars/images through
Lightning. Here the Trainer's writer protocol is two methods
(``add_scalar``/``add_image``, training/loop.py:_write_metrics and
_log_viz_images), so W&B support is a thin adapter over ``wandb.log`` —
usable alone or fanned out next to TensorBoard.

``wandb`` is an optional dependency (absent in offline images): creation
degrades to ``None`` with a warning rather than failing the run.
"""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger(__name__)


class WandbWriter:
    """Adapter: Trainer writer protocol -> wandb.log."""

    def __init__(self, project: str, run_name: Optional[str] = None,
                 config: Optional[dict] = None, mode: Optional[str] = None):
        import wandb  # noqa: F811 - optional dependency

        self._wandb = wandb
        kwargs = {"project": project, "config": config or {}}
        if run_name:
            kwargs["name"] = run_name
        if mode:
            kwargs["mode"] = mode  # 'offline' mirrors the reference flag
        self.run = wandb.init(**kwargs)

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._wandb.log({tag: value}, step=step)

    def add_image(self, tag: str, img, step: int, dataformats: str = "HWC") -> None:
        if dataformats == "CHW":  # wandb.Image expects HWC numpy
            img = img.transpose(1, 2, 0)
        self._wandb.log({tag: self._wandb.Image(img)}, step=step)

    def log_checkpoint_artifact(self, ckpt_dir: str,
                                aliases=("best", "latest")) -> None:
        """Upload a checkpoint directory as the run's ``model-<run_id>``
        artifact — the convention Lightning's WandbLogger(log_model=True)
        uses and the reference's test CLI restores by
        (``model-{run_id}:best``, lit_model_test.py:121-124)."""
        artifact = self._wandb.Artifact(f"model-{self.run.id}", type="model")
        artifact.add_dir(ckpt_dir)
        self.run.log_artifact(artifact, aliases=list(aliases))

    def close(self) -> None:
        self.run.finish()


class RegistryWriter:
    """Writer-protocol sink over the process-wide telemetry registry
    (obs/metrics.py): every scalar lands in the
    ``di_train_metric{metric=...}`` gauge, so a co-resident exposition
    (or a test) can read the trainer's latest epoch metrics without any
    external logging backend. Stacks under :class:`FanoutWriter` next to
    wandb/TensorBoard; images and artifacts are not mirrored."""

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        from deepinteract_tpu.obs import metrics as obs_metrics

        obs_metrics.gauge(
            "di_train_metric", "Last logged value of each trainer scalar",
            labelnames=("metric",),
        ).set(float(value), metric=tag)
        obs_metrics.gauge(
            "di_train_last_epoch", "Epoch of the last logged scalar",
        ).set(float(step))

    def add_image(self, tag, img, step, dataformats="HWC") -> None:
        pass  # gauges cannot carry images; wandb/TB sinks handle these


class FanoutWriter:
    """Broadcast writer calls to several writers (e.g. TB + W&B + the
    registry sink, the reference's logger list). ``None`` entries are
    dropped, so a single configured sink sees the identical call
    sequence it would alone."""

    def __init__(self, writers):
        self.writers = [w for w in writers if w is not None]

    def add_scalar(self, tag, value, step):
        for w in self.writers:
            w.add_scalar(tag, value, step)

    def add_image(self, tag, img, step, dataformats="HWC"):
        for w in self.writers:
            w.add_image(tag, img, step, dataformats=dataformats)

    def log_checkpoint_artifact(self, ckpt_dir, aliases=("best", "latest")):
        for w in self.writers:
            if hasattr(w, "log_checkpoint_artifact"):
                w.log_checkpoint_artifact(ckpt_dir, aliases=aliases)

    def close(self):
        for w in self.writers:
            if hasattr(w, "close"):
                w.close()


def download_checkpoint_artifact(project: str, run_id: str,
                                 entity: Optional[str] = None,
                                 alias: str = "best") -> Optional[str]:
    """Download the ``model-<run_id>:<alias>`` checkpoint artifact and
    return its local directory, or None when wandb/network is unavailable
    (offline-degradable, like every other W&B touchpoint here).

    Reference: ``lit_model_test.py:121-130`` restores
    ``{entity}/{project}/model-{run_id}:best`` before evaluating.
    """
    ref = f"model-{run_id}:{alias}"
    if project:
        ref = f"{project}/{ref}"
    if entity:
        ref = f"{entity}/{ref}"
    try:
        import wandb

        return wandb.Api().artifact(ref, type="model").download()
    except ImportError:
        logger.warning("wandb is not installed; cannot restore artifact %s", ref)
        return None
    except Exception as exc:
        logger.warning("artifact restore failed for %s (%s)", ref, exc)
        return None


def make_wandb_writer(project: str, run_name: Optional[str] = None,
                      config: Optional[dict] = None,
                      offline: bool = False) -> Optional[WandbWriter]:
    """WandbWriter or None (+warning) when wandb is unavailable."""
    try:
        return WandbWriter(project, run_name, config,
                           mode="offline" if offline else None)
    except ImportError:
        logger.warning(
            "wandb is not installed; --use_wandb ignored (TensorBoard "
            "logging via --tb_log_dir still works)"
        )
        return None
    except Exception as exc:  # init/network failures must not kill training
        logger.warning("wandb.init failed (%s); continuing without W&B", exc)
        return None
