"""Weights & Biases metric writer — the reference's default logger.

The reference constructs a ``WandbLogger`` unless ``--offline``
(``lit_model_train.py:169-177``) and logs scalars/images through
Lightning. Here the Trainer's writer protocol is two methods
(``add_scalar``/``add_image``, training/loop.py:_write_metrics and
_log_viz_images), so W&B support is a thin adapter over ``wandb.log`` —
usable alone or fanned out next to TensorBoard.

``wandb`` is an optional dependency (absent in offline images): creation
degrades to ``None`` with a warning rather than failing the run.
"""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger(__name__)


class WandbWriter:
    """Adapter: Trainer writer protocol -> wandb.log."""

    def __init__(self, project: str, run_name: Optional[str] = None,
                 config: Optional[dict] = None, mode: Optional[str] = None):
        import wandb  # noqa: F811 - optional dependency

        self._wandb = wandb
        kwargs = {"project": project, "config": config or {}}
        if run_name:
            kwargs["name"] = run_name
        if mode:
            kwargs["mode"] = mode  # 'offline' mirrors the reference flag
        self.run = wandb.init(**kwargs)

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._wandb.log({tag: value}, step=step)

    def add_image(self, tag: str, img, step: int, dataformats: str = "HWC") -> None:
        if dataformats == "CHW":  # wandb.Image expects HWC numpy
            img = img.transpose(1, 2, 0)
        self._wandb.log({tag: self._wandb.Image(img)}, step=step)

    def close(self) -> None:
        self.run.finish()


class FanoutWriter:
    """Broadcast writer calls to several writers (e.g. TB + W&B, the
    reference's logger list)."""

    def __init__(self, writers):
        self.writers = [w for w in writers if w is not None]

    def add_scalar(self, tag, value, step):
        for w in self.writers:
            w.add_scalar(tag, value, step)

    def add_image(self, tag, img, step, dataformats="HWC"):
        for w in self.writers:
            w.add_image(tag, img, step, dataformats=dataformats)

    def close(self):
        for w in self.writers:
            if hasattr(w, "close"):
                w.close()


def make_wandb_writer(project: str, run_name: Optional[str] = None,
                      config: Optional[dict] = None,
                      offline: bool = False) -> Optional[WandbWriter]:
    """WandbWriter or None (+warning) when wandb is unavailable."""
    try:
        return WandbWriter(project, run_name, config,
                           mode="offline" if offline else None)
    except ImportError:
        logger.warning(
            "wandb is not installed; --use_wandb ignored (TensorBoard "
            "logging via --tb_log_dir still works)"
        )
        return None
    except Exception as exc:  # init/network failures must not kill training
        logger.warning("wandb.init failed (%s); continuing without W&B", exc)
        return None
