"""Train/eval steps: pure jittable functions over a flax TrainState.

Replaces PyTorch Lightning's training loop machinery
(``LitGINI.training_step``/``validation_step``, deepinteract_modules.py:
1756-2016) with compact functional steps designed for ``jax.jit`` /
``shard_map``: params + batch stats in one state pytree, dropout rng folded
per step, donated state for in-place HBM updates.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from flax.training import train_state

from deepinteract_tpu.data.graph import PairedComplex
from deepinteract_tpu.models.model import DeepInteract, ModelConfig
from deepinteract_tpu.training.objective import contact_loss
from deepinteract_tpu.training.optim import OptimConfig, make_optimizer


class TrainState(train_state.TrainState):
    batch_stats: Any = None
    dropout_rng: jax.Array = None
    # Consecutive non-finite (skipped) optimizer steps — maintained on
    # device by the guarded step (robustness/guards.py); None when the
    # guard is unused. Deliberately transient: it is NOT part of the
    # checkpoint payload (training/loop.py _state_dict), so resume resets
    # it to zero and old checkpoints stay restorable.
    bad_steps: Any = None


def create_train_state(
    model: DeepInteract,
    example: PairedComplex,
    seed: int = 42,
    optim_cfg: Optional[OptimConfig] = None,
    frozen_prefixes: tuple = (),
) -> TrainState:
    """Initialize parameters and optimizer state (reference seed 42 default,
    deepinteract_utils.py:1118-1122). ``frozen_prefixes`` freezes top-level
    param subtrees (fine-tune mode, deepinteract_modules.py:1546-1557)."""
    root = jax.random.PRNGKey(seed)
    params_rng, dropout_rng = jax.random.split(root)
    # jit the init: eager flax init dispatches thousands of individual ops,
    # which through a remote-device tunnel (~tens of ms per dispatch) costs
    # minutes; one compiled executable costs one compile (measured, r5
    # bench rehearsal). Shape-identical re-inits also hit the jit cache.
    init_fn = jax.jit(model.init, static_argnames=("train",))
    variables = init_fn(
        {"params": params_rng, "dropout": dropout_rng},
        example.graph1,
        example.graph2,
        train=False,
    )
    return TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=make_optimizer(optim_cfg, frozen_prefixes=frozen_prefixes),
        batch_stats=variables.get("batch_stats", {}),
        # di: allow[prng-key-reuse] init ran train=False (dropout stream unsampled); splitting here would shift every historical dropout sequence
        dropout_rng=dropout_rng,
        bad_steps=jnp.zeros((), jnp.int32),
    )


def loss_and_updates(params, state: TrainState, batch: PairedComplex, weight_classes: bool,
                     dropout_rng):
    outputs, mutated = state.apply_fn(
        {"params": params, "batch_stats": state.batch_stats},
        batch.graph1,
        batch.graph2,
        train=True,
        rngs={"dropout": dropout_rng},
        mutable=["batch_stats"],
    )
    loss = contact_loss(outputs, batch.contact_map, batch.pair_mask, weight_classes)
    return loss, mutated


def train_step(
    state: TrainState,
    batch: PairedComplex,
    weight_classes: bool = False,
    axis_name: Optional[str] = None,
    guard: bool = False,
) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
    """One optimization step. When ``axis_name`` is set (inside pmap /
    shard_map), gradients and metrics are psum-averaged across the data axis
    — the XLA-collective equivalent of DDP's gradient all-reduce
    (SURVEY.md §2.6).

    With ``guard=True`` the update is applied only when loss and gradients
    are finite (robustness/guards.py): bad steps leave the state untouched
    except for the on-device consecutive-skip counter, and the metrics gain
    ``bad_step`` (this step skipped, 0/1) and ``bad_steps`` (consecutive
    skips after this step). The guard decision is computed AFTER the
    cross-host gradient average, so every host branches identically."""
    dropout_rng = jax.random.fold_in(state.dropout_rng, state.step)
    grad_fn = jax.value_and_grad(loss_and_updates, has_aux=True)
    (loss, mutated), grads = grad_fn(state.params, state, batch, weight_classes, dropout_rng)
    if axis_name is not None:
        grads = jax.lax.pmean(grads, axis_name)
        loss = jax.lax.pmean(loss, axis_name)
    batch_stats = mutated.get("batch_stats", state.batch_stats)
    metrics = {"loss": loss, "grad_norm": optax.global_norm(grads)}
    if guard:
        from deepinteract_tpu.robustness.guards import apply_guarded_update

        new_state, finite = apply_guarded_update(state, grads, loss, batch_stats)
        metrics["bad_step"] = 1.0 - finite.astype(jnp.float32)
        metrics["bad_steps"] = new_state.bad_steps.astype(jnp.float32)
    else:
        new_state = state.apply_gradients(grads=grads, batch_stats=batch_stats)
    return new_state, metrics


def multi_train_step(
    state: TrainState,
    batches: PairedComplex,
    weight_classes: bool = False,
    axis_name: Optional[str] = None,
    guard: bool = False,
) -> Tuple[TrainState, Dict[str, jnp.ndarray]]:
    """K optimization steps in ONE dispatch: ``lax.scan`` over batches
    stacked on a leading axis ([K, B, ...] per leaf).

    Motivation (TPU-native, no reference equivalent): host dispatch cost
    scales with the number of result buffers — on this TPU tunnel, merely
    returning the ~3.4k-leaf train state costs ~25 ms per call, an order of
    magnitude more than the device compute of a train step. Scanning K
    steps keeps the state on device across all K updates and pays the
    round-trip once, so per-step overhead drops ~K-fold. Semantics are
    identical to K sequential ``train_step`` calls (parity-tested).

    Returns (final state, metrics with a leading [K] axis).
    """

    def body(s, b):
        s, m = train_step(s, b, weight_classes=weight_classes,
                          axis_name=axis_name, guard=guard)
        return s, m

    return jax.lax.scan(body, state, batches)


def stack_microbatches(batches):
    """Stack same-shape PairedComplex batches along a new leading axis for
    :func:`multi_train_step`."""
    import numpy as np

    return jax.tree_util.tree_map(lambda *xs: np.stack(xs, axis=0), *batches)


def pack_tree(tree):
    """Host pytree -> ``(buffers, spec)`` for single-transfer dispatch.

    Host->device placement of a jit call's arguments pays a fixed
    transport round trip PER LEAF on remote-dispatch backends (the axon
    tunnel; same O(leaves) disease the checkpoint fetch had
    device->host, training/loop.py:_packed_device_get). Packing the
    ~20-leaf stacked batch into ONE contiguous host buffer per dtype
    makes the upload O(dtypes); :func:`unpack_tree` re-slices it inside
    the jitted step (static offsets — XLA folds the slices/reshapes into
    the consumers, so device math is unchanged).

    ``spec`` is hashable: pass it as a static jit argument."""
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    by_dtype: Dict[str, list] = {}
    arrs = [np.asarray(x) for x in leaves]
    for i, a in enumerate(arrs):
        by_dtype.setdefault(a.dtype.name, []).append(i)
    buffers = {}
    info = [None] * len(leaves)
    for dname, idxs in by_dtype.items():
        parts, off = [], 0
        for i in idxs:
            a = arrs[i]
            parts.append(a.ravel())
            info[i] = (dname, off, a.shape)
            off += a.size
        buffers[dname] = np.concatenate(parts)
    return buffers, (treedef, tuple(info))


def unpack_tree(buffers, spec):
    """Inverse of :func:`pack_tree`, traceable under jit (static spec)."""
    treedef, info = spec
    leaves = []
    for dname, off, shape in info:
        n = 1
        for s in shape:
            n *= s
        leaves.append(buffers[dname][off : off + n].reshape(shape))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def eval_step(
    state: TrainState, batch: PairedComplex, weight_classes: bool = False
) -> Dict[str, jnp.ndarray]:
    """Forward pass + loss + per-pair probabilities (no param update)."""
    logits = state.apply_fn(
        {"params": state.params, "batch_stats": state.batch_stats},
        batch.graph1,
        batch.graph2,
        train=False,
    )
    loss = contact_loss(logits, batch.contact_map, batch.pair_mask, weight_classes)
    probs = jax.nn.softmax(logits, axis=-1)
    return {"loss": loss, "probs": probs, "logits": logits}


def multi_eval_step(
    state: TrainState, batches: PairedComplex, weight_classes: bool = False
) -> Dict[str, jnp.ndarray]:
    """K forward passes in ONE dispatch (``lax.scan`` over batches stacked
    [K, B, ...]); the eval twin of :func:`multi_train_step`.

    Motivation: ``Trainer.evaluate`` is dispatch-bound at batch 1 — the
    same ~25 ms host round-trip the train path scans away dominates a
    DIPS-Plus validation epoch (3,548 complexes). Scanning K evals per
    dispatch (on top of batched eval loading) cuts dispatches K-fold.
    Outputs carry a leading [K] axis; state is read-only.
    """

    def body(carry, b):
        return carry, eval_step(state, b, weight_classes=weight_classes)

    _, outs = jax.lax.scan(body, 0, batches)
    return outs
