"""Torch/Lightning checkpoint importer: reference state dicts -> flax trees.

The reference ships trained Lightning checkpoints (``README.md:249-253``,
Zenodo 6671582: ``LitGINI-GeoTran-DilResNet.ckpt``) whose ``state_dict``
follows the torch module layout of ``LitGINI``
(``deepinteract_modules.py:1478-1658``):

* ``node_in_embedding`` — input Linear (:1541-1542)
* ``gnn_module.0`` — one ``DGLGeometricTransformer`` (:1595-1625) holding
  ``init_edge_module`` (:128-264) and ``gt_block.{i}`` layers (:500-951)
* ``interact_module`` — ``ResNet2DInputWithOptAttention`` (:1155-1248)

This module maps those tensors onto our flax tree. Transform rules:

* ``nn.Linear.weight`` ``[out, in]``  -> ``Dense.kernel``  ``[in, out]`` (transpose)
* ``nn.Conv2d.weight`` ``[O, I, kh, kw]`` -> ``nn.Conv.kernel`` ``[kh, kw, I, O]``
* ``nn.Embedding.weight``              -> ``Embed.embedding`` (as-is)
* ``BatchNorm1d``: ``weight/bias`` -> params ``scale/bias``;
  ``running_mean/running_var`` -> ``batch_stats`` ``mean/var``;
  ``num_batches_tracked`` dropped.
* ``InstanceNorm2d``/``LayerNorm``: ``weight/bias`` -> ``scale/bias``.

Layout facts that make the mapping exact (verified against the reference):

* Q/K/V are single ``[C, C]`` Linears viewed as ``[heads, C/heads]``
  head-major (``deepinteract_modules.py:48-51,63-66``); our
  ``reshape(b, n, h, d)`` uses the identical memory order, so **no per-head
  split or permutation is required** — a plain transpose suffices.
* ``construct_interact_tensor`` (``deepinteract_utils.py:158-172``)
  concatenates chain-1 channels then chain-2 channels along dim 1
  (``torch.cat((repeat(x_a), repeat(x_b)), dim=1)``); our
  :func:`~deepinteract_tpu.models.interaction.interaction_tensor` produces
  the same ``[feats1 | feats2]`` channel order in NHWC, so the decoder's
  first conv needs **no input-channel permutation** either.
* The conformation ``ResBlock`` registers ONE norm object at ModuleList
  indices 1, 4 and 7 (``deepinteract_modules.py:468-479``); torch emits
  duplicate state-dict entries for every alias. We read index 1 and verify
  indices 4/7 are byte-identical (they share storage in a real checkpoint).

Keys that carry no weights are dropped: ``num_batches_tracked``, the
regional attention's constant ``stretch_layer.weight``
(``deepinteract_modules.py:1138-1141``), and any torchmetrics buffers.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Tensor transforms (torch layout -> flax layout)
# ---------------------------------------------------------------------------


def _t_linear(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.T)


def _t_conv(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def _t_id(w: np.ndarray) -> np.ndarray:
    return np.asarray(w)


# Inverses, used to synthesize reference-layout state dicts in tests.
_INVERSE = {_t_linear: _t_linear, _t_conv: lambda w: np.transpose(w, (3, 2, 0, 1)),
            _t_id: _t_id}


@dataclasses.dataclass(frozen=True)
class _Rule:
    """One flax leaf's source: reference key, layout transform, and any
    duplicate reference keys that alias the same tensor (shared norms).

    ``stack > 0`` marks a scanned-decoder leaf (``scan_chunks``): ``ref_key``
    is then a template containing ``{i}`` and the flax leaf is the
    [stack, ...] stack of the ``stack`` per-chunk reference tensors."""

    ref_key: str
    transform: Callable[[np.ndarray], np.ndarray]
    aliases: Tuple[str, ...] = ()
    stack: int = 0


# ---------------------------------------------------------------------------
# Path mapping
# ---------------------------------------------------------------------------

_NORM_PARAM = {"scale": "weight", "bias": "bias"}
_NORM_STAT = {"mean": "running_mean", "var": "running_var"}

# MLP ModuleList: [Linear, act, dropout, Linear] (deepinteract_modules.py:
# 628-634) — trainables sit at indices 0 and 3.
_MLP_INDEX = {"GODense_0": 0, "GODense_1": 3}
# ResBlock ModuleList: [Lin, norm, act] x3 (:468-479) — Linears at 0/3/6,
# the shared norm object at 1 (aliased at 4 and 7).
_RESBLOCK_LINEAR = {"linear_0": 0, "linear_1": 3, "linear_2": 6}

IGNORED_REF_KEY_PATTERNS = (
    r"\.num_batches_tracked$",
    r"\.stretch_layer\.weight$",  # constant window-unfold weight (:1138-1141)
    r"^(train|val|test)_(acc|prec|recall|auroc|auprc|f1)\.",  # torchmetrics
    r"^loss_fn\.",
)


def _norm_leaf(ref_module: str, leaf: str, collection: str, aliases=()) -> _Rule:
    table = _NORM_PARAM if collection == "params" else _NORM_STAT
    return _Rule(f"{ref_module}.{table[leaf]}", _t_id,
                 tuple(f"{a}.{table[leaf]}" for a in aliases))


def _dense_leaf(ref_module: str, leaf: str) -> _Rule:
    if leaf == "kernel":
        return _Rule(f"{ref_module}.weight", _t_linear)
    return _Rule(f"{ref_module}.bias", _t_id)


def _conv_leaf(ref_module: str, leaf: str) -> _Rule:
    if leaf == "kernel":
        return _Rule(f"{ref_module}.weight", _t_conv)
    return _Rule(f"{ref_module}.bias", _t_id)


def _map_resblock(base: str, rest: Tuple[str, ...], collection: str) -> _Rule:
    """``{pre,post}_res_block_{j}`` -> ``{pre,post}_res_blocks.{j}.res_block.*``."""
    kind, j = rest[0].rsplit("_", 1)  # 'pre_res_block', '0'
    blocks = "pre_res_blocks" if kind.startswith("pre") else "post_res_blocks"
    child = rest[1]
    leaf = rest[-1]
    prefix = f"{base}.{blocks}.{j}.res_block"
    if child == "shared_norm":
        return _norm_leaf(f"{prefix}.1", leaf, collection,
                          aliases=(f"{prefix}.4", f"{prefix}.7"))
    return _dense_leaf(f"{prefix}.{_RESBLOCK_LINEAR[child]}", leaf)


def _map_gt_layer(idx: int, rest: Tuple[str, ...], collection: str,
                  norm_type: str) -> _Rule:
    base = f"gnn_module.0.gt_block.{idx}"
    sub = rest[0]
    leaf = rest[-1]
    norm_prefix = "layer_norm" if norm_type == "layer" else "batch_norm"
    if sub == "conformation_module":
        child = rest[1]
        if child.startswith(("pre_res_block_", "post_res_block_")):
            return _map_resblock(f"{base}.conformation_module", rest[1:], collection)
        if child == "linear":  # PlainEdgeModule (disable_geometric_mode)
            return _dense_leaf(f"{base}.conformation_module", leaf)
        return _dense_leaf(f"{base}.conformation_module.{child}", leaf)
    if sub.startswith(("norm1_", "norm2_")):
        which, what = sub.split("_")  # norm1, node|edge
        n = which[-1]
        return _norm_leaf(f"{base}.{norm_prefix}{n}_{what}_feats", leaf, collection)
    if sub == "mha":
        return _dense_leaf(f"{base}.mha_module.{rest[1]}", leaf)
    if sub == "O_node":
        return _dense_leaf(f"{base}.O_node_feats", leaf)
    if sub == "O_edge":
        return _dense_leaf(f"{base}.O_edge_feats", leaf)
    if sub in ("node_mlp", "edge_mlp"):
        mlp = "node_feats_MLP" if sub == "node_mlp" else "edge_feats_MLP"
        return _dense_leaf(f"{base}.{mlp}.{_MLP_INDEX[rest[1]]}", leaf)
    raise KeyError(f"unmapped GT-layer path: {sub}/{'/'.join(rest)}")


def _unit_rule(stem: str, unit: str, tail: Tuple[str, ...], leaf: str,
               stack: int = 0) -> _Rule:
    """Map one bottleneck-block sub-unit (conv/inorm/se) under ``stem``."""
    if unit.startswith("conv2d_"):
        rule = _conv_leaf(f"{stem}_{unit}", leaf)
    elif unit.startswith("inorm_"):
        rule = _norm_leaf(f"{stem}_{unit}", leaf, "params")
    elif unit == "se_block":
        lin = {"Dense_0": "linear1", "Dense_1": "linear2"}[tail[0]]
        rule = _dense_leaf(f"{stem}_se_block.{lin}", leaf)
    else:
        raise KeyError(f"unmapped block unit: {stem}/{unit}")
    return dataclasses.replace(rule, stack=stack) if stack else rule


def _map_decoder(rest: Tuple[str, ...], num_chunks: int = 14) -> _Rule:
    base = "interact_module"
    sub = rest[0]
    leaf = rest[-1]
    if sub in ("conv2d_1", "phase2_conv"):
        return _conv_leaf(f"{base}.{sub}", leaf)
    if sub == "inorm_1":
        return _norm_leaf(f"{base}.inorm_1", leaf, "params")
    if sub in ("mha2d_1", "mha2d_2"):
        n = sub[-1]
        return _conv_leaf(f"{base}.MHA2D_{n}.{rest[1]}", leaf)
    if sub in ("base_resnet", "phase2_resnet"):
        # ResNet submodules are name-mangled with the constructor's
        # module_name: 'base_resnet' / 'bin_resnet' (:1187-1201).
        mod = "base_resnet" if sub == "base_resnet" else "bin_resnet"
        child = rest[1]
        if child == "init_proj":
            prefix = f"{base}.{sub}.resnet_{mod}_init_proj"
            return _conv_leaf(prefix, leaf)
        if child == "chunks":
            # Scanned layout (DecoderConfig.scan_chunks): one flax leaf
            # stacks the num_chunks per-chunk reference tensors; '{i}' in
            # the template is the chunk index.
            d = rest[2].rsplit("d", 1)[1]  # block_d{d}
            stem = f"{base}.{sub}.resnet_{mod}_{{i}}_{d}"
            return _unit_rule(stem, rest[3], rest[4:], leaf, stack=num_chunks)
        if child.startswith("extra_block_"):
            i = child.rsplit("_", 1)[1]
            stem = f"{base}.{sub}.resnet_{mod}_extra{i}"
        else:  # block_{i}_{d}
            _, i, d = child.split("_")
            stem = f"{base}.{sub}.resnet_{mod}_{i}_{d}"
        return _unit_rule(stem, rest[2], rest[3:], leaf)
    raise KeyError(f"unmapped decoder path: {'/'.join(rest)}")


def map_flax_path(collection: str, path: Tuple[str, ...], num_layers: int,
                  norm_type: str = "batch", num_chunks: int = 14) -> _Rule:
    """Map one flax leaf path (without the collection prefix) to its
    reference state-dict source."""
    head = path[0]
    if head == "node_in_embedding":
        return _dense_leaf("node_in_embedding", path[-1])
    if head == "gnn":
        sub = path[1]
        if sub == "init_edge_module":
            base = "gnn_module.0.init_edge_module"
            if path[2] == "node_embedding":
                return _Rule(f"{base}.node_embedding.weight", _t_id)
            if path[2] == "linear":  # PlainEdgeModule in geometric-off mode
                return _dense_leaf(base, path[-1])
            return _dense_leaf(f"{base}.{path[2]}", path[-1])
        if sub.startswith("gcn_bias_"):
            i = sub.rsplit("_", 1)[1]
            return _Rule(f"gnn_module.{i}.bias", _t_id)
        if sub.startswith("gcn_"):
            # DGL GraphConv stores weight as [in, out] and right-multiplies
            # (dgl GraphConv matmul convention) — no transpose.
            i = sub.rsplit("_", 1)[1]
            return _Rule(f"gnn_module.{i}.weight", _t_id)
        if sub == "final_gt_layer":
            return _map_gt_layer(num_layers - 1, path[2:], collection, norm_type)
        if sub.startswith("gt_layer_"):
            idx = int(sub.rsplit("_", 1)[1])
            return _map_gt_layer(idx, path[2:], collection, norm_type)
    if head == "decoder":
        return _map_decoder(path[1:], num_chunks)
    raise KeyError(f"unmapped flax path: {collection}/{'/'.join(path)}")


# ---------------------------------------------------------------------------
# Tree walking
# ---------------------------------------------------------------------------


def _iter_leaf_paths(tree: Mapping[str, Any], prefix: Tuple[str, ...] = ()):
    for k, v in tree.items():
        if isinstance(v, Mapping):
            yield from _iter_leaf_paths(v, prefix + (str(k),))
        else:
            yield prefix + (str(k),), v


def _set_leaf(tree: Dict[str, Any], path: Tuple[str, ...], value) -> None:
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def abstract_variables(model_cfg, example_complex) -> Dict[str, Any]:
    """Shape-only init of the model's variable tree (no compile/FLOPs)."""
    import jax

    from deepinteract_tpu.models.model import DeepInteract

    model = DeepInteract(model_cfg)
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), example_complex.graph1,
                           example_complex.graph2, train=False)
    )
    return dict(shapes)  # FrozenDict/dict both satisfy the Mapping walks below


@dataclasses.dataclass
class ImportReport:
    consumed: List[str]
    ignored: List[str]
    unconsumed: List[str]

    def summary(self) -> str:
        return (f"imported {len(self.consumed)} tensors "
                f"({len(self.ignored)} ignored, {len(self.unconsumed)} unconsumed)")


def _clean_key(key: str) -> str:
    # Lightning sometimes nests the network under 'model.' — strip it.
    return key[len("model."):] if key.startswith("model.") else key


def convert_state_dict(
    ref_sd: Mapping[str, np.ndarray],
    model_cfg,
    example_complex,
    strict: bool = True,
) -> Tuple[Dict[str, Any], ImportReport]:
    """Convert a reference-layout state dict into ``{"params": ...,
    "batch_stats": ...}`` matching our flax tree, validating shapes and
    accounting for every reference key."""
    sd = {_clean_key(k): np.asarray(v) for k, v in ref_sd.items()}
    abstract = abstract_variables(model_cfg, example_complex)
    num_layers = model_cfg.gnn.num_layers
    norm_type = model_cfg.gnn.norm_type

    num_chunks = model_cfg.decoder.num_chunks

    out: Dict[str, Any] = {}
    consumed: Dict[str, str] = {}
    missing: List[str] = []
    for collection in ("params", "batch_stats"):
        for path, leaf in _iter_leaf_paths(abstract.get(collection, {})):
            rule = map_flax_path(collection, path, num_layers, norm_type,
                                 num_chunks)
            if rule.stack:
                # Scanned decoder leaf: stack the per-chunk reference tensors.
                keys = [rule.ref_key.format(i=i) for i in range(rule.stack)]
                absent = [k for k in keys if k not in sd]
                if absent:
                    missing.extend(absent)
                    continue
                value = np.stack([rule.transform(sd[k]) for k in keys])
                if tuple(value.shape) != tuple(leaf.shape):
                    raise ValueError(
                        f"shape mismatch for stacked {rule.ref_key} -> "
                        f"{collection}/{'/'.join(path)}: got {value.shape}, "
                        f"expected {tuple(leaf.shape)}"
                    )
                _set_leaf(out, (collection,) + path, value.astype(np.float32))
                for k in keys:
                    consumed[k] = "/".join(path)
                continue
            if rule.ref_key not in sd:
                missing.append(rule.ref_key)
                continue
            value = rule.transform(sd[rule.ref_key])
            if tuple(value.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"shape mismatch for {rule.ref_key} -> "
                    f"{collection}/{'/'.join(path)}: got {value.shape}, "
                    f"expected {tuple(leaf.shape)}"
                )
            _set_leaf(out, (collection,) + path, value.astype(np.float32))
            consumed[rule.ref_key] = "/".join(path)
            for alias in rule.aliases:
                if alias in sd:
                    if not np.array_equal(sd[alias], sd[rule.ref_key]):
                        raise ValueError(
                            f"shared-norm alias {alias} differs from "
                            f"{rule.ref_key}; checkpoint is not reference-shaped"
                        )
                    consumed[alias] = consumed[rule.ref_key]
    if missing and strict:
        raise KeyError(
            f"{len(missing)} expected reference keys absent, e.g. {missing[:5]}"
        )

    ignored, unconsumed = [], []
    for key in sd:
        if key in consumed:
            continue
        if any(re.search(p, key) for p in IGNORED_REF_KEY_PATTERNS):
            ignored.append(key)
        else:
            unconsumed.append(key)
    if unconsumed and strict:
        raise KeyError(
            f"{len(unconsumed)} reference keys not mapped, e.g. {sorted(unconsumed)[:5]}"
        )
    out.setdefault("params", {})
    out.setdefault("batch_stats", {})
    return out, ImportReport(sorted(consumed), sorted(ignored), sorted(unconsumed))


def synthesize_reference_state_dict(
    model_cfg, example_complex, seed: int = 0,
) -> Dict[str, np.ndarray]:
    """Build a random state dict in the exact reference layout (names,
    torch-convention shapes, shared-norm duplicate entries, decoy buffers).
    Used by tests in place of the real Zenodo checkpoint, which this
    offline image cannot download."""
    rng = np.random.default_rng(seed)
    abstract = abstract_variables(model_cfg, example_complex)
    sd: Dict[str, np.ndarray] = {}
    for collection in ("params", "batch_stats"):
        for path, leaf in _iter_leaf_paths(abstract.get(collection, {})):
            rule = map_flax_path(collection, path, model_cfg.gnn.num_layers,
                                 model_cfg.gnn.norm_type,
                                 model_cfg.decoder.num_chunks)
            if rule.ref_key in sd:
                continue  # shared (aliased) tensors emitted once below
            flax_value = rng.standard_normal(leaf.shape).astype(np.float32)
            if len(leaf.shape) >= 2:
                # realistic magnitude (fan-in scaled) so a forward pass with
                # these synthetic weights stays finite through 60+ layers.
                # The stacked chunk axis is not part of the fan-in.
                fan_shape = leaf.shape[1:-1] if rule.stack else leaf.shape[:-1]
                fan_in = int(np.prod(fan_shape))
                flax_value *= 1.0 / np.sqrt(max(fan_in, 1))
            if path[-1] == "var":  # running variances must be positive
                flax_value = np.abs(flax_value) + 0.5
            if rule.stack:
                # One reference tensor per chunk (the flax leaf's leading
                # axis).
                for i in range(rule.stack):
                    sd[rule.ref_key.format(i=i)] = np.ascontiguousarray(
                        _INVERSE[rule.transform](flax_value[i])
                    )
                continue
            ref_value = _INVERSE[rule.transform](flax_value)
            sd[rule.ref_key] = np.ascontiguousarray(ref_value)
            for alias in rule.aliases:
                sd[alias] = sd[rule.ref_key]
            if rule.ref_key.endswith("running_var"):
                # BatchNorm ships a counter buffer alongside its stats.
                sd[rule.ref_key.replace("running_var", "num_batches_tracked")] = (
                    np.asarray(7, dtype=np.int64)
                )
    return sd
