"""Learning-rate range finder (reference: the optional
``trainer.tuner.lr_find`` step, lit_model_train.py:121-127, gated by
``--find_lr``).

Sweeps the learning rate geometrically from ``min_lr`` to ``max_lr`` over
``num_steps`` train steps on a throwaway copy of the model state, records
the loss per step, stops early on divergence (loss > 4x the running best,
Lightning's rule), and suggests the lr at the steepest descent of the
smoothed curve (Lightning's ``suggestion()``).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import jax
import numpy as np
import optax

from deepinteract_tpu.data.graph import PairedComplex
from deepinteract_tpu.models.model import DeepInteract
from deepinteract_tpu.training.optim import OptimConfig
from deepinteract_tpu.training.steps import TrainState, train_step


def lr_find(
    model: DeepInteract,
    example: PairedComplex,
    data: Iterable[PairedComplex],
    optim_cfg: Optional[OptimConfig] = None,
    min_lr: float = 1e-6,
    max_lr: float = 1.0,
    num_steps: int = 30,
    seed: int = 42,
    weight_classes: bool = False,
) -> Tuple[float, List[Tuple[float, float]]]:
    """Returns (suggested_lr, [(lr, loss), ...]).

    ``data`` is cycled if shorter than ``num_steps``. The sweep state is
    discarded; callers re-init training with the suggestion.
    """
    cfg = optim_cfg or OptimConfig()
    ratio = max_lr / min_lr

    def schedule(step):
        return min_lr * ratio ** (step / max(num_steps - 1, 1))

    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        optax.adamw(learning_rate=schedule, weight_decay=cfg.weight_decay),
    )

    root = jax.random.PRNGKey(seed)
    params_rng, dropout_rng = jax.random.split(root)
    variables = model.init(
        {"params": params_rng, "dropout": dropout_rng},
        example.graph1, example.graph2, train=False,
    )
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables.get("batch_stats", {}),
        # di: allow[prng-key-reuse] init ran train=False (dropout stream unsampled); the probe state mirrors create_train_state
        dropout_rng=dropout_rng,
    )

    step_fn = jax.jit(lambda s, b: train_step(s, b, weight_classes=weight_classes))

    batches = list(data)
    history: List[Tuple[float, float]] = []
    best = np.inf
    for i in range(num_steps):
        batch = batches[i % len(batches)]
        lr = float(schedule(i))
        state, metrics = step_fn(state, batch)
        loss = float(metrics["loss"])
        history.append((lr, loss))
        if np.isfinite(loss):
            best = min(best, loss)
        if not np.isfinite(loss) or loss > 4.0 * best:
            break  # diverged (Lightning early-stop rule)

    return suggest_lr(history), history


def suggest_lr(history: List[Tuple[float, float]]) -> float:
    """Steepest negative gradient of the smoothed loss-vs-log(lr) curve."""
    if len(history) < 4:
        return history[len(history) // 2][0] if history else 1e-3
    lrs = np.array([h[0] for h in history])
    losses = np.array([h[1] for h in history])
    finite = np.isfinite(losses)
    lrs, losses = lrs[finite], losses[finite]
    if len(losses) < 4:
        return 1e-3
    # Exponential smoothing, then finite-difference gradient in log-lr.
    smoothed = np.empty_like(losses)
    acc = losses[0]
    for i, l in enumerate(losses):
        acc = 0.7 * acc + 0.3 * l
        smoothed[i] = acc
    grads = np.gradient(smoothed, np.log(lrs))
    return float(lrs[int(np.argmin(grads))])
