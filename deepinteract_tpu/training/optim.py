"""Optimizer: AdamW + cosine-annealing warm restarts + gradient clipping.

Reference: ``LitGINI.configure_optimizers`` (deepinteract_modules.py:2189-
2198) — AdamW(lr=1e-3, weight_decay=1e-2) with
``CosineAnnealingWarmRestarts(T_0=10)`` (epoch-granular restarts), plus
Lightning-level grad clipping by norm 0.5 and optional gradient accumulation
(deepinteract_utils.py:1097-1099).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import optax


@dataclasses.dataclass(frozen=True)
class OptimConfig:
    lr: float = 1e-3
    weight_decay: float = 1e-2
    grad_clip_norm: float = 0.5
    t0_epochs: int = 10  # first cosine restart period, in epochs
    t_mult: int = 1  # torch default T_mult=1: equal-length restart cycles
    eta_min: float = 0.0
    steps_per_epoch: int = 1000
    num_epochs: int = 50
    accumulate_steps: int = 1


def cosine_warm_restarts(cfg: OptimConfig) -> optax.Schedule:
    """CosineAnnealingWarmRestarts as an optax schedule (step-granular)."""
    cycles = []
    total = cfg.num_epochs * cfg.steps_per_epoch
    period = cfg.t0_epochs * cfg.steps_per_epoch
    while sum(cycles) < total:
        cycles.append(period)
        period *= cfg.t_mult if cfg.t_mult > 1 else 1
    schedules = [
        optax.cosine_decay_schedule(cfg.lr, decay_steps=c, alpha=cfg.eta_min / cfg.lr)
        for c in cycles
    ]
    boundaries = []
    acc = 0
    for c in cycles[:-1]:
        acc += c
        boundaries.append(acc)
    return optax.join_schedules(schedules, boundaries)


def make_optimizer(
    cfg: Optional[OptimConfig] = None,
    frozen_prefixes: tuple = (),
) -> optax.GradientTransformation:
    """``frozen_prefixes`` names top-level param subtrees whose updates are
    zeroed — the fine-tune mode that loads a checkpoint and freezes the
    interaction module (reference ``deepinteract_modules.py:1546-1557``);
    pass ``("decoder",)`` for reference behavior."""
    cfg = cfg or OptimConfig()
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm),
        optax.adamw(
            learning_rate=cosine_warm_restarts(cfg),
            b1=0.9,
            b2=0.999,
            eps=1e-8,
            weight_decay=cfg.weight_decay,
        ),
    )
    if frozen_prefixes:
        frozen = tuple(frozen_prefixes)

        def labels(params):
            import jax

            def label_subtree(prefix, subtree):
                tag = "frozen" if prefix in frozen else "train"
                return jax.tree_util.tree_map(lambda _: tag, subtree)

            return {k: label_subtree(k, v) for k, v in params.items()}

        tx = optax.multi_transform({"train": tx, "frozen": optax.set_to_zero()}, labels)
    if cfg.accumulate_steps > 1:
        tx = optax.MultiSteps(tx, every_k_schedule=cfg.accumulate_steps)
    return tx
