"""Per-complex evaluation metrics + median aggregation + CSV export.

Reference semantics reproduced exactly:

* top-k precision/recall over pairs sorted by positive-class probability
  (``deepinteract_utils.py:977-995``): prec = (#true in top k) / k,
  recall = (#true in top k) / (#positives).
* The k grid {10, L//10, L//5} (precision) and {L, L//2, L//5} (recall),
  where **L = n1 + n2 during validation** (``deepinteract_modules.py:1946``)
  but **L = min(n1, n2) at test time** (``:2045``) — a reference discrepancy
  that is part of the published-metric contract, so we keep it.
* Binary metrics follow torchmetrics' multiclass ``average=None`` with the
  class-1 slot selected (``deepinteract_modules.py:1563-1579``): per-class
  "accuracy" is therefore the class-1 recall (a torchmetrics quirk the
  reference inherits), precision/recall/F1 are the usual class-1 one-vs-rest
  definitions, AUROC is one-vs-rest on the class-1 probability, and AUPRC is
  class-1 average precision. Predictions are thresholded at
  ``pos_prob_threshold`` (default 0.5, ``deepinteract_modules.py:1483``).
* Epoch aggregation is the **median over complexes** after a cross-device
  all-gather (``deepinteract_modules.py:1984-2016,2103-2165``); degenerate
  complexes (metrics undefined, e.g. AUROC with no negatives) contribute NaN
  and are skipped via nanmedian.
* Per-target CSV columns match ``test_epoch_end``
  (``deepinteract_modules.py:2130-2145``).

All of this runs on host (numpy): per-complex sorting of ~64K pairs is not
worth a device round-trip, and the reference likewise computes these on
unbatched per-complex tensors.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import numpy as np

from deepinteract_tpu.robustness import artifacts


def top_k_prec(sorted_indices: np.ndarray, labels: np.ndarray, k: int) -> float:
    """Reference ``calculate_top_k_prec`` (deepinteract_utils.py:977-984).
    Guard: the reference divides by k and would crash on k == 0 (chains
    shorter than 10 residues at L//10); we clamp k to 1."""
    k = max(int(k), 1)
    return float(labels[sorted_indices[:k]].sum()) / k


def top_k_recall(sorted_indices: np.ndarray, labels: np.ndarray, k: int) -> float:
    """Reference ``calculate_top_k_recall`` (deepinteract_utils.py:987-995).
    NaN when the complex has no positive labels (reference would divide by
    zero); skipped by nanmedian at aggregation."""
    k = max(int(k), 1)
    num_pos = float(labels.sum())
    if num_pos == 0:
        return float("nan")
    return float(labels[sorted_indices[:k]].sum()) / num_pos


def topk_suite(pos_probs: np.ndarray, labels: np.ndarray, l: int) -> Dict[str, float]:
    """The six top-k metrics over one complex's flattened pair list."""
    order = np.argsort(-pos_probs, kind="stable")
    return {
        "top_10_prec": top_k_prec(order, labels, 10),
        "top_l_by_10_prec": top_k_prec(order, labels, l // 10),
        "top_l_by_5_prec": top_k_prec(order, labels, l // 5),
        "top_l_recall": top_k_recall(order, labels, l),
        "top_l_by_2_recall": top_k_recall(order, labels, l // 2),
        "top_l_by_5_recall": top_k_recall(order, labels, l // 5),
    }


def binary_suite(
    pos_probs: np.ndarray, labels: np.ndarray, threshold: float = 0.5
) -> Dict[str, float]:
    """Class-1 acc/prec/recall/F1/AUROC/AUPRC for one complex."""
    labels = labels.astype(bool)
    pred_pos = pos_probs >= threshold
    tp = float(np.sum(pred_pos & labels))
    fp = float(np.sum(pred_pos & ~labels))
    n_pos = float(labels.sum())
    n_neg = float((~labels).sum())

    recall = tp / n_pos if n_pos else float("nan")
    prec = tp / (tp + fp) if (tp + fp) else 0.0
    f1 = 2 * prec * recall / (prec + recall) if (prec + recall) else 0.0
    return {
        "acc": recall,  # torchmetrics multiclass per-class accuracy == recall
        "prec": prec,
        "recall": recall,
        "f1": f1,
        "auroc": _auroc(pos_probs, labels, n_pos, n_neg),
        "auprc": _average_precision(pos_probs, labels, n_pos),
    }


def _auroc(pos_probs, labels, n_pos, n_neg) -> float:
    """Rank-based (Mann-Whitney U) AUROC; NaN when one class is absent."""
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(pos_probs, kind="stable")
    ranks = np.empty(len(pos_probs), dtype=np.float64)
    # Average ranks over ties.
    sorted_p = pos_probs[order]
    _, inv, counts = np.unique(sorted_p, return_inverse=True, return_counts=True)
    cum = np.cumsum(counts)
    avg_rank_per_group = cum - (counts - 1) / 2.0
    ranks[order] = avg_rank_per_group[inv]
    r_pos = ranks[labels].sum()
    return float((r_pos - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg))


def _average_precision(pos_probs, labels, n_pos) -> float:
    """AP = sum_i (R_i - R_{i-1}) P_i over descending-probability order."""
    if n_pos == 0:
        return float("nan")
    order = np.argsort(-pos_probs, kind="stable")
    hits = labels[order].astype(np.float64)
    cum_tp = np.cumsum(hits)
    precision = cum_tp / np.arange(1, len(hits) + 1)
    return float(np.sum(precision * hits) / n_pos)


def complex_metrics(
    pos_probs: np.ndarray,
    labels: np.ndarray,
    n1: int,
    n2: int,
    stage: str = "val",
    threshold: float = 0.5,
    ce: Optional[float] = None,
) -> Dict[str, float]:
    """All per-complex metrics for one (flattened) pair list.

    ``stage`` selects the reference's L convention: 'val' -> L = n1 + n2
    (deepinteract_modules.py:1946), 'test' -> L = min(n1, n2) (:2045).
    """
    l = (n1 + n2) if stage == "val" else min(n1, n2)
    out = topk_suite(pos_probs, labels, l)
    out.update(binary_suite(pos_probs, labels, threshold))
    if ce is not None:
        out["ce"] = float(ce)
    return out


def aggregate_median(per_complex: Sequence[Dict[str, float]]) -> Dict[str, float]:
    """Median over complexes per metric (reference's ``med_*`` logging),
    NaN-skipping; ``ce`` is averaged (reference logs per-step ce with
    Lightning's default mean reduction)."""
    if not per_complex:
        return {}
    keys = per_complex[0].keys()
    out = {}
    for key in keys:
        vals = np.asarray([m[key] for m in per_complex], dtype=np.float64)
        if key == "ce":
            out[key] = float(np.nanmean(vals))
        else:
            out[f"med_{key}"] = float(np.nanmedian(vals)) if not np.all(np.isnan(vals)) else float("nan")
    return out


TOPK_CSV_COLUMNS = (
    "top_10_prec",
    "top_l_by_10_prec",
    "top_l_by_5_prec",
    "top_l_recall",
    "top_l_by_2_recall",
    "top_l_by_5_recall",
    "target",
)


def write_topk_csv(
    per_complex: Sequence[Dict[str, float]],
    targets: Sequence[str],
    path: str,
) -> None:
    """Per-target CSV matching the reference's ``*_top_metrics.csv``
    (deepinteract_modules.py:2130-2145): pandas-style with an index column."""
    lines = ["," + ",".join(TOPK_CSV_COLUMNS)]
    for i, (metrics, target) in enumerate(zip(per_complex, targets)):
        row = [str(i)]
        for col in TOPK_CSV_COLUMNS[:-1]:
            v = metrics.get(col, float("nan"))
            row.append(repr(v) if not math.isnan(v) else "")
        row.append(str(target))
        lines.append(",".join(row))
    artifacts.atomic_write(path, "\n".join(lines) + "\n")


def gather_pair_predictions(probs: np.ndarray, examples: np.ndarray, example_mask: np.ndarray):
    """Extract (pos_probs, labels) for one complex from dense [L1, L2, 2]
    softmax output using its flattened (i, j, label) example list — the
    flat-index gather of ``deepinteract_modules.py:2030-2034``."""
    ex = examples[example_mask]
    pos_probs = probs[ex[:, 0], ex[:, 1], 1]
    return np.asarray(pos_probs), ex[:, 2].astype(np.int64)
