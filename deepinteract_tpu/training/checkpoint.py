"""Orbax checkpointing: best-k tracking + last, restore, fine-tune warm start.

TPU-native replacement for Lightning's ``ModelCheckpoint`` configuration in
the reference (``lit_model_train.py:139-151``): monitor a chosen metric
(mode 'min' iff its name contains 'ce', exactly the reference's rule),
keep the top ``save_top_k`` checkpoints plus always the latest
(``save_top_k=3, save_last=True``, ``lit_model_train.py:144-151``).

A third root, ``mid/``, holds the newest **intra-epoch** cadence save
(``--save_every_steps``, training/loop.py): one step whose number encodes
the exact resume position (``epoch * MIDEPOCH_STRIDE + batch_index``),
so a kill -9 mid-epoch re-pays at most one save cadence of steps instead
of the whole epoch. ``restore(which='mid')`` is the resume entry point:
it merges all three roots by decoded position and walks back through the
PR-12 verification/quarantine discipline like any other restore.

Durability (robustness/artifacts.py): every retained step directory gets
a tree integrity sidecar (``<step>.integrity.json``, per-file SHA-256)
written at :meth:`Checkpointer.wait`, and :meth:`Checkpointer.restore`
verifies before orbax ever deserializes. A step with POSITIVE corruption
evidence — missing ``_CHECKPOINT_METADATA`` (torn save), a sidecar whose
hashes disagree (bit flip/truncation), or an unreadable sidecar — is
quarantined aside and restore walks back to
the previous retained step or ``best/``, so ``--resume`` after a torn
``last/`` is automatic instead of a crash. A step with no sidecar at all
(legacy root, or a kill between orbax finalize and our sidecar write) is
merely *unverified*: it ranks below every verified candidate in the walk
but is still restorable with a logged warning — quarantining a healthy
finalized save would be worse than restoring it.

Multi-host: only the primary host constructs a Checkpointer
(training/loop.py), so the fallback decision — which step actually
restored — is made on host 0 alone and reaches every other host through
the existing resume broadcast (start_epoch + state tree), the same
discipline as the PR-4 tuning-store read. Hosts can never walk back to
different steps.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os
from typing import Any, List, Optional, Tuple

import orbax.checkpoint as ocp

from deepinteract_tpu.robustness import artifacts, faults

logger = logging.getLogger(__name__)

# Schema kind of the per-step tree sidecars (artifact-integrity/v1) —
# shared with cli/fsck.py via the artifacts module so both paths count
# the same corruption class under one di_artifact_corrupt_total label.
CHECKPOINT_KIND = artifacts.CHECKPOINT_KIND

# Written by orbax at step finalize; its absence in a step directory is
# positive evidence of a torn save (kill -9 mid-commit).
_ORBAX_COMMIT_MARKER = "_CHECKPOINT_METADATA"

# Mid-epoch checkpoint step encoding (the ``mid/`` root only): the orbax
# step number IS the resume position — ``epoch * STRIDE + batch_index``
# — so a ``--resume`` after kill -9 recovers the exact next batch from
# the step name alone, with no sidecar round trip that a crash between
# the orbax save and the sidecar write could tear. ``best/``/``last/``
# keep their historical epoch-boundary numbering (step = resume epoch).
MIDEPOCH_STRIDE = 10 ** 8


def encode_midepoch_step(epoch: int, batch_index: int) -> int:
    if not 0 <= batch_index < MIDEPOCH_STRIDE:
        raise ValueError(f"batch_index {batch_index} outside "
                         f"[0, {MIDEPOCH_STRIDE})")
    return int(epoch) * MIDEPOCH_STRIDE + int(batch_index)


def decode_position(which: Optional[str], step: int) -> Tuple[int, int]:
    """Orbax step -> (resume_epoch, resume_batch). ``mid/`` steps carry
    both; ``best/``/``last/`` steps are epoch boundaries (the step IS
    the epoch to resume at, batch 0)."""
    if which == "mid":
        return int(step) // MIDEPOCH_STRIDE, int(step) % MIDEPOCH_STRIDE
    return int(step), 0


def _partial_restore_args(target: Any):
    """Restore-args for a target tree that holds a SUBSET of the saved
    keys (fine-tune warm starts, serving's params/batch_stats-only
    template). Current orbax spells this ``PyTreeRestore(target,
    partial_restore=True)``; releases before 0.11 reject that kwarg but
    express the same semantics through an empty ``transforms`` dict
    (every target leaf falls back to the same-named checkpoint entry,
    checkpoint keys absent from the target are dropped)."""
    try:
        return ocp.args.PyTreeRestore(target, partial_restore=True)
    except TypeError:  # orbax < 0.11: partial_restore kwarg not yet added
        return ocp.args.PyTreeRestore(
            item=target, transforms={},
            restore_args=ocp.checkpoint_utils.construct_restore_args(target))


def metric_mode(metric_name: str) -> str:
    """'min' iff the tracked metric name contains 'ce' (lit_model_train.py:
    139-143); everything else (prec/recall/auroc...) is maximized."""
    return "min" if "ce" in metric_name else "max"


@dataclasses.dataclass
class CheckpointConfig:
    directory: str
    metric_to_track: str = "val_ce"
    save_top_k: int = 3
    keep_last: bool = True
    # mid/ root for intra-epoch cadence saves (training/loop.py
    # --save_every_steps). Rides with keep_last: a run that keeps no
    # last/ has nothing to resume into either way.
    keep_midepoch: bool = True


class Checkpointer:
    """Thin orbax wrapper holding two managers: ``best/`` (top-k by the
    tracked metric) and ``last/`` (most recent, for resume)."""

    def __init__(self, cfg: CheckpointConfig):
        self.cfg = cfg
        # What the last restore() actually loaded — (which, step). The
        # fallback walk can land on an OLDER step than latest_step(), and
        # resume bookkeeping (training/loop.py start_epoch) must follow
        # the restored state, not the quarantined directory listing.
        self.last_restored_step: Optional[int] = None
        self.last_restored_which: Optional[str] = None
        mode = metric_mode(cfg.metric_to_track)
        sign = 1.0 if mode == "max" else -1.0

        def best_fn(metrics):
            v = metrics.get(cfg.metric_to_track, math.nan)
            # Non-finite/missing must rank worst AFTER the sign flip: a NaN
            # val_ce would score +inf under best_mode='max', and a +inf
            # val_auroc (mode 'max') would latch as an unbeatable best. An
            # epoch whose tracked metric is not a finite number is never
            # "best" — explicit policy, unit-tested in the chaos suite.
            return sign * v if math.isfinite(v) else -math.inf

        # Multi-host runs checkpoint from the primary host only (the state
        # tree is replicated and already materialized as host-local numpy,
        # training/loop.py state_to_tree); restricting orbax's active
        # process set keeps its internal barriers from waiting on hosts
        # that never construct a Checkpointer.
        import jax

        mp_kwargs = {}
        root = os.path.abspath(cfg.directory)
        keep_mid = cfg.keep_last and cfg.keep_midepoch
        if jax.process_count() > 1:
            mp_kwargs["multiprocessing_options"] = ocp.options.MultiprocessingOptions(
                primary_host=jax.process_index(),
                active_processes={jax.process_index()},
            )
            # orbax refuses create=True under active_processes; make the
            # roots ourselves (this manager is single-process by design).
            mp_kwargs["create"] = False
            subs = ["best"] + (["last"] if cfg.keep_last else [])
            subs += ["mid"] if keep_mid else []
            for sub in subs:
                os.makedirs(os.path.join(root, sub), exist_ok=True)
        self.best = ocp.CheckpointManager(
            os.path.join(root, "best"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=cfg.save_top_k, best_fn=best_fn, best_mode="max",
                **mp_kwargs,
            ),
        )
        self.last = (
            ocp.CheckpointManager(
                os.path.join(root, "last"),
                options=ocp.CheckpointManagerOptions(max_to_keep=1, **mp_kwargs),
            )
            if cfg.keep_last
            else None
        )
        # Intra-epoch cadence saves (mid/): the newest resume position,
        # step-number-encoded as epoch*STRIDE+batch (module docstring).
        self.mid = (
            ocp.CheckpointManager(
                os.path.join(root, "mid"),
                options=ocp.CheckpointManagerOptions(max_to_keep=1, **mp_kwargs),
            )
            if keep_mid
            else None
        )
        # Startup sweep: orphaned sidecar tmps from a killed run. The
        # orbax payloads themselves commit via directory rename, so only
        # OUR ``*.integrity.json.<pid>.tmp`` strays can linger here —
        # and the filters matter: the ckpt root is SHARED (the tuning
        # store and trainer_state.json live here), so an unscoped sweep
        # could reap a concurrent cli.tune's live tmp.
        artifacts.sweep_tmp(root, prefix="trainer_state.json")
        for d in (os.path.join(root, "best"), os.path.join(root, "last"),
                  os.path.join(root, "mid")):
            artifacts.sweep_tmp(d, contains=artifacts.SIDECAR_SUFFIX + ".")

    def save(self, step: int, state: Any, metrics: dict) -> None:
        clean = {
            k: float(v)
            for k, v in metrics.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        self.best.save(step, args=ocp.args.StandardSave(state), metrics=clean)
        if self.last is not None:
            self.last.save(step, args=ocp.args.StandardSave(state))

    def save_midepoch(self, epoch: int, batch_index: int, state: Any) -> None:
        """Intra-epoch cadence save (``--save_every_steps``): mid/ only —
        no metric exists mid-epoch, so best/ bookkeeping is untouched, and
        last/ keeps its epoch-boundary meaning. The step number encodes
        the exact resume position."""
        if self.mid is None:
            raise RuntimeError("mid-epoch saves need keep_last + "
                               "keep_midepoch (CheckpointConfig)")
        self.mid.save(encode_midepoch_step(epoch, batch_index),
                      args=ocp.args.StandardSave(state))

    def wait(self) -> None:
        self.best.wait_until_finished()
        if self.last is not None:
            self.last.wait_until_finished()
        if self.mid is not None:
            self.mid.wait_until_finished()
        self._finalize_integrity()

    # -- integrity ---------------------------------------------------------

    def _managers(self) -> List[Tuple[Any, str]]:
        out: List[Tuple[Any, str]] = [(self.best, "best")]
        if self.last is not None:
            out.append((self.last, "last"))
        if self.mid is not None:
            out.append((self.mid, "mid"))
        return out

    @staticmethod
    def _steps(mgr) -> List[int]:
        try:
            return [int(s) for s in mgr.all_steps()]
        except OSError:  # a root that vanished mid-run: nothing retained
            return []

    def _finalize_integrity(self) -> None:
        """Write tree sidecars for retained steps that lack one, and drop
        sidecars orphaned by orbax retention (max_to_keep deletions). A
        finalized step directory never changes, so an existing sidecar is
        never rewritten."""
        for mgr, name in self._managers():
            root = str(mgr.directory)
            for step in self._steps(mgr):
                step_dir = os.path.join(root, str(step))
                if not os.path.isdir(step_dir):
                    continue
                if os.path.exists(artifacts.sidecar_path(step_dir)):
                    continue
                try:
                    artifacts.write_tree_sidecar(
                        step_dir, CHECKPOINT_KIND,
                        extra={"step": int(step), "which": name})
                except OSError as exc:
                    # A full disk must not turn a finished save into a
                    # crash; the step just stays unverified.
                    logger.warning("could not write integrity sidecar for "
                                   "%s: %s", step_dir, exc)
            try:
                names = os.listdir(root)
            except OSError:
                continue
            for nm in names:
                if not nm.endswith(artifacts.SIDECAR_SUFFIX):
                    continue
                target = nm[: -len(artifacts.SIDECAR_SUFFIX)]
                if not os.path.exists(os.path.join(root, target)):
                    try:
                        os.unlink(os.path.join(root, nm))
                    except OSError:
                        pass

    @staticmethod
    def _quarantine_step(mgr, step_dir: str, reason: str) -> None:
        """Quarantine a step dir AND refresh the owning manager's cached
        step metadata — orbax caches the directory listing, and a later
        save's retention pass would otherwise look up the moved step and
        crash."""
        artifacts.quarantine(step_dir, CHECKPOINT_KIND, reason)
        try:
            mgr.reload()
        except (AttributeError, OSError):  # older orbax / racing listing
            pass

    def _verify_step(self, step_dir: str) -> str:
        """'verified' | 'unverified' (no sidecar — legacy/kill-between-
        finalize-and-sidecar), or raises CorruptArtifact/StaleArtifact on
        positive corruption evidence."""
        if faults.fire("checkpoint.restore"):
            raise artifacts.CorruptArtifact(
                step_dir, "injected checkpoint.restore fault")
        if not os.path.isdir(step_dir):
            raise FileNotFoundError(step_dir)
        if not os.path.exists(os.path.join(step_dir, _ORBAX_COMMIT_MARKER)):
            raise artifacts.CorruptArtifact(
                step_dir, f"torn save: {_ORBAX_COMMIT_MARKER} missing "
                          "(killed mid-commit)")
        manifest = artifacts.verify_tree(
            step_dir, kind=CHECKPOINT_KIND, require_sidecar=False)
        return "verified" if manifest is not None else "unverified"

    def best_step(self) -> Optional[int]:
        return self.best.best_step()

    def latest_step(self) -> Optional[int]:
        if self.last is not None and self.last.latest_step() is not None:
            return self.last.latest_step()
        return self.best.latest_step()

    def has_restorable(self) -> bool:
        """Any retained step across mid/last/best (the --resume presence
        probe; latest_step() keeps its historical boundary-roots-only
        meaning for the callers that interpret steps as epochs)."""
        if self.mid is not None and self._steps(self.mid):
            return True
        return self.latest_step() is not None

    def _restore_candidates(self, which: str) -> List[Tuple[Any, str, int]]:
        """(manager, name, step) in walk-back preference order: the
        requested root newest-first, then the sibling root newest-first —
        except that ``which='best'`` leads with the metric-best step, and
        ``which='mid'`` (the resume entry) merges all three roots by
        DECODED resume position, newest position first (a mid-epoch save
        outranks its own epoch's boundary, the next boundary outranks it;
        within a tie last/ is preferred over best/)."""
        out: List[Tuple[Any, str, int]] = []
        if which == "mid":
            rank = {"mid": 2, "last": 1, "best": 0}
            cands = [
                (mgr, name, s)
                for mgr, name in self._managers()
                for s in self._steps(mgr)
            ]
            cands.sort(key=lambda t: (decode_position(t[1], t[2]),
                                      rank[t[1]]), reverse=True)
            return cands
        if which == "last" and self.last is not None:
            for s in sorted(self._steps(self.last), reverse=True):
                out.append((self.last, "last", s))
            for s in sorted(self._steps(self.best), reverse=True):
                out.append((self.best, "best", s))
            return out
        steps = sorted(self._steps(self.best), reverse=True)
        top = self.best.best_step()
        if top is not None and top in steps:
            steps.remove(top)
            steps.insert(0, top)
        for s in steps:
            out.append((self.best, "best", s))
        if self.last is not None:
            for s in sorted(self._steps(self.last), reverse=True):
                out.append((self.last, "last", s))
        return out

    def _orbax_restore(self, mgr, step: int, target: Any, partial: bool):
        if partial:
            return mgr.restore(step, args=_partial_restore_args(target))
        return mgr.restore(step, args=ocp.args.StandardRestore(target))

    def restore(
        self, target: Any, step: Optional[int] = None, which: str = "best",
        partial: bool = False,
    ) -> Any:
        """Restore into the structure of ``target`` (an abstract or concrete
        state pytree). ``partial=True`` restores only the keys present in
        ``target`` (e.g. params/batch_stats for fine-tune warm starts whose
        optimizer structure differs from the saved one).

        Every step is integrity-verified before orbax deserializes it.
        With ``step=None`` a corrupt candidate is quarantined and the walk
        falls back to the previous retained step or the sibling root
        (last-good fallback; the one-line log names what was skipped).
        Verified steps are always preferred over sidecar-less ones. An
        EXPLICIT ``step`` disables the walk: the caller asked for that
        state and nothing else, so corruption raises
        :class:`~deepinteract_tpu.robustness.artifacts.CorruptArtifact`
        after quarantining it. Restored-step identity is decided on the
        host that owns this Checkpointer (host 0 in multi-host runs) and
        reaches the others via the resume broadcast in training/loop.py.
        """
        if which == "mid" and self.mid is not None:
            mgr = self.mid
        else:
            mgr = (self.best if which == "best" or self.last is None
                   else self.last)
        if step is not None:
            step_dir = os.path.join(str(mgr.directory), str(step))
            try:
                self._verify_step(step_dir)
            except (artifacts.CorruptArtifact, artifacts.StaleArtifact) as exc:
                self._quarantine_step(mgr, step_dir, exc.reason)
                raise artifacts.CorruptArtifact(
                    step_dir, f"requested step {step} is corrupt "
                              f"({exc.reason}); quarantined")
            state = self._orbax_restore(mgr, step, target, partial)
            self.last_restored_step = int(step)
            self.last_restored_which = which
            return state

        unverified: List[Tuple[Any, str, int, str]] = []
        requested = None
        for mgr_i, name, s in self._restore_candidates(which):
            step_dir = os.path.join(str(mgr_i.directory), str(s))
            if requested is None:
                requested = (name, s)
            try:
                status = self._verify_step(step_dir)
            except FileNotFoundError:
                continue
            except (artifacts.CorruptArtifact, artifacts.StaleArtifact) as exc:
                self._quarantine_step(mgr_i, step_dir, exc.reason)
                continue
            if status == "unverified":
                unverified.append((mgr_i, name, s, step_dir))
                continue
            return self._attempt(mgr_i, name, s, step_dir, target,
                                 partial, requested)
        for mgr_i, name, s, step_dir in unverified:
            logger.warning("restoring UNVERIFIED checkpoint %s (no "
                           "integrity sidecar — pre-integrity save?)",
                           step_dir)
            return self._attempt(mgr_i, name, s, step_dir, target,
                                 partial, requested)
        raise FileNotFoundError(
            f"no restorable checkpoint under {self.cfg.directory} "
            f"({which}): every retained step was missing or corrupt "
            "(quarantined — see *.corrupt-* aside)")

    def _attempt(self, mgr, name: str, step: int, step_dir: str,
                 target: Any, partial: bool, requested) -> Any:
        """One orbax restore. An orbax exception here PROPAGATES: the
        step's bytes already passed (or had no) integrity checks, so a
        deserialize failure means the CALLER's target tree doesn't match
        the saved one (changed optimizer/model config) or an orbax bug —
        quarantining on it would empty the whole checkpoint root one
        healthy step at a time, since every candidate fails the same way
        against the same target. Only positive on-disk corruption
        evidence quarantines (_verify_step)."""
        state = self._orbax_restore(mgr, step, target, partial)
        if requested is not None and requested != (name, step):
            logger.warning(
                "checkpoint fallback: restored %s/%s instead of the "
                "newest candidate %s/%s (corrupt/unrestorable steps "
                "quarantined along the walk)", name, step, *requested)
        self.last_restored_step = int(step)
        self.last_restored_which = name
        return state

    def close(self) -> None:
        self.wait()
        self.best.close()
        if self.last is not None:
            self.last.close()
        if self.mid is not None:
            self.mid.close()
