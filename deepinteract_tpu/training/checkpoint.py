"""Orbax checkpointing: best-k tracking + last, restore, fine-tune warm start.

TPU-native replacement for Lightning's ``ModelCheckpoint`` configuration in
the reference (``lit_model_train.py:139-151``): monitor a chosen metric
(mode 'min' iff its name contains 'ce', exactly the reference's rule),
keep the top ``save_top_k`` checkpoints plus always the latest
(``save_top_k=3, save_last=True``, ``lit_model_train.py:144-151``).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Optional

import orbax.checkpoint as ocp


def _partial_restore_args(target: Any):
    """Restore-args for a target tree that holds a SUBSET of the saved
    keys (fine-tune warm starts, serving's params/batch_stats-only
    template). Current orbax spells this ``PyTreeRestore(target,
    partial_restore=True)``; releases before 0.11 reject that kwarg but
    express the same semantics through an empty ``transforms`` dict
    (every target leaf falls back to the same-named checkpoint entry,
    checkpoint keys absent from the target are dropped)."""
    try:
        return ocp.args.PyTreeRestore(target, partial_restore=True)
    except TypeError:  # orbax < 0.11: partial_restore kwarg not yet added
        return ocp.args.PyTreeRestore(
            item=target, transforms={},
            restore_args=ocp.checkpoint_utils.construct_restore_args(target))


def metric_mode(metric_name: str) -> str:
    """'min' iff the tracked metric name contains 'ce' (lit_model_train.py:
    139-143); everything else (prec/recall/auroc...) is maximized."""
    return "min" if "ce" in metric_name else "max"


@dataclasses.dataclass
class CheckpointConfig:
    directory: str
    metric_to_track: str = "val_ce"
    save_top_k: int = 3
    keep_last: bool = True


class Checkpointer:
    """Thin orbax wrapper holding two managers: ``best/`` (top-k by the
    tracked metric) and ``last/`` (most recent, for resume)."""

    def __init__(self, cfg: CheckpointConfig):
        self.cfg = cfg
        mode = metric_mode(cfg.metric_to_track)
        sign = 1.0 if mode == "max" else -1.0

        def best_fn(metrics):
            v = metrics.get(cfg.metric_to_track, math.nan)
            # Non-finite/missing must rank worst AFTER the sign flip: a NaN
            # val_ce would score +inf under best_mode='max', and a +inf
            # val_auroc (mode 'max') would latch as an unbeatable best. An
            # epoch whose tracked metric is not a finite number is never
            # "best" — explicit policy, unit-tested in the chaos suite.
            return sign * v if math.isfinite(v) else -math.inf

        # Multi-host runs checkpoint from the primary host only (the state
        # tree is replicated and already materialized as host-local numpy,
        # training/loop.py state_to_tree); restricting orbax's active
        # process set keeps its internal barriers from waiting on hosts
        # that never construct a Checkpointer.
        import jax

        mp_kwargs = {}
        root = os.path.abspath(cfg.directory)
        if jax.process_count() > 1:
            mp_kwargs["multiprocessing_options"] = ocp.options.MultiprocessingOptions(
                primary_host=jax.process_index(),
                active_processes={jax.process_index()},
            )
            # orbax refuses create=True under active_processes; make the
            # roots ourselves (this manager is single-process by design).
            mp_kwargs["create"] = False
            for sub in ("best", "last") if cfg.keep_last else ("best",):
                os.makedirs(os.path.join(root, sub), exist_ok=True)
        self.best = ocp.CheckpointManager(
            os.path.join(root, "best"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=cfg.save_top_k, best_fn=best_fn, best_mode="max",
                **mp_kwargs,
            ),
        )
        self.last = (
            ocp.CheckpointManager(
                os.path.join(root, "last"),
                options=ocp.CheckpointManagerOptions(max_to_keep=1, **mp_kwargs),
            )
            if cfg.keep_last
            else None
        )

    def save(self, step: int, state: Any, metrics: dict) -> None:
        clean = {
            k: float(v)
            for k, v in metrics.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        self.best.save(step, args=ocp.args.StandardSave(state), metrics=clean)
        if self.last is not None:
            self.last.save(step, args=ocp.args.StandardSave(state))

    def wait(self) -> None:
        self.best.wait_until_finished()
        if self.last is not None:
            self.last.wait_until_finished()

    def best_step(self) -> Optional[int]:
        return self.best.best_step()

    def latest_step(self) -> Optional[int]:
        if self.last is not None and self.last.latest_step() is not None:
            return self.last.latest_step()
        return self.best.latest_step()

    def restore(
        self, target: Any, step: Optional[int] = None, which: str = "best",
        partial: bool = False,
    ) -> Any:
        """Restore into the structure of ``target`` (an abstract or concrete
        state pytree). ``partial=True`` restores only the keys present in
        ``target`` (e.g. params/batch_stats for fine-tune warm starts whose
        optimizer structure differs from the saved one)."""
        mgr = self.best if which == "best" or self.last is None else self.last
        if step is None:
            step = mgr.best_step() if mgr is self.best and which == "best" else mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.cfg.directory} ({which})")
        if partial:
            return mgr.restore(step, args=_partial_restore_args(target))
        return mgr.restore(step, args=ocp.args.StandardRestore(target))

    def close(self) -> None:
        self.wait()
        self.best.close()
        if self.last is not None:
            self.last.close()
