"""Edge-gated multi-head graph attention on the dense [N, K] edge layout.

This is the reference's hottest loop — the DGL edge-softmax pipeline
``apply_edges(K.Q) -> scale/clip(+-5) -> *proj_e -> exp(clip(+-5)) ->
send_and_recv(u_mul_e, sum)`` (``deepinteract_modules.py:76-96``,
``graph_utils.py:21-63``) — recast as dense tensor algebra:

* ``scatter`` mode reproduces the reference semantics exactly: edge (i, k)
  carries K[i] . Q[nbr_idx[i,k]]; each node normalizes over its *incoming*
  edges (reverse-kNN, variable degree) via a static-shape ``segment_sum``.
* ``gather`` mode is the TPU-optimal transposed formulation: node i attends
  over its own K out-edges (Q[i] . K[nbr_idx[i,k]]), so the softmax is a
  plain masked reduction over axis K — no scatter at all. Identical to
  ``scatter`` when the kNN graph is symmetric; real kNN graphs are ~35-45%
  non-mutual and the node outputs diverge by O(10%) median relative
  deviation (measured in ``tests/test_attention_modes.py``), so ``scatter``
  is the default and ``gather`` is an opt-in approximation.

Both share the clip/eps numerics of the reference (score clip +-5 after
1/sqrt(d) scaling, exp-clamp +-5, z + 1e-6 denominator), which are part of
the model's behavior, not incidental.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

CLIP = 5.0
EPS = 1e-6


def _gather_nodes(x: jnp.ndarray, nbr_idx: jnp.ndarray) -> jnp.ndarray:
    """x: [B, N, ...], nbr_idx: [B, N, K] -> [B, N, K, ...]."""
    return jax.vmap(lambda xb, nb: xb[nb])(x, nbr_idx)


def edge_scores(
    q: jnp.ndarray,
    k: jnp.ndarray,
    proj_e: jnp.ndarray,
    nbr_idx: jnp.ndarray,
    mode: str = "scatter",
) -> jnp.ndarray:
    """Per-edge gated score vectors [B, N, K, H, D].

    score = clip(K_src * Q_recv / sqrt(D), +-5) * proj_e, elementwise per
    head dim (reference ``src_dot_dst``/``scaling``/``imp_exp_attn``).
    The receiver holds Q: the edge destination in ``scatter`` mode, the row
    owner in ``gather`` mode.
    """
    d = q.shape[-1]
    if mode == "scatter":
        q_recv = _gather_nodes(q, nbr_idx)  # Q at destination
        k_src = k[:, :, None]  # K at row owner (source)
        raw = k_src * q_recv
    elif mode == "gather":
        k_other = _gather_nodes(k, nbr_idx)
        raw = q[:, :, None] * k_other
    else:
        raise ValueError(f"unknown attention mode: {mode}")
    scaled = jnp.clip(raw / jnp.sqrt(jnp.asarray(d, raw.dtype)), -CLIP, CLIP)
    return scaled * proj_e


@partial(jax.jit, static_argnames=("mode",))
def edge_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    proj_e: jnp.ndarray,
    nbr_idx: jnp.ndarray,
    edge_mask: jnp.ndarray,
    mode: str = "scatter",
):
    """Full edge-gated attention.

    Args:
      q, k, v:    [B, N, H, D] head-split node projections
      proj_e:     [B, N, K, H, D] head-split edge projections
      nbr_idx:    [B, N, K] destination of edge (i, k)
      edge_mask:  [B, N, K] validity of edges
      mode:       'scatter' (reference-exact) or 'gather' (TPU-fast)

    Returns:
      h_out: [B, N, H, D] attention-weighted values per node
      e_out: [B, N, K, H, D] gated score vectors (pre-exp), the edge update
             (reference ``out_edge_features``)
    """
    b, n, h, d = q.shape
    kk = nbr_idx.shape[-1]
    f32 = jnp.float32
    score_vec = edge_scores(q, k, proj_e, nbr_idx, mode=mode)  # [B,N,K,H,D]
    # Softmax accumulators in float32 regardless of the compute dtype
    # (models/policy.py: exp/sum reductions are the bf16-unsafe part; with
    # float32 inputs every cast here is the identity, so f32 numerics are
    # unchanged). Values may stay bf16 — the weighted sums promote to f32.
    logits = jnp.clip(jnp.sum(score_vec.astype(f32), axis=-1), -CLIP, CLIP)
    weights = jnp.exp(logits) * edge_mask[..., None].astype(f32)  # [B,N,K,H]

    if mode == "gather":
        v_nbr = _gather_nodes(v, nbr_idx)  # [B,N,K,H,D]
        wv = jnp.einsum("bnkh,bnkhd->bnhd", weights, v_nbr,
                        preferred_element_type=f32)
        z = jnp.sum(weights, axis=2)  # [B,N,H]
    else:
        # Scatter contributions of edge (i, k) onto its destination node.
        def scatter_one(w_b, v_b, nbr_b):
            flat_w = w_b.reshape(n * kk, h)
            flat_v = jnp.repeat(v_b, kk, axis=0)  # [N*K,H,D] source values
            seg = nbr_b.reshape(n * kk)
            wv_b = jax.ops.segment_sum(flat_w[..., None] * flat_v, seg, num_segments=n)
            z_b = jax.ops.segment_sum(flat_w, seg, num_segments=n)
            return wv_b, z_b

        wv, z = jax.vmap(scatter_one)(weights, v, nbr_idx)

    # Back to the caller's compute dtype (no-op under float32).
    h_out = (wv / (z[..., None] + EPS)).astype(q.dtype)
    e_out = score_vec * edge_mask[..., None, None].astype(score_vec.dtype)
    return h_out, e_out
