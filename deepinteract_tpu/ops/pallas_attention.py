"""Pallas TPU kernel for the fused edge-attention hot loop (scatter mode).

The reference's hottest path is the DGL edge-softmax pipeline
(``deepinteract_modules.py:76-96``); :mod:`deepinteract_tpu.ops.attention`
recasts it as dense algebra with a ``segment_sum`` scatter. This kernel goes
one step further for TPU: **the scatter itself becomes an MXU matmul**.

Key idea: with the dense ``[N, K]`` edge layout, "sum edge quantities into
their destination node" is ``onehot(nbr)^T @ X`` where
``onehot[e, j] = (nbr_flat[e] == j)`` — a [E, N] x [E, HD] contraction the
systolic array eats, instead of a serial scatter the VPU would crawl
through. Likewise "gather Q at each edge's destination" is
``onehot @ Q`` and per-head reductions/broadcasts are matmuls against
block-diagonal 0/1 matrices, so the entire op — score, gate, clip, exp,
normalize, aggregate — runs in one kernel launch with everything resident
in VMEM.

Numerics are bit-compatible with ``edge_attention(..., mode='scatter')``
(same clip/eps constants); the parity test drives both on the same inputs.

Scope: an edge-block grid keeps every working set in VMEM at any bucket up
to ``MAX_KERNEL_NODES`` (the full reference regime — 256 residues,
deepinteract_constants.py:10-12). Buckets <= 128 nodes run as one block
(whole graph resident); larger buckets split the edge list into
``n // 64`` blocks, accumulate the per-node numerator in the (revisited)
output block and the softmax denominator in VMEM scratch, and normalize in
the final grid step. Backward runs through ``jax.custom_vjp`` delegating
to the jnp reference implementation's VJP — semantics-identical gradients
with zero duplicated math.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepinteract_tpu.ops.attention import CLIP, EPS, edge_attention

# Largest supported padded bucket (= the reference's RESIDUE_COUNT_LIMIT).
# Per-block VMEM at N=256, K=20, HD=128 with n//64 = 4 edge blocks:
# two [1280, 256] one-hot selectors (~1.3 MB each), [1280, 128] edge tiles
# (~0.65 MB each) and two [256, 128] accumulators — comfortably inside a
# v5e core's ~16 MB VMEM (the whole-graph formulation needs ~26 MB there).
MAX_KERNEL_NODES = 256


def _num_edge_blocks(n: int) -> int:
    return 1 if n <= 128 else n // 64


def _kernel(nbr_ref, mask_ref, q_ref, k_ref, v_ref, pe_ref, h_ref, e_ref,
            z_acc, *, num_nodes: int, knn: int, num_heads: int,
            head_dim: int, num_eblocks: int):
    n, kk, h, d = num_nodes, knn, num_heads, head_dim
    hd = h * d
    eb = n * kk // num_eblocks  # edges per grid block
    f32 = jnp.float32
    j = pl.program_id(1)

    nbr = nbr_ref[0]          # [EB, 1] int32
    mask = mask_ref[0]        # [EB, 1] f32
    q = q_ref[0]              # [N, HD]
    k = k_ref[0]
    v = v_ref[0]
    pe = pe_ref[0]            # [EB, HD]

    node_ids = jax.lax.broadcasted_iota(jnp.int32, (eb, n), 1)
    onehot_dst = (nbr == node_ids).astype(f32)                      # [EB, N]
    src_ids = (jax.lax.broadcasted_iota(jnp.int32, (eb, 1), 0) + j * eb) // kk
    onehot_src = (src_ids == node_ids).astype(f32)                  # [EB, N]

    # Per-head sum / broadcast as block-diagonal 0/1 matmuls.
    lane_head = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 0) // d
    head_ids = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 1)
    sum_mat = (lane_head == head_ids).astype(f32)                   # [HD, H]

    dot = functools.partial(jnp.dot, preferred_element_type=f32)
    q_dst = dot(onehot_dst, q)                                      # [EB, HD]
    k_src = dot(onehot_src, k)
    v_src = dot(onehot_src, v)

    inv_sqrt_d = 1.0 / (d ** 0.5)
    scaled = jnp.clip(k_src * q_dst * inv_sqrt_d, -CLIP, CLIP) * pe  # [EB, HD]
    logits = jnp.clip(dot(scaled, sum_mat), -CLIP, CLIP)             # [EB, H]
    w = jnp.exp(logits) * mask                                       # [EB, H]

    w_full = dot(w, sum_mat.T)                                       # [EB, HD]
    x = w_full * v_src
    wv = jax.lax.dot_general(onehot_dst, x, (((0,), (0,)), ((), ())),
                             preferred_element_type=f32)             # [N, HD]
    z = jax.lax.dot_general(onehot_dst, w, (((0,), (0,)), ((), ())),
                            preferred_element_type=f32)              # [N, H]
    z_full = dot(z, sum_mat.T)                                       # [N, HD]

    e_ref[0] = scaled * mask

    # Numerator accumulates in the revisited output block, denominator in
    # scratch; both zeroed on the first edge block, normalized on the last.
    @pl.when(j == 0)
    def _init():
        h_ref[0] = jnp.zeros((n, hd), f32)
        z_acc[...] = jnp.zeros((n, hd), f32)

    h_ref[0] += wv
    z_acc[...] += z_full

    @pl.when(j == num_eblocks - 1)
    def _normalize():
        h_ref[0] = h_ref[0] / (z_acc[...] + EPS)


def _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask, interpret=False):
    b, n, h, d = q.shape
    kk = nbr_idx.shape[-1]
    e = n * kk
    hd = h * d
    nb = _num_edge_blocks(n)
    eb = e // nb

    kernel = functools.partial(
        _kernel, num_nodes=n, knn=kk, num_heads=h, head_dim=d, num_eblocks=nb
    )
    flat = lambda t: t.reshape(b, -1, hd)  # noqa: E731
    h_out, e_out = pl.pallas_call(
        kernel,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, eb, 1), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, 1), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, hd), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, hd), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, e, hd), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((n, hd), jnp.float32)],
        interpret=interpret,
    )(
        nbr_idx.reshape(b, e, 1).astype(jnp.int32),
        edge_mask.reshape(b, e, 1).astype(jnp.float32),
        flat(q).astype(jnp.float32),
        flat(k).astype(jnp.float32),
        flat(v).astype(jnp.float32),
        flat(proj_e).astype(jnp.float32),
    )
    return h_out.reshape(b, n, h, d), e_out.reshape(b, n, kk, h, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def edge_attention_pallas(q, k, v, proj_e, nbr_idx, edge_mask, interpret=False):
    """Drop-in replacement for ``edge_attention(..., mode='scatter')`` on
    TPU for buckets with N <= MAX_KERNEL_NODES. Returns (h_out, e_out)."""
    return _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask, interpret)


def _fwd(q, k, v, proj_e, nbr_idx, edge_mask, interpret=False):
    out = _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask, interpret)
    return out, (q, k, v, proj_e, nbr_idx, edge_mask)


def _bwd(interpret, res, grads):
    q, k, v, proj_e, nbr_idx, edge_mask = res
    # Gradients via the semantics-identical jnp reference path: XLA already
    # emits a good backward for the dense formulation, and this guarantees
    # kernel/readback gradient parity by construction.
    _, vjp = jax.vjp(
        lambda q_, k_, v_, pe_: edge_attention(
            q_, k_, v_, pe_, nbr_idx, edge_mask, mode="scatter"
        ),
        q, k, v, proj_e,
    )
    dq, dk, dv, dpe = vjp(grads)
    return dq, dk, dv, dpe, None, None


edge_attention_pallas.defvjp(_fwd, _bwd)


def supports(n: int) -> bool:
    """Whether the kernel applies to this bucket: whole-graph up to 128
    nodes, edge-block grid (requires the 64-multiple bucket sizes the
    loader produces) up to the reference's 256-residue regime."""
    if n <= 128:
        return True
    return n <= MAX_KERNEL_NODES and n % 64 == 0
