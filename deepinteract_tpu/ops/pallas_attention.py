"""Pallas TPU kernel for the fused edge-attention hot loop (scatter mode).

The reference's hottest path is the DGL edge-softmax pipeline
(``deepinteract_modules.py:76-96``); :mod:`deepinteract_tpu.ops.attention`
recasts it as dense algebra with a ``segment_sum`` scatter. This kernel goes
one step further for TPU: **the scatter itself becomes an MXU matmul**.

Key idea: with the dense ``[N, K]`` edge layout, "sum edge quantities into
their destination node" is ``onehot(nbr)^T @ X`` where
``onehot[e, j] = (nbr_flat[e] == j)`` — a [E, N] x [E, HD] contraction the
systolic array eats, instead of a serial scatter the VPU would crawl
through. Likewise "gather Q at each edge's destination" is
``onehot @ Q`` and per-head reductions/broadcasts are matmuls against
block-diagonal 0/1 matrices, so the entire op — score, gate, clip, exp,
normalize, aggregate — runs in one kernel launch with everything resident
in VMEM.

Generation 2 (this revision) — dtype and grid changes driven by the PR-5
end-to-end bf16 policy and the PR-7 attribution data:

* **Policy-dtype inputs, bf16 MXU gathers.** q/k/v/proj_e enter the
  kernel in the caller's compute dtype (bf16 under ``--compute_dtype
  bfloat16``) instead of being upcast to f32 at the launch boundary. The
  one-hot gather selectors are built in the same dtype (0/1 is exact in
  bf16), so the three big ``onehot @ {q,k,v}`` contractions run as native
  bf16 MXU matmuls with ``preferred_element_type=f32`` accumulation —
  FlashAttention's discipline (arXiv:2205.14135): low-precision operands
  on the MXU, f32 softmax/accumulator state. One-hot gathers sum exactly
  one term per output element, so the f32-accumulated gather of bf16
  inputs is EXACT — no numerics change beyond the input rounding the
  policy already applied.
* **Policy-dtype edge outputs.** ``e_out`` — the [B, N, K, H, D] gated
  score tensor, the kernel's largest store — and the backward's ``dpe``
  are written in the input dtype (the caller casts to the compute dtype
  anyway, ``models/geometric_transformer.py``), halving their HBM
  traffic under bf16. ``h_out``/``z_out`` stay f32: ``h_ref`` is the
  cross-edge-block numerator ACCUMULATOR (revisited output block), and
  accumulating in bf16 would lose the f32 softmax discipline.
* **Dtype-aware legality, b16 bf16 unlocked.** ``supports`` scales both
  VMEM gates by the policy dtype's itemsize: the measured whole-batch
  edge-stream bound (gen-1 compiles kept the streamed [B, N*K, H]
  tensors resident across the batch grid dim despite the batch-size-1
  blocks — b16 p128 f32 failed AOT at 20.17 MB) and a new PER-BLOCK
  estimate (:func:`kernel_vmem_estimate`) that sizes the long-context
  grids. Under the bf16 policy the edge streams halve, so b16 p128
  bf16 (10.5 MB — the same bytes as the measured-working b8 f32 point)
  is now accepted while the measured b16 f32 failure stays rejected.
  Misestimates cannot ship silently: the autotuner records failed trial
  compiles per config, and auto-routing consults the measured A/B
  evidence (:func:`resolve_attention_impl`).
* **Long-context legality.** ``MAX_KERNEL_NODES`` is 512 (2x the
  reference's 256-residue cap), with finer default edge-block grids past
  n=256 so the [EB, N] selectors stay small; p384/p512 buckets (and
  ``models/tiled.py``'s 512-pad tiles' encoder legs) dispatch through the
  kernel instead of the jnp fallback.

Numerics vs ``edge_attention(..., mode='scatter')``: bit-compatible for the
single-block float32 formulation (n <= 128, same clip/eps constants and
float accumulation order); for the blocked path (n > 128) each destination
node's softmax numerator/denominator sums are split across edge blocks,
which changes float accumulation order — parity there is tolerance-level
(~1e-5, see tests/test_pallas_attention.py), not bitwise. Under bf16 the
kernel computes per-edge scores in f32 from exact bf16 inputs where the
jnp path computes them in bf16 — the kernel is the more precise of the
two; parity is at bf16 tolerance.

Backward is a fused Pallas kernel in the same edge-block grid
(``_bwd_kernel``): it recomputes the per-edge forward quantities from the
saved inputs plus the forward's denominator output, then forms every
gradient scatter as the transposed one-hot matmul — gradient parity vs
the jnp path's VJP is tested at 1e-5 (f32).
"""

from __future__ import annotations

import functools
import json
import logging
import os
import threading

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepinteract_tpu.ops.attention import CLIP, EPS, edge_attention

logger = logging.getLogger(__name__)

# Largest supported padded bucket — 2x the reference's RESIDUE_COUNT_LIMIT,
# covering the long-context tier (p384/p512 buckets and models/tiled.py's
# 512-pad tiles). Legality past 256 comes from the finer default edge-block
# grids below (the [EB, N] one-hot selectors are the n-scaling term of the
# per-block working set; see kernel_vmem_estimate).
MAX_KERNEL_NODES = 512

# Per-block VMEM budget for the legality estimate: a 16 MB core minus
# headroom for Mosaic's block pipelining and fused temporaries the
# estimate does not itemize. Calibrated so the known-good gen-1 points
# (p128 f32 fwd+bwd at any batch, p256 with the default grids) pass and
# oversized single-block overrides fail. A config that passes here can
# still fail a real AOT compile, which the autotuner records as a failed
# trial rather than adopting.
VMEM_BUDGET_BYTES = 14 * 1024 * 1024


def _num_edge_blocks(n: int, override=None) -> int:
    if override is not None:
        return int(override)
    if n <= 128:
        return 1
    if n <= 256:
        return n // 64
    # Long-context tier: halve the edge block again — the [EB, N]
    # selector is EB*N*itemsize and N itself doubled.
    return n // 32


def _num_edge_blocks_bwd(n: int, override=None) -> int:
    if override is not None:
        return int(override)
    # The backward kernel holds ~2x the per-edge working set of forward
    # (both gradient and recomputed-forward tiles), so it halves the edge
    # block relative to forward at every tier.
    if n <= 128:
        return 1
    if n <= 256:
        return n // 32
    return n // 16


def edge_block_options(n: int, knn: int = 20, backward: bool = False,
                       ) -> tuple:
    """Legal edge-block grid sizes for a bucket — the tunable axis the
    autotuner searches (``tuning/space.py``).

    Legality is structural only: the block count must divide the edge
    list evenly and leave sublane-aligned blocks of useful size. Whether
    a legal grid is FAST (or even fits VMEM at a given batch) is exactly
    what the tuner measures — an over-aggressive grid fails its trial's
    compile and is recorded as a failed config, not guessed at here. The
    built-in heuristic values are always included."""
    e = n * knn
    default = _num_edge_blocks_bwd(n) if backward else _num_edge_blocks(n)
    opts = {default} if e % default == 0 else set()
    for nb in (1, 2, 3, 4, 6, 8, 12, 16, 24, 32, 40, 64):
        if e % nb:
            continue
        eb = e // nb
        if eb % 8 or eb < 128:  # sublane alignment / degenerate blocks
            continue
        opts.add(nb)
    return tuple(sorted(opts))


def _check_blocks(n: int, knn: int, nb: int, tag: str) -> None:
    e = n * knn
    if e % nb:
        raise ValueError(
            f"pallas edge attention: {tag} block count {nb} does not "
            f"divide the edge list (n={n}, knn={knn}, E={e}); legal "
            f"counts: {edge_block_options(n, knn)}")


def _itemsize(dtype) -> int:
    """Bytes per element of a compute dtype ('bfloat16'/'float32' strings
    or jnp dtypes); unknown dtypes count as 4 (conservative)."""
    if dtype is None:
        return 4
    try:
        return int(jnp.dtype(dtype).itemsize)
    except TypeError:
        return 4


def _canonical_dtype(dtype):
    """The in-kernel operand dtype for a caller dtype: bf16 stays bf16,
    everything else (f32, f64, ints from sloppy callers) runs f32."""
    if dtype is not None and jnp.dtype(dtype) == jnp.bfloat16:
        return jnp.bfloat16
    return jnp.float32


def kernel_vmem_estimate(n: int, knn: int = 20, hidden: int = 128,
                         itemsize: int = 4, num_blocks=None,
                         backward: bool = False) -> int:
    """Estimated per-grid-step VMEM bytes of the gen-2 kernel.

    Batch-independent by construction — every BlockSpec carries a
    batch-size-1 block, so the grid's batch axis changes the step count,
    not the resident set. Itemized streams (lane dim pads to 128):

    * edge tiles: proj_e in + e_out out ([EB, HD] in the input dtype)
      plus ~2 fused f32 per-edge temporaries (scores/weights);
    * one-hot selectors: dst + src [EB, N], one copy in the input dtype
      (MXU gathers) and one in f32 (scatter contractions);
    * node tensors: q/k/v in the input dtype + h/z/scratch accumulators
      in f32.

    The backward holds roughly the forward set plus the gradient tiles —
    modeled as 2x the edge-stream term (which is why its default block
    count is twice the forward's).

    This is a LEGALITY estimate, not a measurement: it exists to reject
    configurations that are certain not to fit, while the autotuner's
    per-config trial compiles (and the measured A/B evidence consulted by
    :func:`resolve_attention_impl`) gate what actually ships.
    """
    nb = (_num_edge_blocks_bwd if backward else _num_edge_blocks)(
        n, num_blocks)
    e = n * knn
    if e % nb:
        return 1 << 62  # illegal grid: never fits by definition
    eb = e // nb
    lanes = max(hidden, 128)
    npad = max(n, 128)
    edge_streams = eb * lanes * (2 * itemsize + 2 * 4)
    if backward:
        # The gradient tiles (de in, dpe out) join the recomputed forward
        # set, but Mosaic retires the forward temporaries as the gradient
        # chain consumes them — ~1.5x forward, not 2x (gen-1's bwd ran
        # the same n<=128 single-block grid as forward within budget).
        edge_streams = (edge_streams * 3) // 2
    onehots = 2 * eb * npad * (itemsize + 4)
    nodes = npad * lanes * (3 * itemsize + 3 * 4)
    return edge_streams + onehots + nodes


# Empirical whole-batch edge-stream bound: Mosaic was MEASURED (gen-1,
# on the same batch-tiled grid this kernel still uses) keeping the
# streamed [B, N*K, H] edge tensors resident across the batch grid dim —
# b16 p128 f32 allocated 20.17 MB and failed AOT compile with 'Ran out
# of memory in memory space vmem' while b8 p128 f32 (~10.5 MB) compiled
# and ran. The calibration point: the bound is the measured-working
# ~10.5 MB plus headroom.
BATCH_EDGE_BUDGET_BYTES = 12 * 1024 * 1024


def supports(n: int, batch: int = 1, knn: int = 20, hidden: int = 128,
             num_heads: int = 4, dtype=None) -> bool:
    """Whether the kernel applies to this bucket: whole-graph up to 128
    nodes, edge-block grid (requires the 64-multiple bucket sizes the
    loader produces) up to ``MAX_KERNEL_NODES`` (2x the reference's
    256-residue regime).

    Two VMEM gates, both dtype-aware since gen-2:

    * the MEASURED whole-batch edge-stream bound
      (``BATCH_EDGE_BUDGET_BYTES``): despite the batch-tiled grid,
      gen-1 compiles showed per-batch edge streams held resident across
      the batch grid dim (b16 p128 f32 failed AOT at 20.17 MB; b8 fit
      at ~10.5 MB). The bound now scales with the POLICY dtype's
      itemsize, so the b16 p128 refusal lifts exactly for the bf16
      policy (16*128*20*128*2 = 10.5 MB — the same bytes as the
      measured-working b8 f32 point) while b16 f32 (21 MB, the measured
      failure) stays rejected;
    * the PER-BLOCK estimate (:func:`kernel_vmem_estimate`) for the
      block-level working set the long-context grids are sized against.

    The hidden/head floor excludes degenerate-tiling configs: lanes pad
    the channel dim to 128, so tiny models inflate the stack instead of
    shrinking it (measured on gen-1: hidden=8 / head_dim=4 at n=128
    allocated 16.18 M and failed AOT compile — a smoke config, not a perf
    target; such models route to the jnp path, where they are fast
    anyway)."""
    if hidden < 64 or hidden // max(num_heads, 1) < 16:
        return False
    item = _itemsize(_canonical_dtype(dtype))
    if batch * n * knn * hidden * item > BATCH_EDGE_BUDGET_BYTES:
        return False
    if kernel_vmem_estimate(n, knn, hidden, item) > VMEM_BUDGET_BYTES:
        return False
    if kernel_vmem_estimate(n, knn, hidden, item,
                            backward=True) > VMEM_BUDGET_BYTES:
        return False
    if n <= 128:
        return True
    return n <= MAX_KERNEL_NODES and n % 64 == 0


def supports_config(gnn_cfg, n: int, batch: int = 1, knn: int = 20) -> bool:
    """:func:`supports` with ``hidden``/``num_heads``/``compute_dtype``
    taken from a real ``GTConfig`` instead of assumed defaults.

    Call-site guard for code that holds a model config rather than runtime
    tensor shapes (bench.py's A/B section; the serving engine's warmup
    legality; the model itself threads the live shapes at
    ``models/geometric_transformer.py``). A caller that passed only ``n``
    would silently evaluate the head-dim floor against the flagship
    defaults instead of the measured configuration (round-5 advisor
    finding) — and, since gen-2, the dtype-aware VMEM estimate against
    f32 instead of the configured policy dtype."""
    return supports(n, batch=batch, knn=knn,
                    hidden=gnn_cfg.hidden, num_heads=gnn_cfg.num_heads,
                    dtype=getattr(gnn_cfg, "compute_dtype", None))


# ---------------------------------------------------------------------------
# Measured-A/B routing evidence (autotune-guarded kernel adoption)
# ---------------------------------------------------------------------------

# Evidence file (attention_ab/v1): written by tools/scan_ab.py and bench's
# inline A/B, consulted by auto routing so a bucket where the kernel
# measurably LOSES (BENCH_r05: 0.97x forward at b1 p128) can never ship as
# the default again. {"schema": "attention_ab/v1", "entries":
#   {"b8_p128": {"bfloat16": {"train_scan_speedup": 1.14, ...}}}}
ATTENTION_AB_ENV = "DI_ATTENTION_AB"
AB_SCHEMA = "attention_ab/v1"
# Speedups at or below this are a measured loss -> auto routes to jnp.
AB_LOSS_THRESHOLD = 1.0

_ab_lock = threading.Lock()
_ab_cache: dict = {"path": None, "mtime": None, "data": None}
_route_logged: set = set()


def attention_ab_path() -> str:
    return os.environ.get(ATTENTION_AB_ENV, "")


def load_attention_ab(path: str = "") -> dict:
    """The evidence entries mapping (empty when unset/unreadable — a
    corrupt evidence file must degrade to 'no opinion', not crash the
    model's forward)."""
    path = path or attention_ab_path()
    if not path or not os.path.exists(path):
        return {}
    try:
        mtime = os.path.getmtime(path)
        with _ab_lock:
            if _ab_cache["path"] == path and _ab_cache["mtime"] == mtime:
                return _ab_cache["data"]
        with open(path) as fh:
            blob = json.load(fh)
        entries = blob.get("entries", {}) if isinstance(blob, dict) else {}
        with _ab_lock:
            _ab_cache.update(path=path, mtime=mtime, data=entries)
        return entries
    except (OSError, ValueError):
        return {}


def record_attention_ab(path: str, batch: int, pad: int, dtype: str,
                        **speedups) -> None:
    """Merge one bucket's measured Pallas-vs-jnp speedups into the
    evidence file (atomic rewrite). ``speedups`` keys are e.g.
    ``train_scan_speedup`` / ``forward_speedup`` — jnp_time / pallas_time,
    so <= 1.0 means the kernel lost."""
    entries: dict = {}
    if os.path.exists(path):
        try:
            with open(path) as fh:
                entries = json.load(fh).get("entries", {})
        except (OSError, ValueError):
            entries = {}
    bucket = f"b{int(batch)}_p{int(pad)}"
    per_dtype = entries.setdefault(bucket, {}).setdefault(str(dtype), {})
    per_dtype.update({k: float(v) for k, v in speedups.items()
                      if v is not None})
    from deepinteract_tpu.robustness import artifacts

    artifacts.atomic_write(
        path, json.dumps({"schema": AB_SCHEMA, "entries": entries},
                         indent=2) + "\n")
    with _ab_lock:
        _ab_cache.update(path=None, mtime=None, data=None)


def measured_loss_reason(n: int, batch: int, dtype) -> str:
    """Non-empty reason string when the evidence store records the kernel
    LOSING (speedup <= AB_LOSS_THRESHOLD) for this exact bucket+dtype;
    '' = no adverse evidence (missing evidence is not a loss).

    Key precedence mirrors the repo's measurement lore (BASELINE.md):
    ``train_scan_speedup`` — the K-step scanned dispatch — is the
    decision-grade figure and, when present, decides ALONE; the
    single-dispatch forward/train ratios carry ±10-20% tunnel spread and
    are consulted only when no scanned evidence exists (so one noisy
    single-dispatch rep cannot demote a bucket whose scanned A/B shows a
    real win)."""
    entries = load_attention_ab()
    if not entries:
        return ""
    per_dtype = entries.get(f"b{int(batch)}_p{int(n)}", {})
    ev = per_dtype.get(str(jnp.dtype(_canonical_dtype(dtype)).name), {})
    speedups = {k: v for k, v in ev.items()
                if k.endswith("speedup") and isinstance(v, (int, float))}
    if not speedups:
        return ""
    if "train_scan_speedup" in speedups:
        judged = {"train_scan_speedup": speedups["train_scan_speedup"]}
    else:
        judged = speedups
    worst_key = min(judged, key=judged.get)
    if judged[worst_key] <= AB_LOSS_THRESHOLD:
        return (f"measured A/B shows pallas {judged[worst_key]:.3f}x "
                f"({worst_key}) <= {AB_LOSS_THRESHOLD}x vs jnp for "
                f"b{batch}_p{n}")
    return ""


def resolve_attention_impl(attention_mode: str, attention_impl: str,
                           n: int, batch: int = 1, knn: int = 20,
                           hidden: int = 128, num_heads: int = 4,
                           dtype=None, backend: str = "") -> tuple:
    """The routing decision ``(impl, reason)`` with impl in
    {'pallas', 'jnp'} — the pure function behind ``_dispatch_attention``
    (``models/geometric_transformer.py``), split out so the policy is
    testable off-TPU.

    ``auto`` uses the kernel wherever (a) the Mosaic TPU backend is
    present, (b) :func:`supports` accepts the shape/dtype, and (c) the
    measured A/B evidence store (``DI_ATTENTION_AB``) does not record the
    kernel LOSING for the bucket — the autotune guard that makes the
    BENCH_r05 0.97x-forward default unshippable. 'pallas' forces the
    kernel on supported shapes regardless of evidence (the bench A/B
    itself needs that); 'jnp' forces the reference path."""
    if attention_mode != "scatter" or attention_impl == "jnp":
        return "jnp", "jnp forced (impl or non-scatter mode)"
    if not supports(n, batch=batch, knn=knn, hidden=hidden,
                    num_heads=num_heads, dtype=dtype):
        return "jnp", f"kernel does not support shape n={n} (see supports())"
    if attention_impl == "pallas":
        return "pallas", "pallas forced"
    if backend != "tpu":
        return "jnp", "auto: non-TPU backend"
    reason = measured_loss_reason(n, batch, dtype)
    if reason:
        key = (n, batch, str(dtype))
        if key not in _route_logged:
            _route_logged.add(key)
            logger.info("attention auto-routing picks jnp: %s", reason)
        return "jnp", reason
    return "pallas", "auto: supported bucket, no adverse A/B evidence"


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _kernel(nbr_ref, mask_ref, q_ref, k_ref, v_ref, pe_ref, h_ref, e_ref,
            z_ref, z_acc, *, num_nodes: int, knn: int, num_heads: int,
            head_dim: int, num_eblocks: int):
    n, kk, h, d = num_nodes, knn, num_heads, head_dim
    hd = h * d
    eb = n * kk // num_eblocks  # edges per grid block
    f32 = jnp.float32
    j = pl.program_id(1)

    nbr = nbr_ref[0]          # [EB, 1] int32
    mask = mask_ref[0]        # [EB, 1] f32
    q = q_ref[0]              # [N, HD] in the input (policy) dtype
    k = k_ref[0]
    v = v_ref[0]
    pe = pe_ref[0]            # [EB, HD] in the input dtype
    in_dtype = q.dtype

    node_ids = jax.lax.broadcasted_iota(jnp.int32, (eb, n), 1)
    onehot_dst_b = (nbr == node_ids)                                # [EB, N]
    src_ids = (jax.lax.broadcasted_iota(jnp.int32, (eb, 1), 0) + j * eb) // kk
    onehot_src_b = (src_ids == node_ids)                            # [EB, N]
    # Gather selectors in the input dtype (bf16 MXU matmuls against the
    # bf16 inputs; 0/1 is exact in bf16) ...
    onehot_dst = onehot_dst_b.astype(in_dtype)
    onehot_src = onehot_src_b.astype(in_dtype)
    # ... scatter selectors in f32: the scatter contracts against f32
    # softmax-weighted values (accumulation discipline, ops/attention.py).
    onehot_dst_f = onehot_dst_b.astype(f32)

    # Per-head sum / broadcast as block-diagonal 0/1 matmuls (f32: they
    # contract the f32 score/weight tensors).
    lane_head = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 0) // d
    head_ids = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 1)
    sum_mat = (lane_head == head_ids).astype(f32)                   # [HD, H]

    dot = functools.partial(jnp.dot, preferred_element_type=f32)
    # One-hot gathers: exactly one nonzero per row, so the f32-accumulated
    # result of bf16 operands is EXACT (no summation error to accumulate).
    q_dst = dot(onehot_dst, q)                                      # [EB, HD]
    k_src = dot(onehot_src, k)
    v_src = dot(onehot_src, v)

    inv_sqrt_d = 1.0 / (d ** 0.5)
    scaled = jnp.clip(k_src * q_dst * inv_sqrt_d, -CLIP, CLIP) * pe  # [EB, HD]
    logits = jnp.clip(dot(scaled, sum_mat), -CLIP, CLIP)             # [EB, H]
    w = jnp.exp(logits) * mask                                       # [EB, H]

    w_full = dot(w, sum_mat.T)                                       # [EB, HD]
    x = w_full * v_src
    wv = jax.lax.dot_general(onehot_dst_f, x, (((0,), (0,)), ((), ())),
                             preferred_element_type=f32)             # [N, HD]
    z = jax.lax.dot_general(onehot_dst_f, w, (((0,), (0,)), ((), ())),
                            preferred_element_type=f32)              # [N, H]
    z_full = dot(z, sum_mat.T)                                       # [N, HD]

    # The edge output is stored in the input dtype (the caller casts to
    # the compute dtype anyway) — the kernel's largest store, halved
    # under bf16. A no-op cast under f32 keeps gen-1 bit-compatibility.
    e_ref[0] = (scaled * mask).astype(e_ref.dtype)

    # Numerator accumulates in the revisited output block, denominator in
    # scratch; both zeroed on the first edge block, normalized on the last.
    @pl.when(j == 0)
    def _init():
        h_ref[0] = jnp.zeros((n, hd), f32)
        z_acc[...] = jnp.zeros((n, hd), f32)

    h_ref[0] += wv
    z_acc[...] += z_full

    @pl.when(j == num_eblocks - 1)
    def _normalize():
        h_ref[0] = h_ref[0] / (z_acc[...] + EPS)
        z_ref[0] = z_acc[...]


def _bwd_kernel(nbr_ref, mask_ref, q_ref, k_ref, v_ref, pe_ref, h_ref, z_ref,
                dh_ref, de_ref, dq_ref, dk_ref, dv_ref, dpe_ref, *,
                num_nodes: int, knn: int, num_heads: int, head_dim: int,
                num_eblocks: int):
    """Fused backward in the forward's edge-block grid.

    Per block: recompute the per-edge forward quantities (scores, clips,
    softmax weights) from the saved inputs plus the forward's denominator
    ``z`` and normalized output ``h``, then form every gradient scatter as
    the transposed one-hot matmul. dq/dk/dv accumulate in revisited f32
    [N, HD] output blocks across edge blocks (TPU grids iterate the last
    dim sequentially); dpe is per-edge-block, stored in the input dtype.

    Gradient math (e = edge, n = dst, s = src, heads h, dims d):
      num_nd = sum_e w_eh v_sd,  Z_nh = sum_e w_eh,  h = num / (Z + eps)
      dnum = dh / (Z + eps);  dZ_nh = -sum_{d in h} h_nd dh_nd / (Z + eps)
      dw_eh = sum_{d in h} dnum_nd v_sd + dZ_nh
      dv_sd += w_eh dnum_nd            (scatter to src)
      dl = dw * w;  dsum = dl * 1{|sum_pre| < C}
      ds = broadcast(dsum) + de * mask  (e_out = s * mask)
      dpe = ds * c;  dc = ds * pe;  da = dc * 1{|a| < C} / sqrt(d)
      dq_nd += da k_sd;  dk_sd += da q_nd  (scatter to dst / src)
    """
    n, kk, h, d = num_nodes, knn, num_heads, head_dim
    hd = h * d
    eb = n * kk // num_eblocks
    f32 = jnp.float32
    j = pl.program_id(1)

    nbr = nbr_ref[0]
    mask = mask_ref[0]
    q = q_ref[0]              # input (policy) dtype
    k = k_ref[0]
    v = v_ref[0]
    pe = pe_ref[0]
    h_saved = h_ref[0]        # f32 residuals
    zf = z_ref[0]
    dh = dh_ref[0]            # f32 cotangent (h_out is f32)
    de = de_ref[0]            # input-dtype cotangent (e_out dtype)
    in_dtype = q.dtype

    node_ids = jax.lax.broadcasted_iota(jnp.int32, (eb, n), 1)
    onehot_dst_b = (nbr == node_ids)
    src_ids = (jax.lax.broadcasted_iota(jnp.int32, (eb, 1), 0) + j * eb) // kk
    onehot_src_b = (src_ids == node_ids)
    onehot_dst = onehot_dst_b.astype(in_dtype)   # bf16 MXU gathers
    onehot_src = onehot_src_b.astype(in_dtype)
    onehot_dst_f = onehot_dst_b.astype(f32)      # f32 scatters
    onehot_src_f = onehot_src_b.astype(f32)

    lane_head = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 0) // d
    head_ids = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 1)
    sum_mat = (lane_head == head_ids).astype(f32)

    dot = functools.partial(jnp.dot, preferred_element_type=f32)

    def scatter(onehot, x):  # [EB, N]^T @ [EB, X] -> [N, X], f32
        return jax.lax.dot_general(onehot, x, (((0,), (0,)), ((), ())),
                                   preferred_element_type=f32)

    # Recomputed forward per-edge quantities (gathers of the policy-dtype
    # inputs are exact in f32 accumulation — see _kernel).
    q_dst = dot(onehot_dst, q)
    k_src = dot(onehot_src, k)
    v_src = dot(onehot_src, v)
    inv_sqrt_d = 1.0 / (d ** 0.5)
    a = k_src * q_dst * inv_sqrt_d
    c = jnp.clip(a, -CLIP, CLIP)
    s = c * pe
    sum_pre = dot(s, sum_mat)                                    # [EB, H]
    w = jnp.exp(jnp.clip(sum_pre, -CLIP, CLIP)) * mask           # [EB, H]
    w_full = dot(w, sum_mat.T)                                   # [EB, HD]

    # Node-level gradient terms (cheap, recomputed every block).
    invz = 1.0 / (zf + EPS)                                      # [N, HD]
    dnum = dh * invz
    dz_h = -dot(h_saved * dnum, sum_mat)                         # [N, H]

    dnum_dst = dot(onehot_dst_f, dnum)                           # [EB, HD]
    dz_dst = dot(onehot_dst_f, dz_h)                             # [EB, H]
    dw = dot(dnum_dst * v_src, sum_mat) + dz_dst                 # [EB, H]
    dl = dw * w
    dsum = jnp.where((sum_pre > -CLIP) & (sum_pre < CLIP), dl, 0.0)
    ds = dot(dsum, sum_mat.T) + de * mask                        # [EB, HD]
    dpe_ref[0] = (ds * c).astype(dpe_ref.dtype)
    dc = ds * pe
    da = jnp.where((a > -CLIP) & (a < CLIP), dc, 0.0) * inv_sqrt_d

    @pl.when(j == 0)
    def _init():
        dq_ref[0] = jnp.zeros((n, hd), f32)
        dk_ref[0] = jnp.zeros((n, hd), f32)
        dv_ref[0] = jnp.zeros((n, hd), f32)

    dq_ref[0] += scatter(onehot_dst_f, da * k_src)
    dk_ref[0] += scatter(onehot_src_f, da * q_dst)
    dv_ref[0] += scatter(onehot_src_f, w_full * dnum_dst)


def _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask, interpret=False,
                    num_blocks=None):
    b, n, h, d = q.shape
    kk = nbr_idx.shape[-1]
    e = n * kk
    hd = h * d
    nb = _num_edge_blocks(n, num_blocks)
    _check_blocks(n, kk, nb, "forward")
    eb = e // nb
    in_dtype = _canonical_dtype(q.dtype)

    kernel = functools.partial(
        _kernel, num_nodes=n, knn=kk, num_heads=h, head_dim=d, num_eblocks=nb
    )
    flat = lambda t: t.reshape(b, -1, hd)  # noqa: E731
    h_out, e_out, z_out = pl.pallas_call(
        kernel,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, eb, 1), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, 1), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, hd), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, hd), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, e, hd), in_dtype),
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((n, hd), jnp.float32)],
        interpret=interpret,
    )(
        nbr_idx.reshape(b, e, 1).astype(jnp.int32),
        edge_mask.reshape(b, e, 1).astype(jnp.float32),
        flat(q).astype(in_dtype),
        flat(k).astype(in_dtype),
        flat(v).astype(in_dtype),
        flat(proj_e).astype(in_dtype),
    )
    return h_out.reshape(b, n, h, d), e_out.reshape(b, n, kk, h, d), z_out


def _pallas_backward(q, k, v, proj_e, nbr_idx, edge_mask, h_out, z_out,
                     dh, de, interpret=False, num_blocks=None):
    b, n, h, d = q.shape
    kk = nbr_idx.shape[-1]
    e = n * kk
    hd = h * d
    nb = _num_edge_blocks_bwd(n, num_blocks)
    _check_blocks(n, kk, nb, "backward")
    eb = e // nb
    in_dtype = _canonical_dtype(q.dtype)

    kernel = functools.partial(
        _bwd_kernel, num_nodes=n, knn=kk, num_heads=h, head_dim=d,
        num_eblocks=nb,
    )
    flat = lambda t: t.reshape(b, -1, hd)  # noqa: E731
    node_spec = pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    edge_spec = pl.BlockSpec((1, eb, hd), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM)
    idx_spec = pl.BlockSpec((1, eb, 1), lambda i, j: (i, j, 0),
                            memory_space=pltpu.VMEM)
    dq, dk, dv, dpe = pl.pallas_call(
        kernel,
        grid=(b, nb),
        in_specs=[idx_spec, idx_spec, node_spec, node_spec, node_spec,
                  edge_spec, node_spec, node_spec, node_spec, edge_spec],
        out_specs=[node_spec, node_spec, node_spec, edge_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, e, hd), in_dtype),
        ],
        interpret=interpret,
    )(
        nbr_idx.reshape(b, e, 1).astype(jnp.int32),
        edge_mask.reshape(b, e, 1).astype(jnp.float32),
        flat(q).astype(in_dtype),
        flat(k).astype(in_dtype),
        flat(v).astype(in_dtype),
        flat(proj_e).astype(in_dtype),
        flat(h_out).astype(jnp.float32),
        z_out.astype(jnp.float32),
        flat(dh).astype(jnp.float32),
        flat(de).astype(in_dtype),
    )
    return (dq.reshape(b, n, h, d), dk.reshape(b, n, h, d),
            dv.reshape(b, n, h, d), dpe.reshape(b, n, kk, h, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def edge_attention_pallas(q, k, v, proj_e, nbr_idx, edge_mask,
                          interpret=False, fwd_blocks=None, bwd_blocks=None):
    """Drop-in replacement for ``edge_attention(..., mode='scatter')`` on
    TPU for buckets with N <= MAX_KERNEL_NODES. Returns (h_out, e_out) —
    h_out in f32 (the softmax accumulator), e_out in the input dtype.

    ``fwd_blocks``/``bwd_blocks`` override the edge-block grid sizes
    (None = the built-in per-bucket heuristic). These are the real
    block-shape parameters the autotuner searches — see
    :func:`edge_block_options` for legality and ``tuning/space.py`` for
    the axis definition. Numerics: a different block count only changes
    float accumulation order across edge blocks (tolerance-level parity,
    same as the existing n > 128 path)."""
    h_out, e_out, _ = _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask,
                                      interpret, fwd_blocks)
    return h_out, e_out


def _fwd(q, k, v, proj_e, nbr_idx, edge_mask, interpret=False,
         fwd_blocks=None, bwd_blocks=None):
    h_out, e_out, z_out = _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask,
                                          interpret, fwd_blocks)
    # h and z (the softmax denominator) ride along as residuals so the
    # backward kernel never re-runs the full forward — it recomputes only
    # the per-edge quantities block-locally. q/k/v/proj_e residuals stay
    # in the policy dtype (half the residual bytes under bf16).
    return (h_out, e_out), (q, k, v, proj_e, nbr_idx, edge_mask, h_out, z_out)


def _bwd(interpret, fwd_blocks, bwd_blocks, res, grads):
    q, k, v, proj_e, nbr_idx, edge_mask, h_out, z_out = res
    dh, de = grads
    dq, dk, dv, dpe = _pallas_backward(
        q, k, v, proj_e, nbr_idx, edge_mask, h_out, z_out, dh, de, interpret,
        bwd_blocks,
    )
    # dq/dk/dv accumulate in float32 in-kernel; cotangents must match the
    # primals' dtypes — under a bf16 compute policy q/k/v/proj_e arrive
    # bf16 while the f32 accumulation above stays intact (dpe is already
    # written in the input dtype by the kernel).
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dpe.astype(proj_e.dtype), None, None)


edge_attention_pallas.defvjp(_fwd, _bwd)
