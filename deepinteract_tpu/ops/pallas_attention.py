"""Pallas TPU kernel for the fused edge-attention hot loop (scatter mode).

The reference's hottest path is the DGL edge-softmax pipeline
(``deepinteract_modules.py:76-96``); :mod:`deepinteract_tpu.ops.attention`
recasts it as dense algebra with a ``segment_sum`` scatter. This kernel goes
one step further for TPU: **the scatter itself becomes an MXU matmul**.

Key idea: with the dense ``[N, K]`` edge layout, "sum edge quantities into
their destination node" is ``onehot(nbr)^T @ X`` where
``onehot[e, j] = (nbr_flat[e] == j)`` — a [E, N] x [E, HD] contraction the
systolic array eats, instead of a serial scatter the VPU would crawl
through. Likewise "gather Q at each edge's destination" is
``onehot @ Q`` and per-head reductions/broadcasts are matmuls against
block-diagonal 0/1 matrices, so the entire op — score, gate, clip, exp,
normalize, aggregate — runs in one kernel launch with everything resident
in VMEM.

Numerics vs ``edge_attention(..., mode='scatter')``: bit-compatible for the
single-block formulation (n <= 128, same clip/eps constants and float
accumulation order); for the blocked path (n > 128) each destination
node's softmax numerator/denominator sums are split across edge blocks,
which changes float accumulation order — parity there is tolerance-level
(~1e-5, see tests/test_pallas_attention.py), not bitwise.

Scope: an edge-block grid keeps every working set in VMEM at any bucket up
to ``MAX_KERNEL_NODES`` (the full reference regime — 256 residues,
deepinteract_constants.py:10-12). Buckets <= 128 nodes run as one block
(whole graph resident); larger buckets split the edge list into
``n // 64`` blocks, accumulate the per-node numerator in the (revisited)
output block and the softmax denominator in VMEM scratch, and normalize in
the final grid step. Backward is a fused Pallas kernel in the same
edge-block grid (``_bwd_kernel``): it recomputes the per-edge forward
quantities from the saved inputs plus the forward's denominator output,
then forms every gradient scatter as the transposed one-hot matmul —
gradient parity vs the jnp path's VJP is tested at 1e-5.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepinteract_tpu.ops.attention import CLIP, EPS, edge_attention

# Largest supported padded bucket (= the reference's RESIDUE_COUNT_LIMIT).
# Per-block VMEM at N=256, K=20, HD=128 with n//64 = 4 edge blocks:
# two [1280, 256] one-hot selectors (~1.3 MB each), [1280, 128] edge tiles
# (~0.65 MB each) and two [256, 128] accumulators — comfortably inside a
# v5e core's ~16 MB VMEM (the whole-graph formulation needs ~26 MB there).
MAX_KERNEL_NODES = 256


def _num_edge_blocks(n: int, override=None) -> int:
    if override is not None:
        return int(override)
    return 1 if n <= 128 else n // 64


def _num_edge_blocks_bwd(n: int, override=None) -> int:
    if override is not None:
        return int(override)
    # The backward kernel holds ~2x the per-edge working set of forward
    # (both gradient and recomputed-forward tiles), so it halves the edge
    # block relative to forward to stay comfortably inside VMEM at n=256.
    return 1 if n <= 128 else n // 32


def edge_block_options(n: int, knn: int = 20, backward: bool = False,
                       ) -> tuple:
    """Legal edge-block grid sizes for a bucket — the tunable axis the
    autotuner searches (``tuning/space.py``).

    Legality is structural only: the block count must divide the edge
    list evenly and leave sublane-aligned blocks of useful size. Whether
    a legal grid is FAST (or even fits VMEM at a given batch) is exactly
    what the tuner measures — an over-aggressive grid fails its trial's
    compile and is recorded as a failed config, not guessed at here. The
    built-in heuristic values are always included."""
    e = n * knn
    default = _num_edge_blocks_bwd(n) if backward else _num_edge_blocks(n)
    opts = {default} if e % default == 0 else set()
    for nb in (1, 2, 3, 4, 6, 8, 12, 16, 24, 32):
        if e % nb:
            continue
        eb = e // nb
        if eb % 8 or eb < 128:  # sublane alignment / degenerate blocks
            continue
        opts.add(nb)
    return tuple(sorted(opts))


def _check_blocks(n: int, knn: int, nb: int, tag: str) -> None:
    e = n * knn
    if e % nb:
        raise ValueError(
            f"pallas edge attention: {tag} block count {nb} does not "
            f"divide the edge list (n={n}, knn={knn}, E={e}); legal "
            f"counts: {edge_block_options(n, knn)}")


def _kernel(nbr_ref, mask_ref, q_ref, k_ref, v_ref, pe_ref, h_ref, e_ref,
            z_ref, z_acc, *, num_nodes: int, knn: int, num_heads: int,
            head_dim: int, num_eblocks: int):
    n, kk, h, d = num_nodes, knn, num_heads, head_dim
    hd = h * d
    eb = n * kk // num_eblocks  # edges per grid block
    f32 = jnp.float32
    j = pl.program_id(1)

    nbr = nbr_ref[0]          # [EB, 1] int32
    mask = mask_ref[0]        # [EB, 1] f32
    q = q_ref[0]              # [N, HD]
    k = k_ref[0]
    v = v_ref[0]
    pe = pe_ref[0]            # [EB, HD]

    node_ids = jax.lax.broadcasted_iota(jnp.int32, (eb, n), 1)
    onehot_dst = (nbr == node_ids).astype(f32)                      # [EB, N]
    src_ids = (jax.lax.broadcasted_iota(jnp.int32, (eb, 1), 0) + j * eb) // kk
    onehot_src = (src_ids == node_ids).astype(f32)                  # [EB, N]

    # Per-head sum / broadcast as block-diagonal 0/1 matmuls.
    lane_head = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 0) // d
    head_ids = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 1)
    sum_mat = (lane_head == head_ids).astype(f32)                   # [HD, H]

    dot = functools.partial(jnp.dot, preferred_element_type=f32)
    q_dst = dot(onehot_dst, q)                                      # [EB, HD]
    k_src = dot(onehot_src, k)
    v_src = dot(onehot_src, v)

    inv_sqrt_d = 1.0 / (d ** 0.5)
    scaled = jnp.clip(k_src * q_dst * inv_sqrt_d, -CLIP, CLIP) * pe  # [EB, HD]
    logits = jnp.clip(dot(scaled, sum_mat), -CLIP, CLIP)             # [EB, H]
    w = jnp.exp(logits) * mask                                       # [EB, H]

    w_full = dot(w, sum_mat.T)                                       # [EB, HD]
    x = w_full * v_src
    wv = jax.lax.dot_general(onehot_dst, x, (((0,), (0,)), ((), ())),
                             preferred_element_type=f32)             # [N, HD]
    z = jax.lax.dot_general(onehot_dst, w, (((0,), (0,)), ((), ())),
                            preferred_element_type=f32)              # [N, H]
    z_full = dot(z, sum_mat.T)                                       # [N, HD]

    e_ref[0] = scaled * mask

    # Numerator accumulates in the revisited output block, denominator in
    # scratch; both zeroed on the first edge block, normalized on the last.
    @pl.when(j == 0)
    def _init():
        h_ref[0] = jnp.zeros((n, hd), f32)
        z_acc[...] = jnp.zeros((n, hd), f32)

    h_ref[0] += wv
    z_acc[...] += z_full

    @pl.when(j == num_eblocks - 1)
    def _normalize():
        h_ref[0] = h_ref[0] / (z_acc[...] + EPS)
        z_ref[0] = z_acc[...]


def _bwd_kernel(nbr_ref, mask_ref, q_ref, k_ref, v_ref, pe_ref, h_ref, z_ref,
                dh_ref, de_ref, dq_ref, dk_ref, dv_ref, dpe_ref, *,
                num_nodes: int, knn: int, num_heads: int, head_dim: int,
                num_eblocks: int):
    """Fused backward in the forward's edge-block grid.

    Per block: recompute the per-edge forward quantities (scores, clips,
    softmax weights) from the saved inputs plus the forward's denominator
    ``z`` and normalized output ``h``, then form every gradient scatter as
    the transposed one-hot matmul. dq/dk/dv accumulate in revisited
    [N, HD] output blocks across edge blocks (TPU grids iterate the last
    dim sequentially); dpe is per-edge-block.

    Gradient math (e = edge, n = dst, s = src, heads h, dims d):
      num_nd = sum_e w_eh v_sd,  Z_nh = sum_e w_eh,  h = num / (Z + eps)
      dnum = dh / (Z + eps);  dZ_nh = -sum_{d in h} h_nd dh_nd / (Z + eps)
      dw_eh = sum_{d in h} dnum_nd v_sd + dZ_nh
      dv_sd += w_eh dnum_nd            (scatter to src)
      dl = dw * w;  dsum = dl * 1{|sum_pre| < C}
      ds = broadcast(dsum) + de * mask  (e_out = s * mask)
      dpe = ds * c;  dc = ds * pe;  da = dc * 1{|a| < C} / sqrt(d)
      dq_nd += da k_sd;  dk_sd += da q_nd  (scatter to dst / src)
    """
    n, kk, h, d = num_nodes, knn, num_heads, head_dim
    hd = h * d
    eb = n * kk // num_eblocks
    f32 = jnp.float32
    j = pl.program_id(1)

    nbr = nbr_ref[0]
    mask = mask_ref[0]
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    pe = pe_ref[0]
    h_saved = h_ref[0]
    zf = z_ref[0]
    dh = dh_ref[0]
    de = de_ref[0]

    node_ids = jax.lax.broadcasted_iota(jnp.int32, (eb, n), 1)
    onehot_dst = (nbr == node_ids).astype(f32)
    src_ids = (jax.lax.broadcasted_iota(jnp.int32, (eb, 1), 0) + j * eb) // kk
    onehot_src = (src_ids == node_ids).astype(f32)

    lane_head = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 0) // d
    head_ids = jax.lax.broadcasted_iota(jnp.int32, (hd, h), 1)
    sum_mat = (lane_head == head_ids).astype(f32)

    dot = functools.partial(jnp.dot, preferred_element_type=f32)

    def scatter(onehot, x):  # [EB, N]^T @ [EB, X] -> [N, X]
        return jax.lax.dot_general(onehot, x, (((0,), (0,)), ((), ())),
                                   preferred_element_type=f32)

    # Recomputed forward per-edge quantities.
    q_dst = dot(onehot_dst, q)
    k_src = dot(onehot_src, k)
    v_src = dot(onehot_src, v)
    inv_sqrt_d = 1.0 / (d ** 0.5)
    a = k_src * q_dst * inv_sqrt_d
    c = jnp.clip(a, -CLIP, CLIP)
    s = c * pe
    sum_pre = dot(s, sum_mat)                                    # [EB, H]
    w = jnp.exp(jnp.clip(sum_pre, -CLIP, CLIP)) * mask           # [EB, H]
    w_full = dot(w, sum_mat.T)                                   # [EB, HD]

    # Node-level gradient terms (cheap, recomputed every block).
    invz = 1.0 / (zf + EPS)                                      # [N, HD]
    dnum = dh * invz
    dz_h = -dot(h_saved * dnum, sum_mat)                         # [N, H]

    dnum_dst = dot(onehot_dst, dnum)                             # [EB, HD]
    dz_dst = dot(onehot_dst, dz_h)                               # [EB, H]
    dw = dot(dnum_dst * v_src, sum_mat) + dz_dst                 # [EB, H]
    dl = dw * w
    dsum = jnp.where((sum_pre > -CLIP) & (sum_pre < CLIP), dl, 0.0)
    ds = dot(dsum, sum_mat.T) + de * mask                        # [EB, HD]
    dpe_ref[0] = ds * c
    dc = ds * pe
    da = jnp.where((a > -CLIP) & (a < CLIP), dc, 0.0) * inv_sqrt_d

    @pl.when(j == 0)
    def _init():
        dq_ref[0] = jnp.zeros((n, hd), f32)
        dk_ref[0] = jnp.zeros((n, hd), f32)
        dv_ref[0] = jnp.zeros((n, hd), f32)

    dq_ref[0] += scatter(onehot_dst, da * k_src)
    dk_ref[0] += scatter(onehot_src, da * q_dst)
    dv_ref[0] += scatter(onehot_src, w_full * dnum_dst)


def _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask, interpret=False,
                    num_blocks=None):
    b, n, h, d = q.shape
    kk = nbr_idx.shape[-1]
    e = n * kk
    hd = h * d
    nb = _num_edge_blocks(n, num_blocks)
    _check_blocks(n, kk, nb, "forward")
    eb = e // nb

    kernel = functools.partial(
        _kernel, num_nodes=n, knn=kk, num_heads=h, head_dim=d, num_eblocks=nb
    )
    flat = lambda t: t.reshape(b, -1, hd)  # noqa: E731
    h_out, e_out, z_out = pl.pallas_call(
        kernel,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, eb, 1), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, 1), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, hd), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, eb, hd), lambda i, j: (i, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, e, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((n, hd), jnp.float32)],
        interpret=interpret,
    )(
        nbr_idx.reshape(b, e, 1).astype(jnp.int32),
        edge_mask.reshape(b, e, 1).astype(jnp.float32),
        flat(q).astype(jnp.float32),
        flat(k).astype(jnp.float32),
        flat(v).astype(jnp.float32),
        flat(proj_e).astype(jnp.float32),
    )
    return h_out.reshape(b, n, h, d), e_out.reshape(b, n, kk, h, d), z_out


def _pallas_backward(q, k, v, proj_e, nbr_idx, edge_mask, h_out, z_out,
                     dh, de, interpret=False, num_blocks=None):
    b, n, h, d = q.shape
    kk = nbr_idx.shape[-1]
    e = n * kk
    hd = h * d
    nb = _num_edge_blocks_bwd(n, num_blocks)
    _check_blocks(n, kk, nb, "backward")
    eb = e // nb

    kernel = functools.partial(
        _bwd_kernel, num_nodes=n, knn=kk, num_heads=h, head_dim=d,
        num_eblocks=nb,
    )
    flat = lambda t: t.reshape(b, -1, hd)  # noqa: E731
    node_spec = pl.BlockSpec((1, n, hd), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM)
    edge_spec = pl.BlockSpec((1, eb, hd), lambda i, j: (i, j, 0),
                             memory_space=pltpu.VMEM)
    idx_spec = pl.BlockSpec((1, eb, 1), lambda i, j: (i, j, 0),
                            memory_space=pltpu.VMEM)
    dq, dk, dv, dpe = pl.pallas_call(
        kernel,
        grid=(b, nb),
        in_specs=[idx_spec, idx_spec, node_spec, node_spec, node_spec,
                  edge_spec, node_spec, node_spec, node_spec, edge_spec],
        out_specs=[node_spec, node_spec, node_spec, edge_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, n, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, e, hd), jnp.float32),
        ],
        interpret=interpret,
    )(
        nbr_idx.reshape(b, e, 1).astype(jnp.int32),
        edge_mask.reshape(b, e, 1).astype(jnp.float32),
        flat(q).astype(jnp.float32),
        flat(k).astype(jnp.float32),
        flat(v).astype(jnp.float32),
        flat(proj_e).astype(jnp.float32),
        flat(h_out).astype(jnp.float32),
        z_out.astype(jnp.float32),
        flat(dh).astype(jnp.float32),
        flat(de).astype(jnp.float32),
    )
    return (dq.reshape(b, n, h, d), dk.reshape(b, n, h, d),
            dv.reshape(b, n, h, d), dpe.reshape(b, n, kk, h, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def edge_attention_pallas(q, k, v, proj_e, nbr_idx, edge_mask,
                          interpret=False, fwd_blocks=None, bwd_blocks=None):
    """Drop-in replacement for ``edge_attention(..., mode='scatter')`` on
    TPU for buckets with N <= MAX_KERNEL_NODES. Returns (h_out, e_out).

    ``fwd_blocks``/``bwd_blocks`` override the edge-block grid sizes
    (None = the built-in per-bucket heuristic). These are the real
    block-shape parameters the autotuner searches — see
    :func:`edge_block_options` for legality and ``tuning/space.py`` for
    the axis definition. Numerics: a different block count only changes
    float accumulation order across edge blocks (tolerance-level parity,
    same as the existing n > 128 path)."""
    h_out, e_out, _ = _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask,
                                      interpret, fwd_blocks)
    return h_out, e_out


def _fwd(q, k, v, proj_e, nbr_idx, edge_mask, interpret=False,
         fwd_blocks=None, bwd_blocks=None):
    h_out, e_out, z_out = _pallas_forward(q, k, v, proj_e, nbr_idx, edge_mask,
                                          interpret, fwd_blocks)
    # h and z (the softmax denominator) ride along as residuals so the
    # backward kernel never re-runs the full forward — it recomputes only
    # the per-edge quantities block-locally.
    return (h_out, e_out), (q, k, v, proj_e, nbr_idx, edge_mask, h_out, z_out)


def _bwd(interpret, fwd_blocks, bwd_blocks, res, grads):
    q, k, v, proj_e, nbr_idx, edge_mask, h_out, z_out = res
    dh, de = grads
    dq, dk, dv, dpe = _pallas_backward(
        q, k, v, proj_e, nbr_idx, edge_mask, h_out, z_out, dh, de, interpret,
        bwd_blocks,
    )
    # The kernel computes (and returns) float32; cotangents must match the
    # primals' dtypes — under a bf16 compute policy q/k/v/proj_e arrive
    # bf16 while the f32 accumulation above stays intact.
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            dpe.astype(proj_e.dtype), None, None)


edge_attention_pallas.defvjp(_fwd, _bwd)


def supports(n: int, batch: int = 1, knn: int = 20, hidden: int = 128,
             num_heads: int = 4) -> bool:
    """Whether the kernel applies to this bucket: whole-graph up to 128
    nodes, edge-block grid (requires the 64-multiple bucket sizes the
    loader produces) up to the reference's 256-residue regime.

    The batch guard bounds the kernel's scoped-vmem stack: blocks carry
    the whole batch dim, so the [B, N*K, H] edge tensor must fit the
    ~16 MB vmem stack with headroom (measured: b16 p128 allocates
    20.17 M and fails AOT compile with 'Ran out of memory in memory
    space vmem'; b8 p128 at ~10.5 MB compiles and runs).

    The hidden/head floor excludes degenerate-tiling configs: lanes pad
    the channel dim to 128, so tiny models inflate the stack instead of
    shrinking it (measured: hidden=8 / head_dim=4 at n=128 allocates
    16.18 M and fails AOT compile — a smoke config, not a perf target;
    such models route to the jnp path, where they are fast anyway)."""
    if hidden < 64 or hidden // max(num_heads, 1) < 16:
        return False
    if batch * n * knn * hidden * 4 > 12 * 1024 * 1024:
        return False
    if n <= 128:
        return True
    return n <= MAX_KERNEL_NODES and n % 64 == 0


def supports_config(gnn_cfg, n: int, batch: int = 1, knn: int = 20) -> bool:
    """:func:`supports` with ``hidden``/``num_heads`` taken from a real
    ``GTConfig`` instead of assumed defaults.

    Call-site guard for code that holds a model config rather than runtime
    tensor shapes (bench.py's A/B section; the model itself threads the
    live shapes at ``models/geometric_transformer.py:252``). A caller that
    passed only ``n`` would silently evaluate the head-dim floor against
    the flagship defaults instead of the measured configuration (round-5
    advisor finding)."""
    return supports(n, batch=batch, knn=knn,
                    hidden=gnn_cfg.hidden, num_heads=gnn_cfg.num_heads)
