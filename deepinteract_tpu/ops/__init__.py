"""TPU compute primitives: fused edge attention on the dense [N, K] layout."""

from deepinteract_tpu.ops.attention import edge_attention  # noqa: F401
