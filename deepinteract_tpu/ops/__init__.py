"""TPU compute primitives: edge attention (jnp reference + Pallas kernel)."""

from deepinteract_tpu.ops.attention import edge_attention  # noqa: F401
