"""The assembly runner: all C(k,2) pairs of one complex, encode-once.

Work plan for one assembly:

1. **Encode phase** — delegated to
   :meth:`ScreenRunner.ensure_embeddings`, so each UNIQUE chain pays
   exactly one encoder pass per embedding identity (content + bucket +
   weights + control flag + dtype) no matter how many pairs reference
   it; ``di_assembly_encodes_total`` counts the passes actually
   executed — the encode-once contract the tests assert.
2. **Decode phase** — the pair loop replicates ScreenRunner's decode
   scheduling EXACTLY (canonical bucket orientation incl. the
   strictly-greater swap, ``_slots`` power-of-two padding, first-row
   fill, sorted (b1, b2) group order), because the decoder is not
   bit-symmetric under argument swap: assembly per-pair scores must be
   byte-identical to a bulk screen of the same pairs. Unlike the
   screen, the full depadded ``[n1, n2]`` contact map is retained per
   pair (the assembly bundle persists them).
3. **Assembly** — records are ranked, calibrated when a fitted
   :class:`~deepinteract_tpu.calibration.Calibrator` is attached (raw
   scores always preserved alongside), thresholded into the interface
   graph, and reduced to the complex-level interactability score. An
   optional control pass re-scores every pair with zeroed node/edge
   features (the VERDICT item-6 ``input_indep`` control) so the result
   carries its honesty baseline.
"""

from __future__ import annotations

import dataclasses
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs import spans as obs_spans
from deepinteract_tpu.screening.embcache import EmbeddingCache
from deepinteract_tpu.screening.library import ChainEntry
from deepinteract_tpu.screening.manifest import pair_id
from deepinteract_tpu.screening.runner import (
    ScreenConfig,
    ScreenRunner,
    _slots,
)
from deepinteract_tpu.screening.scoring import pair_summary, rank_records
from deepinteract_tpu.serving.admission import (
    DeadlineExceeded,
    expired_counter,
)

ASSEMBLY_BUNDLE_KIND = "assembly-bundle"  # sidecar kind (fsck dispatches)

_RUNS = obs_metrics.counter(
    "di_assembly_runs_total", "Assemblies scored")
_CHAINS = obs_metrics.counter(
    "di_assembly_chains_total", "Unique chains entering assemblies")
_ENCODES = obs_metrics.counter(
    "di_assembly_encodes_total",
    "Encoder passes executed by assemblies (unique-chain cache misses)")
_ENCODE_HITS = obs_metrics.counter(
    "di_assembly_encode_cache_hits_total",
    "Assembly chains served straight from the embedding cache")
_PAIRS = obs_metrics.counter(
    "di_assembly_pairs_scored_total", "Assembly chain pairs decoded")
_DECODE_BATCHES = obs_metrics.counter(
    "di_assembly_decode_batches_total",
    "Coalesced assembly decode dispatches")


@dataclasses.dataclass(frozen=True)
class AssemblyConfig:
    """Runner knobs (CLI surface: ``cli/assemble.py``)."""

    top_k: int = 10            # contacts kept per pair summary
    decode_batch: int = 8      # pairs per decode dispatch
    encode_batch: int = 8      # chains per encoder dispatch
    edge_threshold: float = 0.5  # interface-graph edge cut (on the
    # calibrated score when a calibrator is attached, raw otherwise)
    control: bool = True       # also score the input_indep control pass
    keep_maps: bool = True     # retain full [n1, n2] maps per pair


@dataclasses.dataclass
class AssemblyResult:
    """One assembly's outcome. ``records`` are ranked best-first; raw
    ``score`` fields are byte-identical to a ScreenRunner screen of the
    same pairs, calibrated/control fields ride alongside."""

    records: List[Dict]
    maps: Dict[str, np.ndarray]       # pair_id -> raw [n1, n2] map
    chain_ids: List[str]
    chains: int
    pairs_total: int
    pairs_scored: int
    unique_encodes: int               # encoder passes actually executed
    encode_cache_hits: int
    encode_batches: int
    decode_batches: int
    interface: Dict                   # {"nodes": [...], "edges": [...]}
    interactability: float            # mean effective pair score
    control_score: Optional[float]    # input_indep baseline (None = off)
    calibrated: bool
    encode_seconds: float
    decode_seconds: float
    emb_cache: Dict

    def summary(self) -> Dict:
        return {
            "chains": self.chains,
            "pairs_total": self.pairs_total,
            "pairs_scored": self.pairs_scored,
            "unique_encodes": self.unique_encodes,
            "encode_cache_hits": self.encode_cache_hits,
            "decode_batches": self.decode_batches,
            "interface_edges": len(self.interface["edges"]),
            "interactability": round(self.interactability, 6),
            "control_score": (round(self.control_score, 6)
                              if self.control_score is not None else None),
            "calibrated": self.calibrated,
            "encode_seconds": round(self.encode_seconds, 3),
            "decode_seconds": round(self.decode_seconds, 3),
            "emb_cache_hit_rate": round(
                self.emb_cache.get("hit_rate", 0.0), 3),
        }


class _ZeroedLibrary:
    """Library view whose chains carry zeroed node/edge features — the
    input_indep control identity (distinct embedding-cache keys come
    from hashing the zeroed raw, so control embeddings never collide
    with the real ones)."""

    def __init__(self, library):
        self._library = library

    def __getitem__(self, chain_id: str) -> ChainEntry:
        e = self._library[chain_id]
        raw = dict(e.raw,
                   node_feats=np.zeros_like(e.raw["node_feats"]),
                   edge_feats=np.zeros_like(e.raw["edge_feats"]))
        return ChainEntry(e.chain_id, raw, e.n)


class AssemblyRunner:
    """Schedules one assembly over a resident engine + embedding cache
    (both shareable with ScreenRunner — same cache keys, same AOT
    executables, so a chain screened earlier costs zero encodes here)."""

    def __init__(self, engine, cache: Optional[EmbeddingCache] = None,
                 cfg: AssemblyConfig = AssemblyConfig(), calibrator=None):
        self.engine = engine
        self.cache = cache if cache is not None else EmbeddingCache()
        self.cfg = cfg
        self.calibrator = calibrator
        self._screen = ScreenRunner(
            engine, cache=self.cache,
            cfg=ScreenConfig(top_k=cfg.top_k,
                             decode_batch=cfg.decode_batch,
                             encode_batch=cfg.encode_batch))

    def assemble(self, library, chain_ids: Optional[Sequence[str]] = None,
                 deadline=None, trace_id: str = "") -> AssemblyResult:
        """Score every pair of ``chain_ids`` (default: the whole
        library, in library order). ``deadline`` is enforced at encode-
        and decode-batch boundaries (DeadlineExceeded — the synchronous
        ``POST /assembly`` path)."""
        ids = list(chain_ids) if chain_ids else list(library.ids())
        if len(ids) < 2:
            raise ValueError(f"an assembly needs at least 2 chains, "
                             f"got {len(ids)}")
        if len(set(ids)) != len(ids):
            raise ValueError("assembly chain ids must be unique")
        pairs = [(ids[i], ids[j])
                 for i in range(len(ids)) for j in range(i + 1, len(ids))]
        trace_attrs = {"trace_id": trace_id} if trace_id else {}

        t0 = time.perf_counter()
        with obs_spans.span("assembly_encode", chains=len(ids),
                            **trace_attrs):
            emb, executed, hits, enc_batches = \
                self._screen.ensure_embeddings(library, sorted(ids),
                                               deadline=deadline)
        encode_s = time.perf_counter() - t0
        _CHAINS.inc(len(ids))
        _ENCODES.inc(executed)
        _ENCODE_HITS.inc(hits)

        t1 = time.perf_counter()
        records, maps, decode_batches = self._decode_pairs(
            emb, pairs, deadline=deadline, trace_attrs=trace_attrs)
        decode_s = time.perf_counter() - t1
        _PAIRS.inc(len(pairs))
        _DECODE_BATCHES.inc(decode_batches)
        _RUNS.inc()

        if self.calibrator is not None:
            for rec in records:
                cal_map = self.calibrator.apply(maps[rec["pair_id"]])
                cal = pair_summary(cal_map, self.cfg.top_k)
                rec["calibrated_score"] = cal["score"]
                rec["calibrated_max_prob"] = cal["max_prob"]
                for contact in rec["top_contacts"]:
                    contact["p_cal"] = round(float(self.calibrator.apply(
                        np.asarray(contact["p"]))), 6)
        records = rank_records(records)

        control_score = None
        if self.cfg.control:
            control_score = self._control_pass(library, pairs, records,
                                               deadline=deadline,
                                               trace_id=trace_id)

        def effective(rec: Dict) -> float:
            return rec.get("calibrated_score", rec["score"])

        edges = []
        for rec in records:
            if effective(rec) >= self.cfg.edge_threshold:
                edge = {"chain1": rec["chain1"], "chain2": rec["chain2"],
                        "pair_id": rec["pair_id"],
                        "score": rec["score"]}
                if "calibrated_score" in rec:
                    edge["calibrated_score"] = rec["calibrated_score"]
                edges.append(edge)
        interface = {"nodes": ids, "edges": edges}
        interactability = float(np.mean([effective(r) for r in records]))

        if not self.cfg.keep_maps:
            maps = {}
        return AssemblyResult(
            records=records,
            maps=maps,
            chain_ids=ids,
            chains=len(ids),
            pairs_total=len(pairs),
            pairs_scored=len(pairs),
            unique_encodes=executed,
            encode_cache_hits=hits,
            encode_batches=enc_batches,
            decode_batches=decode_batches,
            interface=interface,
            interactability=interactability,
            control_score=control_score,
            calibrated=self.calibrator is not None,
            encode_seconds=encode_s,
            decode_seconds=decode_s,
            emb_cache=self.cache.stats(),
        )

    # -- decode loop (ScreenRunner-parity scheduling) ----------------------

    def _decode_pairs(self, emb, pairs, deadline=None, trace_attrs=None,
                      ) -> Tuple[List[Dict], Dict[str, np.ndarray], int]:
        # Canonical orientation: bucket1 <= bucket2, swapping ONLY on
        # strictly greater — identical to ScreenRunner.screen, which is
        # what makes the per-pair summaries byte-identical.
        groups = defaultdict(list)  # (b1, b2) -> [(pid, c1, c2)]
        for c1, c2 in pairs:
            pid = pair_id(c1, c2)
            if emb[c1][2] > emb[c2][2]:
                c1, c2 = c2, c1
            groups[(emb[c1][2], emb[c2][2])].append((pid, c1, c2))

        records: List[Dict] = []
        maps: Dict[str, np.ndarray] = {}
        decode_batches = 0
        with obs_spans.span("assembly_decode", pairs=len(pairs),
                            **(trace_attrs or {})):
            for (b1, b2), items in sorted(groups.items()):
                for lo in range(0, len(items), self.cfg.decode_batch):
                    if deadline is not None and deadline.expired:
                        expired_counter("assembly")
                        raise DeadlineExceeded(
                            "assembly deadline "
                            f"({deadline.budget_s * 1e3:.0f}ms) expired "
                            f"during decode ({len(records)}/{len(pairs)} "
                            "pairs scored)")
                    chunk = items[lo:lo + self.cfg.decode_batch]
                    slots = _slots(len(chunk), self.cfg.decode_batch)
                    rows = chunk + [chunk[0]] * (slots - len(chunk))
                    feats1 = np.stack([emb[c1][0] for _, c1, _ in rows])
                    feats2 = np.stack([emb[c2][0] for _, _, c2 in rows])
                    mask1 = np.stack([np.arange(b1) < emb[c1][1]
                                      for _, c1, _ in rows])
                    mask2 = np.stack([np.arange(b2) < emb[c2][1]
                                      for _, _, c2 in rows])
                    compiled = self.engine.decode_executable(
                        b1, b2, slots, (feats1, feats2, mask1, mask2))
                    probs = np.asarray(compiled(
                        self.engine.params, self.engine.batch_stats,
                        feats1, feats2, mask1, mask2))
                    for i, (pid, c1, c2) in enumerate(chunk):
                        n1, n2 = emb[c1][1], emb[c2][1]
                        depadded = probs[i, :n1, :n2]
                        records.append({
                            "pair_id": pid,
                            "chain1": c1, "chain2": c2,
                            "n1": n1, "n2": n2,
                            "bucket": [b1, b2],
                            **pair_summary(depadded, self.cfg.top_k),
                        })
                        maps[pid] = np.array(depadded)
                    decode_batches += 1
        return records, maps, decode_batches

    # -- input_indep control ----------------------------------------------

    def _control_pass(self, library, pairs, records, deadline=None,
                      trace_id: str = "") -> float:
        """Score the same oriented pairs with zeroed input features and
        annotate each record with its per-pair ``control_score``. The
        return value is the complex-level control mean — what an input-
        independent model claims about this assembly; a real prediction
        should separate from it. (When the ENGINE itself runs with
        cfg.input_indep, main and control passes coincide by design.)"""
        result = self._screen.screen(_ZeroedLibrary(library), list(pairs),
                                     trace_id=trace_id, deadline=deadline)
        by_pid = {r["pair_id"]: r["score"] for r in result.records}
        for rec in records:
            rec["control_score"] = round(by_pid[rec["pair_id"]], 6)
        return float(np.mean(list(by_pid.values())))
