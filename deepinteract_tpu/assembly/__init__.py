"""k-chain assembly scoring (PR-19).

A complex with k chains has C(k, 2) chain pairs; this package scores
all of them with one encoder pass per UNIQUE chain (the PR-6 embedding
cache, counter-asserted), micro-batched contact decodes through the
engine's existing AOT inventory, and assembles the per-assembly result:
per-pair contact maps, an interface graph (edges = pairs whose
calibrated interaction score clears a threshold), a complex-level
interactability score, and the ``input_indep`` control score — the
wired-in honesty baseline every ranking is reported next to.
"""

from deepinteract_tpu.assembly.runner import (
    ASSEMBLY_BUNDLE_KIND,
    AssemblyConfig,
    AssemblyResult,
    AssemblyRunner,
)

__all__ = [
    "ASSEMBLY_BUNDLE_KIND",
    "AssemblyConfig",
    "AssemblyResult",
    "AssemblyRunner",
]
