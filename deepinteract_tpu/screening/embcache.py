"""Content-addressed per-chain embedding cache: encode once, decode many.

A screened chain's encoder output is a pure function of its featurized
arrays, the padded bucket, and the served weights — so an exact content
hash is a sound cache key (the same argument ``serving/cache.py`` makes
for whole-complex results, one level down the split forward). The cache
holds the PADDED ``[bucket, C]`` float32 embedding plus the real length,
so a hit feeds the decode batch without any re-layout.

Two tiers:

* **in-memory LRU** — bounded by entry count; the working set of an
  all-vs-all screen is the library itself, so the default capacity covers
  thousands of chains before eviction matters;
* **optional on-disk npz spill** — entries evicted from memory are written
  to ``spill_dir`` (atomic tmp+rename) and transparently reloaded on a
  later get, so a library larger than memory still encodes each chain
  once per screen, and a RESUMED screen (robustness/preemption.py) skips
  re-encoding everything the killed run already paid for.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np

from deepinteract_tpu.data.io import GRAPH_KEYS
from deepinteract_tpu.obs import metrics as obs_metrics

_HITS = obs_metrics.counter(
    "di_screen_embedding_cache_hits_total",
    "Chain encodes skipped because the embedding was cached")
_MISSES = obs_metrics.counter(
    "di_screen_embedding_cache_misses_total",
    "Embedding-cache lookups that required an encoder pass")
_SPILLS = obs_metrics.counter(
    "di_screen_embedding_cache_spills_total",
    "Embeddings evicted from memory and written to the spill dir")


def chain_hash(raw_chain: Dict[str, np.ndarray], extra: Iterable = ()) -> str:
    """SHA-256 over one chain's model-visible arrays (the per-chain half
    of ``serving/cache.content_hash``). ``extra`` mixes in everything else
    the embedding depends on: bucket, weights identity, input_indep,
    compute dtype."""
    h = hashlib.sha256()
    for key in GRAPH_KEYS:
        a = np.ascontiguousarray(raw_chain[key])
        h.update(f"{key}:{a.dtype.str}:{a.shape}".encode())
        h.update(a.tobytes())
    for item in extra:
        h.update(repr(item).encode())
    return h.hexdigest()


class EmbeddingCache:
    """Thread-safe LRU of padded chain embeddings with optional disk spill.

    Values are ``(feats [bucket, C] float32, n real residues)``. Returned
    arrays are read-only views — the decode path stacks copies anyway, and
    a client mutating a cached embedding must fail loudly.
    """

    def __init__(self, capacity: int = 4096,
                 spill_dir: Optional[str] = None):
        self.capacity = int(capacity)
        self.spill_dir = spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        self._entries: "OrderedDict[str, Tuple[np.ndarray, int]]" = (
            OrderedDict())
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._spills = 0
        self._spill_hits = 0

    # -- key ---------------------------------------------------------------

    def _spill_path(self, key: str) -> str:
        return os.path.join(self.spill_dir, f"emb_{key}.npz")

    # -- access ------------------------------------------------------------

    def get(self, key: str) -> Optional[Tuple[np.ndarray, int]]:
        with self._lock:
            if self.capacity > 0 and key in self._entries:
                self._entries.move_to_end(key)
                self._hits += 1
                _HITS.inc()
                return self._entries[key]
        if self.spill_dir:
            path = self._spill_path(key)
            if os.path.exists(path):
                try:
                    with np.load(path, allow_pickle=False) as z:
                        feats = np.asarray(z["feats"], dtype=np.float32)
                        n = int(z["n"])
                except Exception:  # truncated spill (killed mid-write
                    # before the atomic rename should make this
                    # unreachable, but a corrupt file must read as a
                    # miss, not kill the screen)
                    with self._lock:
                        self._misses += 1
                    _MISSES.inc()
                    return None
                feats.setflags(write=False)
                with self._lock:
                    self._hits += 1
                    self._spill_hits += 1
                _HITS.inc()
                self._admit(key, feats, n)
                return feats, n
        with self._lock:
            self._misses += 1
        _MISSES.inc()
        return None

    def put(self, key: str, feats: np.ndarray, n: int) -> None:
        feats = np.asarray(feats, dtype=np.float32)
        feats.setflags(write=False)
        self._admit(key, feats, int(n))

    def _admit(self, key: str, feats: np.ndarray, n: int) -> None:
        evicted = []
        with self._lock:
            if self.capacity > 0:
                self._entries[key] = (feats, n)
                self._entries.move_to_end(key)
                while len(self._entries) > self.capacity:
                    evicted.append(self._entries.popitem(last=False))
            elif self.spill_dir:
                evicted.append((key, (feats, n)))  # disk-only mode
        for ekey, (efeats, en) in evicted:
            self._spill(ekey, efeats, en)

    def _spill(self, key: str, feats: np.ndarray, n: int) -> None:
        if not self.spill_dir:
            return
        path = self._spill_path(key)
        if os.path.exists(path):
            return
        tmp = path + ".tmp"
        try:
            # Through a file handle: np.savez given a PATH appends ".npz",
            # which would break the tmp+rename atomicity dance.
            with open(tmp, "wb") as fh:
                np.savez(fh, feats=feats, n=np.int64(n))
            os.replace(tmp, path)
            with self._lock:
                self._spills += 1
            _SPILLS.inc()
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "capacity": self.capacity,
                "size": len(self._entries),
                "spill_dir": self.spill_dir,
                "hits": self._hits,
                "misses": self._misses,
                "spills": self._spills,
                "spill_hits": self._spill_hits,
                "hit_rate": (self._hits / total) if total else 0.0,
                "resident_bytes": sum(
                    f.nbytes for f, _ in self._entries.values()),
            }
