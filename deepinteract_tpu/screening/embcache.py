"""Content-addressed per-chain embedding cache: encode once, decode many.

A screened chain's encoder output is a pure function of its featurized
arrays, the padded bucket, and the served weights — so an exact content
hash is a sound cache key (the same argument ``serving/cache.py`` makes
for whole-complex results, one level down the split forward). The cache
holds the PADDED ``[bucket, C]`` float32 embedding plus the real length,
so a hit feeds the decode batch without any re-layout.

Two tiers:

* **in-memory LRU** — bounded by entry count; the working set of an
  all-vs-all screen is the library itself, so the default capacity covers
  thousands of chains before eviction matters;
* **optional on-disk npz spill** — entries evicted from memory are written
  to ``spill_dir`` (robustness/artifacts.py: atomic write + SHA-256
  integrity sidecar) and transparently reloaded on a later get, so a
  library larger than memory still encodes each chain once per screen,
  and a RESUMED screen (robustness/preemption.py) skips re-encoding
  everything the killed run already paid for. A spill read is VERIFIED
  before np.load ever parses it: a truncated or bit-flipped file is
  quarantined and served as a miss (the chain is re-encoded), never
  admitted as a silently wrong embedding; a payload whose sidecar hasn't
  landed yet (concurrent spill mid-write, or a kill between the two
  writes) is a plain miss and is healed whole by the next re-spill.
"""

from __future__ import annotations

import hashlib
import io
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterable, Optional, Tuple

import numpy as np

from deepinteract_tpu.data.io import GRAPH_KEYS
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import artifacts

SPILL_KIND = "embcache-spill"

_HITS = obs_metrics.counter(
    "di_screen_embedding_cache_hits_total",
    "Chain encodes skipped because the embedding was cached")
_MISSES = obs_metrics.counter(
    "di_screen_embedding_cache_misses_total",
    "Embedding-cache lookups that required an encoder pass")
_SPILLS = obs_metrics.counter(
    "di_screen_embedding_cache_spills_total",
    "Embeddings evicted from memory and written to the spill dir")


def chain_hash(raw_chain: Dict[str, np.ndarray], extra: Iterable = ()) -> str:
    """SHA-256 over one chain's model-visible arrays (the per-chain half
    of ``serving/cache.content_hash``). ``extra`` mixes in everything else
    the embedding depends on: bucket, weights identity, input_indep,
    compute dtype."""
    h = hashlib.sha256()
    for key in GRAPH_KEYS:
        a = np.ascontiguousarray(raw_chain[key])
        h.update(f"{key}:{a.dtype.str}:{a.shape}".encode())
        h.update(a.tobytes())
    for item in extra:
        h.update(repr(item).encode())
    return h.hexdigest()


class EmbeddingCache:
    """Thread-safe LRU of padded chain embeddings with optional disk spill.

    Values are ``(feats [bucket, C] float32, n real residues)``. Returned
    arrays are read-only views — the decode path stacks copies anyway, and
    a client mutating a cached embedding must fail loudly.
    """

    def __init__(self, capacity: int = 4096,
                 spill_dir: Optional[str] = None):
        self.capacity = int(capacity)
        self.spill_dir = spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
            # A killed run's mid-flight spill leaves only an orphaned
            # tmp; its destination is whole or absent (atomic replace).
            artifacts.sweep_tmp(spill_dir, prefix="emb_")
        self._entries: "OrderedDict[str, Tuple[np.ndarray, int]]" = (
            OrderedDict())
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._spills = 0
        self._spill_hits = 0

    # -- key ---------------------------------------------------------------

    def _spill_path(self, key: str) -> str:
        return os.path.join(self.spill_dir, f"emb_{key}.npz")

    # -- access ------------------------------------------------------------

    def get(self, key: str) -> Optional[Tuple[np.ndarray, int]]:
        with self._lock:
            if self.capacity > 0 and key in self._entries:
                self._entries.move_to_end(key)
                self._hits += 1
                _HITS.inc()
                return self._entries[key]
        if self.spill_dir:
            path = self._spill_path(key)
            if os.path.exists(path):
                if not os.path.exists(artifacts.sidecar_path(path)):
                    # Payload landed but no sidecar YET: a concurrent
                    # _spill is between its two writes (or a kill landed
                    # there). A miss — NOT a quarantine of a healthy
                    # mid-write file; _spill heals the sidecar on the
                    # re-spill after this miss's re-encode.
                    with self._lock:
                        self._misses += 1
                    _MISSES.inc()
                    return None
                try:
                    # Integrity gate BEFORE the deserializer: without it,
                    # only np.load's format checks stood between a
                    # flipped bit and a wrong embedding — and a bit flip
                    # inside the float payload passes format checks.
                    raw = artifacts.verify_read(path, kind=SPILL_KIND)
                    with np.load(io.BytesIO(raw), allow_pickle=False) as z:
                        feats = np.asarray(z["feats"], dtype=np.float32)
                        n = int(z["n"])
                except (artifacts.ArtifactError, ValueError,
                        KeyError) as exc:
                    # Positive corruption (hash/length/sidecar mismatch)
                    # or verified-bytes-that-won't-deserialize (writer
                    # bug): quarantine and re-encode (a miss), never
                    # kill the screen or admit garbage.
                    if os.path.exists(path):
                        artifacts.quarantine(path, SPILL_KIND, str(exc))
                    with self._lock:
                        self._misses += 1
                    _MISSES.inc()
                    return None
                except OSError:
                    # TRANSIENT read failure (or the file vanished): a
                    # plain miss — the intact spill stays in place for
                    # the next attempt, no false corruption signal.
                    with self._lock:
                        self._misses += 1
                    _MISSES.inc()
                    return None
                feats.setflags(write=False)
                with self._lock:
                    self._hits += 1
                    self._spill_hits += 1
                _HITS.inc()
                self._admit(key, feats, n)
                return feats, n
        with self._lock:
            self._misses += 1
        _MISSES.inc()
        return None

    def put(self, key: str, feats: np.ndarray, n: int) -> None:
        feats = np.asarray(feats, dtype=np.float32)
        feats.setflags(write=False)
        self._admit(key, feats, int(n))

    def _admit(self, key: str, feats: np.ndarray, n: int) -> None:
        evicted = []
        with self._lock:
            if self.capacity > 0:
                self._entries[key] = (feats, n)
                self._entries.move_to_end(key)
                while len(self._entries) > self.capacity:
                    evicted.append(self._entries.popitem(last=False))
            elif self.spill_dir:
                evicted.append((key, (feats, n)))  # disk-only mode
        for ekey, (efeats, en) in evicted:
            self._spill(ekey, efeats, en)

    def _spill(self, key: str, feats: np.ndarray, n: int) -> None:
        if not self.spill_dir:
            return
        path = self._spill_path(key)
        if (os.path.exists(path)
                and os.path.exists(artifacts.sidecar_path(path))):
            # Complete pair already on disk (content-addressed: same key
            # = same bytes). A payload WITHOUT its sidecar — a kill
            # between the two writes — is rewritten whole, healing it.
            return
        try:
            # Serialize in memory, then one atomic_write + sidecar: the
            # destination is only ever a COMPLETE npz with a matching
            # hash, so a reader (or a resumed run) can verify-then-load.
            # The key already binds weights_signature/bucket/dtype
            # (chain_hash extras), so sidecar extras carry only n.
            buf = io.BytesIO()
            np.savez(buf, feats=feats, n=np.int64(n))
            artifacts.atomic_write_artifact(
                path, buf.getvalue(), SPILL_KIND, extra={"n": int(n)})
            with self._lock:
                self._spills += 1
            _SPILLS.inc()
        except OSError:
            # Failed spill (disk full / injected storage fault): drop the
            # entry — it will be re-encoded — and let the startup sweep
            # collect any orphaned tmp.
            pass

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "capacity": self.capacity,
                "size": len(self._entries),
                "spill_dir": self.spill_dir,
                "hits": self._hits,
                "misses": self._misses,
                "spills": self._spills,
                "spill_hits": self._spill_hits,
                "hit_rate": (self._hits / total) if total else 0.0,
                "resident_bytes": sum(
                    f.nbytes for f, _ in self._entries.values()),
            }
