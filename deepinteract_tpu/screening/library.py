"""Chain libraries: the input side of a bulk screen.

A screen operates on CHAINS, not complexes — the unit the shared-weight
encoder leg consumes. Every in-repo storage format is a *complex* (two
chains), so a library is assembled by splitting complexes: each
``.npz`` (``data/io.py`` schema) or packed-memmap item (``data/
packed.py``) contributes its two chains as ``<name>:g1`` / ``<name>:g2``.
A synthetic generator covers tests and benches.

Chains are kept as raw featurizer dicts (``GRAPH_KEYS`` arrays,
unpadded); padding to the engine's chain bucket happens at encode time so
one library serves every bucket policy.
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deepinteract_tpu.data.io import GRAPH_KEYS, load_complex_npz

from deepinteract_tpu.screening.embcache import chain_hash


@dataclasses.dataclass(frozen=True)
class ChainEntry:
    """One library chain: stable id, raw featurizer arrays, real length."""

    chain_id: str
    raw: Dict[str, np.ndarray]
    n: int


class ChainLibrary:
    """Ordered collection of chains with stable ids and a content
    signature (manifest compatibility check across resumes)."""

    def __init__(self, chains: Sequence[ChainEntry]):
        if not chains:
            raise ValueError("chain library is empty")
        ids = [c.chain_id for c in chains]
        if len(set(ids)) != len(ids):
            dupes = sorted({i for i in ids if ids.count(i) > 1})[:5]
            raise ValueError(f"duplicate chain ids in library: {dupes}")
        self.chains: List[ChainEntry] = list(chains)
        self._by_id = {c.chain_id: c for c in self.chains}

    def __len__(self) -> int:
        return len(self.chains)

    def __getitem__(self, chain_id: str) -> ChainEntry:
        return self._by_id[chain_id]

    def ids(self) -> List[str]:
        return [c.chain_id for c in self.chains]

    def signature(self) -> str:
        """Content signature over ids + per-chain array hashes: a resumed
        manifest written for a DIFFERENT library must not be trusted."""
        h = hashlib.sha256()
        for c in self.chains:
            h.update(f"{c.chain_id}:{c.n}:".encode())
            h.update(chain_hash(c.raw).encode())
        return h.hexdigest()[:16]

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_complex_files(cls, paths: Sequence[str]) -> "ChainLibrary":
        """Each complex ``.npz`` contributes chains ``<stem>:g1`` and
        ``<stem>:g2``."""
        chains = []
        for path in paths:
            raw = load_complex_npz(path)
            stem = os.path.splitext(os.path.basename(path))[0]
            for part in ("g1", "g2"):
                graph = raw["graph1" if part == "g1" else "graph2"]
                chains.append(ChainEntry(
                    chain_id=f"{stem}:{part}",
                    raw={k: np.asarray(graph[k]) for k in GRAPH_KEYS},
                    n=int(graph["node_feats"].shape[0])))
        return cls(chains)

    @classmethod
    def from_npz_dir(cls, directory: str) -> "ChainLibrary":
        paths = sorted(glob.glob(os.path.join(directory, "*.npz")))
        if not paths:
            raise FileNotFoundError(f"no .npz complexes under {directory}")
        return cls.from_complex_files(paths)

    @classmethod
    def from_pack(cls, pack_dir: str) -> "ChainLibrary":
        """Chains out of a pre-padded memmap pack (``data/packed.py``):
        rows are de-padded back to their real lengths (padding is appended
        at the tail, so a ``[:n]`` slice is exact)."""
        from deepinteract_tpu.data.packed import PackedDataset

        ds = PackedDataset(pack_dir)
        chains = []
        for idx in range(len(ds)):
            pc = ds.padded_batch([idx], ds.bucket_of(idx))
            stem = os.path.splitext(os.path.basename(ds.target_of(idx)))[0]
            for part, graph in (("g1", pc.graph1), ("g2", pc.graph2)):
                n = int(np.asarray(graph.num_nodes).reshape(-1)[0])
                raw = {k: np.asarray(getattr(graph, k))[0, :n]
                       for k in GRAPH_KEYS}
                chains.append(ChainEntry(chain_id=f"{stem}:{part}",
                                         raw=raw, n=n))
        return cls(chains)

    @classmethod
    def synthetic(cls, num_chains: int, len_lo: int = 24, len_hi: int = 48,
                  seed: int = 0, knn: Optional[int] = None,
                  geo_nbrhd_size: Optional[int] = None) -> "ChainLibrary":
        """Deterministic synthetic library (tests / bench / smoke)."""
        from deepinteract_tpu import constants
        from deepinteract_tpu.data import features as F
        from deepinteract_tpu.data.synthetic import (
            random_backbone,
            random_residue_feats,
        )

        knn = knn or constants.KNN
        geo = geo_nbrhd_size or constants.GEO_NBRHD_SIZE
        rng = np.random.default_rng(seed)
        chains = []
        for i in range(num_chains):
            n = int(rng.integers(max(len_lo, knn + 1), len_hi + 1))
            raw = F.featurize_chain(
                random_backbone(n, rng), random_residue_feats(n, rng),
                knn=knn, geo_nbrhd_size=geo, rng=rng)
            chains.append(ChainEntry(chain_id=f"syn{i:04d}", raw=raw, n=n))
        return cls(chains)


def enumerate_pairs(
    library: ChainLibrary,
    queries: Optional[Iterable[str]] = None,
    include_self: bool = False,
    max_pairs: int = 0,
) -> List[Tuple[str, str]]:
    """The screen's work list, in deterministic order.

    All-vs-all (default): unordered pairs ``(i, j)`` with ``i < j`` in
    library order (plus the diagonal under ``include_self`` — homodimer
    screening). Query mode: every query against the full library, one
    entry per unordered pair (two queries never produce both
    orientations). ``max_pairs`` truncates the list (0 = no cap).
    """
    ids = library.ids()
    pairs: List[Tuple[str, str]] = []
    seen = set()
    if queries:
        queries = list(queries)
        missing = [q for q in queries if q not in set(ids)]
        if missing:
            raise KeyError(f"query chains not in library: {missing[:5]}")
        for q in queries:
            for other in ids:
                if other == q and not include_self:
                    continue
                key = frozenset((q, other))
                if key in seen:
                    continue
                seen.add(key)
                pairs.append((q, other))
    else:
        for a in range(len(ids)):
            start = a if include_self else a + 1
            for b in range(start, len(ids)):
                pairs.append((ids[a], ids[b]))
    if max_pairs and len(pairs) > max_pairs:
        pairs = pairs[:max_pairs]
    return pairs
