"""Per-pair summary scoring: one implementation, two consumers.

Screening ranks candidate partners by a scalar per chain pair; the
predict CLI's ``--top_k`` flag reports the same ranked contacts for a
single complex. Both call :func:`pair_summary`, so the two outputs can
never disagree about what "top-k contact probability" means.

The score is the MEAN of the top-k contact probabilities: a single
spurious high pixel ranks below k consistent ones, while a genuinely
interacting pair (whose interface spans many residue pairs) saturates
the average — the standard interface-propensity summary for partner
retrieval.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def pair_summary(probs: np.ndarray, top_k: int = 10) -> Dict:
    """Ranked summary of a depadded ``[n1, n2]`` contact-probability map.

    Returns ``score`` (mean of the top-k probabilities — the ranking
    key), ``max_prob``, the effective ``top_k`` (clamped to the map
    size), and ``top_contacts`` as ``(i, j, p)`` triplets in descending
    probability order.
    """
    probs = np.asarray(probs)
    if probs.ndim != 2:
        raise ValueError(f"pair_summary wants a [n1, n2] map, got "
                         f"shape {probs.shape}")
    flat = probs.ravel()
    k = max(1, min(int(top_k), flat.size))
    idx = np.argpartition(flat, flat.size - k)[-k:]
    order = idx[np.argsort(flat[idx])[::-1]]
    n2 = probs.shape[1]
    contacts: List[Dict] = [
        {"i": int(f // n2), "j": int(f % n2), "p": round(float(flat[f]), 6)}
        for f in order
    ]
    return {
        "score": float(flat[order].mean()),
        "max_prob": float(flat[order[0]]),
        "top_k": k,
        "top_contacts": contacts,
    }


def rank_records(records: List[Dict]) -> List[Dict]:
    """Descending-score ordering with a deterministic tie-break on the
    pair id (stable across resumes and re-runs of the same library)."""
    return sorted(records,
                  key=lambda r: (-r["score"], r.get("pair_id", "")))
