"""The screen runner: pair scheduler over split-phase executables.

Work plan for one screen (all-vs-all or query-vs-library):

1. **Encode phase** — unique chains are grouped by (chain bucket, shape
   signature), batched, and pushed through the engine's AOT-compiled
   ``encode`` executable; every embedding lands in the content-addressed
   :class:`~deepinteract_tpu.screening.embcache.EmbeddingCache`, so each
   chain is encoded at most once per screen (and zero times when a
   previous screen or a killed run already cached it).
2. **Decode phase** — pairs are grouped by (bucket1, bucket2), micro-
   batched to the decode executable over stacked cached embeddings, and
   summarized to a scalar ranking score
   (:func:`~deepinteract_tpu.screening.scoring.pair_summary`).
3. **Checkpointing** — the manifest is flushed atomically after every
   decode batch; a PR-1 :class:`PreemptionGuard` request stops the screen
   at the next batch boundary with everything scored so far durable, and
   a rerun completes the remaining pairs exactly once.

The naive alternative — ``engine.predict`` per pair — re-encodes every
chain O(N) times; the split-phase path pays N encoder passes for N^2
decodes (bench.py's ``screening`` section measures the win).
"""

from __future__ import annotations

import dataclasses
import time
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepinteract_tpu.data.graph import pad_graph, stack_graphs
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs import spans as obs_spans
from deepinteract_tpu.screening.embcache import EmbeddingCache, chain_hash
from deepinteract_tpu.serving.admission import DeadlineExceeded, expired_counter
from deepinteract_tpu.screening.library import ChainLibrary
from deepinteract_tpu.screening.manifest import ScreenManifest, pair_id
from deepinteract_tpu.screening.scoring import pair_summary, rank_records

_ENCODED = obs_metrics.counter(
    "di_screen_encoded_chains_total",
    "Chain encoder passes executed by screens (cache misses)")
_ENCODE_BATCHES = obs_metrics.counter(
    "di_screen_encode_batches_total", "Coalesced encoder dispatches")
_PAIRS = obs_metrics.counter(
    "di_screen_pairs_scored_total", "Chain pairs decoded and scored")
_DECODE_BATCHES = obs_metrics.counter(
    "di_screen_decode_batches_total", "Coalesced decode dispatches")
_PREEMPTIONS = obs_metrics.counter(
    "di_screen_preemptions_total",
    "Screens stopped early by a preemption request")


@dataclasses.dataclass(frozen=True)
class ScreenConfig:
    """Runner knobs (CLI surface: ``cli/screen.py``)."""

    top_k: int = 10            # contacts kept per pair summary
    decode_batch: int = 8      # pairs per decode dispatch
    encode_batch: int = 8      # chains per encoder dispatch


@dataclasses.dataclass
class ScreenResult:
    """One run's outcome; ``records`` covers the WHOLE screen (resumed
    pairs included), counters cover only this run."""

    records: List[Dict]
    pairs_total: int
    pairs_scored: int
    pairs_resumed: int
    chains: int
    encodes_executed: int
    encode_cache_hits: int
    encode_batches: int
    decode_batches: int
    preempted: bool
    resumed: bool
    encode_seconds: float
    decode_seconds: float
    emb_cache: Dict

    @property
    def encode_reuse_ratio(self) -> float:
        """Embedding uses per encoder pass: 2 per scored pair, amortized
        over the encodes actually executed (the naive per-pair loop is
        pinned at 1.0 by construction)."""
        uses = 2 * self.pairs_scored
        return uses / max(1, self.encodes_executed)

    def summary(self) -> Dict:
        return {
            "pairs_total": self.pairs_total,
            "pairs_scored": self.pairs_scored,
            "pairs_resumed": self.pairs_resumed,
            "chains": self.chains,
            "encodes_executed": self.encodes_executed,
            "encode_cache_hits": self.encode_cache_hits,
            "encode_reuse_ratio": round(self.encode_reuse_ratio, 2),
            "decode_batches": self.decode_batches,
            "preempted": self.preempted,
            "resumed": self.resumed,
            "encode_seconds": round(self.encode_seconds, 3),
            "decode_seconds": round(self.decode_seconds, 3),
            "emb_cache_hit_rate": round(self.emb_cache.get("hit_rate", 0.0),
                                        3),
        }


def _slots(n: int, cap: int) -> int:
    """Next power-of-two batch size, capped — the engine's batch-inventory
    policy (``InferenceEngine._batch_slots``) applied to a caller-chosen
    cap so encode/decode inventories stay O(log cap) per bucket."""
    return min(1 << (max(1, n) - 1).bit_length(), max(1, cap))


class ScreenRunner:
    """Schedules one or more screens over a resident engine + embedding
    cache. Thread-compatible with the engine's /predict traffic: decode
    dispatches go straight to the device (the runtime serializes), never
    through the micro-batch scheduler."""

    def __init__(self, engine, cache: Optional[EmbeddingCache] = None,
                 cfg: ScreenConfig = ScreenConfig()):
        self.engine = engine
        # Explicit None check: an EMPTY EmbeddingCache is falsy (__len__),
        # and `cache or ...` would silently replace the caller's shared
        # cache with a private one.
        self.cache = cache if cache is not None else EmbeddingCache()
        self.cfg = cfg

    # -- per-chain helpers -------------------------------------------------

    def _chain_key(self, entry, bucket: int) -> str:
        """Embedding identity: chain content + bucket + everything else
        the encoder output depends on (weights, control flag, dtype)."""
        return chain_hash(entry.raw, extra=(
            "emb", bucket, self.engine.weights_signature(),
            self.engine.cfg.input_indep,
            self.engine.model.cfg.gnn.compute_dtype))

    def _padded_graph(self, entry, bucket: int):
        raw = entry.raw
        if self.engine.cfg.input_indep:
            raw = dict(raw,
                       node_feats=np.zeros_like(raw["node_feats"]),
                       edge_feats=np.zeros_like(raw["edge_feats"]))
        return pad_graph(raw, bucket)

    @staticmethod
    def _chain_sig(raw: Dict[str, np.ndarray]) -> Tuple[int, int, int, int]:
        return (int(raw["nbr_idx"].shape[1]),
                int(raw["src_nbr_eids"].shape[2]),
                int(raw["node_feats"].shape[1]),
                int(raw["edge_feats"].shape[2]))

    # -- encode phase ------------------------------------------------------

    def ensure_embeddings(self, library: ChainLibrary,
                          chain_ids: Sequence[str],
                          deadline=None):
        """Encode every chain in ``chain_ids`` not already cached.
        Returns (chain_id -> (feats, n, bucket), encodes_executed,
        cache_hits, encode_batches). ``deadline`` (a
        ``serving.admission.Deadline``) is checked before each encoder
        dispatch — an expired budget raises :class:`DeadlineExceeded`
        instead of burning more device work for a client that gave up."""
        out: Dict[str, Tuple[np.ndarray, int, int]] = {}
        todo = defaultdict(list)  # (bucket, sig) -> [(id, key, entry)]
        hits = 0
        for cid in chain_ids:
            entry = library[cid]
            bucket = self.engine.chain_bucket(entry.n)
            key = self._chain_key(entry, bucket)
            cached = self.cache.get(key)
            if cached is not None:
                out[cid] = (cached[0], cached[1], bucket)
                hits += 1
            else:
                todo[(bucket, self._chain_sig(entry.raw))].append(
                    (cid, key, entry))
        executed = 0
        batches = 0
        for (bucket, sig), items in sorted(todo.items(),
                                           key=lambda kv: kv[0][:1]):
            for lo in range(0, len(items), self.cfg.encode_batch):
                if deadline is not None and deadline.expired:
                    expired_counter("screen")
                    raise DeadlineExceeded(
                        f"screen deadline ({deadline.budget_s * 1e3:.0f}ms)"
                        f" expired during encode ({executed} chains done)")
                chunk = items[lo:lo + self.cfg.encode_batch]
                slots = _slots(len(chunk), self.cfg.encode_batch)
                graphs = [self._padded_graph(e, bucket)
                          for _, _, e in chunk]
                graphs.extend([graphs[0]] * (slots - len(chunk)))
                graph_batch = stack_graphs(graphs)
                compiled = self.engine.encode_executable(
                    bucket, sig, slots, graph_batch)
                feats = np.asarray(compiled(
                    self.engine.params, self.engine.batch_stats,
                    graph_batch))
                for i, (cid, key, entry) in enumerate(chunk):
                    self.cache.put(key, feats[i], entry.n)
                    out[cid] = (feats[i], entry.n, bucket)
                executed += len(chunk)
                batches += 1
                _ENCODED.inc(len(chunk))
                _ENCODE_BATCHES.inc()
        return out, executed, hits, batches

    # -- full screen -------------------------------------------------------

    def screen(
        self,
        library: ChainLibrary,
        pairs: Sequence[Tuple[str, str]],
        manifest: Optional[ScreenManifest] = None,
        guard=None,
        after_batch: Optional[Callable[[int], None]] = None,
        trace_id: str = "",
        deadline=None,
    ) -> ScreenResult:
        """Score ``pairs`` (chain-id tuples); see module docstring.

        ``guard`` is a PR-1 PreemptionGuard (or any object with a
        ``requested`` flag) polled at decode-batch boundaries.
        ``after_batch(num_batches)`` is a test hook (fault injection).
        ``trace_id`` (request-scoped tracing, obs/reqtrace.py) labels
        this screen's span events so one id connects the HTTP response,
        ``events.jsonl``, and the phase histograms. ``deadline`` (a
        ``serving.admission.Deadline``; the synchronous ``POST /screen``
        path) is enforced at encode- and decode-batch boundaries —
        expiry raises :class:`DeadlineExceeded` (manifest-backed CLI
        screens keep using ``guard`` + resume instead: their half-done
        work is durable, a synchronous HTTP screen's is not)."""
        trace_attrs = {"trace_id": trace_id} if trace_id else {}
        resumed_pairs = 0
        resumed = False
        if manifest is not None:
            before = len(pairs)
            pairs = manifest.remaining(pairs)
            resumed_pairs = before - len(pairs)
            resumed = resumed_pairs > 0

        needed = sorted({cid for p in pairs for cid in p})
        t0 = time.perf_counter()
        with obs_spans.span("screen_encode", chains=len(needed),
                            **trace_attrs):
            emb, executed, enc_hits, enc_batches = self.ensure_embeddings(
                library, needed, deadline=deadline)
        encode_s = time.perf_counter() - t0

        # Pairs are oriented so bucket1 <= bucket2: the top-k summary is
        # transpose-invariant, and canonical orientation halves the
        # decode-executable inventory for asymmetric libraries. The
        # recorded chain1/chain2 match the orientation actually decoded.
        groups = defaultdict(list)  # (b1, b2) -> [(pid, c1, c2)]
        for c1, c2 in pairs:
            pid = pair_id(c1, c2)
            if emb[c1][2] > emb[c2][2]:
                c1, c2 = c2, c1
            groups[(emb[c1][2], emb[c2][2])].append((pid, c1, c2))

        scored = 0
        decode_batches = 0
        preempted = False
        run_records: List[Dict] = []
        t0 = time.perf_counter()
        with obs_spans.span("screen_decode", pairs=len(pairs),
                            **trace_attrs):
            for (b1, b2), items in sorted(groups.items()):
                if preempted:
                    break
                for lo in range(0, len(items), self.cfg.decode_batch):
                    if guard is not None and getattr(guard, "requested",
                                                     False):
                        preempted = True
                        _PREEMPTIONS.inc()
                        break
                    if deadline is not None and deadline.expired:
                        expired_counter("screen")
                        raise DeadlineExceeded(
                            "screen deadline "
                            f"({deadline.budget_s * 1e3:.0f}ms) expired "
                            f"during decode ({scored}/{len(pairs)} pairs "
                            "scored)")
                    chunk = items[lo:lo + self.cfg.decode_batch]
                    slots = _slots(len(chunk), self.cfg.decode_batch)
                    rows = chunk + [chunk[0]] * (slots - len(chunk))
                    feats1 = np.stack([emb[c1][0] for _, c1, _ in rows])
                    feats2 = np.stack([emb[c2][0] for _, _, c2 in rows])
                    mask1 = np.stack([np.arange(b1) < emb[c1][1]
                                      for _, c1, _ in rows])
                    mask2 = np.stack([np.arange(b2) < emb[c2][1]
                                      for _, _, c2 in rows])
                    compiled = self.engine.decode_executable(
                        b1, b2, slots, (feats1, feats2, mask1, mask2))
                    probs = np.asarray(compiled(
                        self.engine.params, self.engine.batch_stats,
                        feats1, feats2, mask1, mask2))
                    for i, (pid, c1, c2) in enumerate(chunk):
                        n1, n2 = emb[c1][1], emb[c2][1]
                        record = {
                            "pair_id": pid,
                            "chain1": c1, "chain2": c2,
                            "n1": n1, "n2": n2,
                            "bucket": [b1, b2],
                            **pair_summary(probs[i, :n1, :n2],
                                           self.cfg.top_k),
                        }
                        run_records.append(record)
                        if manifest is not None:
                            manifest.mark_done(pid, record)
                    scored += len(chunk)
                    decode_batches += 1
                    _PAIRS.inc(len(chunk))
                    _DECODE_BATCHES.inc()
                    if manifest is not None:
                        # Atomic per-batch checkpoint: a kill after this
                        # line never re-scores the batch; a kill before
                        # it re-scores at most one batch, but only into a
                        # manifest that never recorded it — exactly-once
                        # COMPLETION either way.
                        manifest.flush()
                    if after_batch is not None:
                        after_batch(decode_batches)
        decode_s = time.perf_counter() - t0

        if manifest is not None:
            # The manifest's ledger covers resumed pairs too, so a
            # resumed run's ranked output spans the WHOLE screen.
            manifest.flush()
            records = rank_records(manifest.records())
        else:
            records = rank_records(run_records)
        return ScreenResult(
            records=records,
            pairs_total=len(pairs) + resumed_pairs,
            pairs_scored=scored,
            pairs_resumed=resumed_pairs,
            chains=len(needed),
            encodes_executed=executed,
            encode_cache_hits=enc_hits,
            encode_batches=enc_batches,
            decode_batches=decode_batches,
            preempted=preempted,
            resumed=resumed,
            encode_seconds=encode_s,
            decode_seconds=decode_s,
            emb_cache=self.cache.stats(),
        )
