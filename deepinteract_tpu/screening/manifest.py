"""Screen job manifest: atomic progress checkpoints + exactly-once resume.

A bulk screen is long-running batch work on preemptible capacity, so it
gets the same discipline training got in PR 1: progress is flushed
atomically (tmp + ``os.replace``) after every decode batch, and a
SIGTERM'd screen rerun against the same manifest scores ONLY the
remaining pairs — each pair is decoded exactly once across the runs
(pinned by the chaos test in tests/test_screening.py).

The manifest stores each completed pair's full score record, so the final
ranked JSONL/CSV can always be regenerated from the manifest alone — a
resumed run's output covers the whole screen, not just its own slice.
The library signature guards against resuming over different data.

Durability (robustness/artifacts.py): flushes carry a SHA-256 integrity
sidecar and loads verify it before parsing. A corrupt manifest (torn,
truncated, bit-flipped — or one whose sidecar is) is quarantined aside
with a logged reason and the screen starts FRESH: loudly recoverable —
the lost batches are simply re-derived and re-scored, which costs
compute but can never adopt a wrong ledger. A sidecar-less manifest from
an older run still resumes (legacy-unverified, warned).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

from deepinteract_tpu.robustness import artifacts

logger = logging.getLogger(__name__)

MANIFEST_VERSION = 1
MANIFEST_KIND = "screen-manifest"


def pair_id(chain1: str, chain2: str) -> str:
    return f"{chain1}|{chain2}"


class ScreenManifest:
    """Completed-pair ledger with atomic flushes."""

    def __init__(self, path: str, signature: str, total_pairs: int,
                 completed: Optional[Dict[str, Dict]] = None):
        self.path = path
        self.signature = signature
        self.total_pairs = int(total_pairs)
        self.completed: Dict[str, Dict] = dict(completed or {})
        self._dirty = False

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def load_or_create(cls, path: str, signature: str,
                       total_pairs: int) -> Tuple["ScreenManifest", bool]:
        """(manifest, resumed). An existing manifest is resumed only when
        it verifies against its integrity sidecar AND its version and
        library signature match. A corrupt file is quarantined (fresh
        start — lost batches re-derive); a mismatched-but-intact one is
        kept aside as ``<path>.stale`` rather than silently merged into a
        different screen."""
        artifacts.sweep_tmp(os.path.dirname(os.path.abspath(path)),
                            prefix=os.path.basename(path))
        if os.path.exists(path):
            data = None
            try:
                raw = artifacts.verify_read(path, kind=MANIFEST_KIND,
                                            require_sidecar=False)
                data = json.loads(raw.decode("utf-8"))
            except (artifacts.ArtifactError, UnicodeDecodeError,
                    json.JSONDecodeError) as exc:
                # Positive corruption (hash/length mismatch, unreadable
                # sidecar, or unparseable verified bytes): quarantine and
                # start fresh — loud, recoverable, never adopted.
                artifacts.quarantine(path, MANIFEST_KIND, str(exc))
            except OSError as exc:
                # TRANSIENT read failure (flaky FS), not corruption: the
                # ledger may be intact, so keep it aside as .stale rather
                # than letting the fresh manifest's first flush overwrite
                # it (pre-integrity behavior, preserved).
                logger.warning("could not read screen manifest %s (%s); "
                               "keeping it aside as .stale", path, exc)
            if (data and data.get("version") == MANIFEST_VERSION
                    and data.get("signature") == signature):
                return cls(path, signature, total_pairs,
                           completed=data.get("completed", {})), True
            if os.path.exists(path):
                try:
                    os.replace(path, path + ".stale")
                except OSError:
                    pass
        return cls(path, signature, total_pairs), False

    def mark_done(self, pid: str, record: Dict) -> None:
        self.completed[pid] = record
        self._dirty = True

    def discard(self, pid: str) -> bool:
        """Un-complete one work unit (True when it was completed). The
        index builder uses this when a LEDGER-complete partition's shard
        turns out corrupt on disk: quarantine the shard, discard its
        ledger entry, and only that partition is rebuilt."""
        if pid in self.completed:
            del self.completed[pid]
            self._dirty = True
            return True
        return False

    def flush(self) -> None:
        """Atomic write; called after every decode batch and on
        preemption. A reader never sees a torn manifest."""
        if not self._dirty and os.path.exists(self.path):
            return
        payload = {
            "version": MANIFEST_VERSION,
            "signature": self.signature,
            "total_pairs": self.total_pairs,
            "num_completed": len(self.completed),
            "completed": self.completed,
        }
        artifacts.atomic_write_artifact(
            self.path, json.dumps(payload), MANIFEST_KIND,
            version=MANIFEST_VERSION,
            extra={"signature": self.signature})
        self._dirty = False

    # -- queries -----------------------------------------------------------

    def remaining(self, pairs: Sequence[Tuple[str, str]]
                  ) -> List[Tuple[str, str]]:
        return [p for p in pairs if pair_id(*p) not in self.completed]

    def records(self) -> List[Dict]:
        return list(self.completed.values())

    @property
    def done(self) -> bool:
        return len(self.completed) >= self.total_pairs
