"""Screen job manifest: atomic progress checkpoints + exactly-once resume.

A bulk screen is long-running batch work on preemptible capacity, so it
gets the same discipline training got in PR 1: progress is flushed
atomically (tmp + ``os.replace``) after every decode batch, and a
SIGTERM'd screen rerun against the same manifest scores ONLY the
remaining pairs — each pair is decoded exactly once across the runs
(pinned by the chaos test in tests/test_screening.py).

The manifest stores each completed pair's full score record, so the final
ranked JSONL/CSV can always be regenerated from the manifest alone — a
resumed run's output covers the whole screen, not just its own slice.
The library signature guards against resuming over different data.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

MANIFEST_VERSION = 1


def pair_id(chain1: str, chain2: str) -> str:
    return f"{chain1}|{chain2}"


class ScreenManifest:
    """Completed-pair ledger with atomic flushes."""

    def __init__(self, path: str, signature: str, total_pairs: int,
                 completed: Optional[Dict[str, Dict]] = None):
        self.path = path
        self.signature = signature
        self.total_pairs = int(total_pairs)
        self.completed: Dict[str, Dict] = dict(completed or {})
        self._dirty = False

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def load_or_create(cls, path: str, signature: str,
                       total_pairs: int) -> Tuple["ScreenManifest", bool]:
        """(manifest, resumed). An existing manifest is resumed only when
        its version AND library signature match; anything else starts
        fresh (the stale file is kept aside as ``<path>.stale`` rather
        than silently merged into a different screen)."""
        if os.path.exists(path):
            try:
                with open(path) as fh:
                    data = json.load(fh)
            except (OSError, json.JSONDecodeError):
                data = None
            if (data and data.get("version") == MANIFEST_VERSION
                    and data.get("signature") == signature):
                return cls(path, signature, total_pairs,
                           completed=data.get("completed", {})), True
            os.replace(path, path + ".stale")
        return cls(path, signature, total_pairs), False

    def mark_done(self, pid: str, record: Dict) -> None:
        self.completed[pid] = record
        self._dirty = True

    def flush(self) -> None:
        """Atomic write; called after every decode batch and on
        preemption. A reader never sees a torn manifest."""
        if not self._dirty and os.path.exists(self.path):
            return
        payload = {
            "version": MANIFEST_VERSION,
            "signature": self.signature,
            "total_pairs": self.total_pairs,
            "num_completed": len(self.completed),
            "completed": self.completed,
        }
        tmp = self.path + ".tmp"
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, self.path)
        self._dirty = False

    # -- queries -----------------------------------------------------------

    def remaining(self, pairs: Sequence[Tuple[str, str]]
                  ) -> List[Tuple[str, str]]:
        return [p for p in pairs if pair_id(*p) not in self.completed]

    def records(self) -> List[Dict]:
        return list(self.completed.values())

    @property
    def done(self) -> bool:
        return len(self.completed) >= self.total_pairs
