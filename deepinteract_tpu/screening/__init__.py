"""Bulk screening: all-vs-all chain-pair scoring over the serving engine.

The model is siamese by construction (one shared-weight Geometric
Transformer leg per chain, then an interaction stem + decoder), so an
N-chain screen needs N encoder passes and N^2 cheap decodes — this
package turns the serving stack into exactly that pipeline:

* :mod:`~deepinteract_tpu.screening.library` — chain libraries from npz
  dirs / packed memmaps / synthetic generators, plus pair enumeration;
* :mod:`~deepinteract_tpu.screening.embcache` — content-addressed
  embedding cache (in-memory LRU + optional npz spill);
* :mod:`~deepinteract_tpu.screening.runner` — the pair scheduler over
  the engine's split-phase AOT executables;
* :mod:`~deepinteract_tpu.screening.manifest` — atomic progress ledger
  with exactly-once preemption resume;
* :mod:`~deepinteract_tpu.screening.scoring` — top-k contact summary
  shared with ``cli/predict.py --top_k``.

Entry points: ``python -m deepinteract_tpu.cli.screen`` (offline) and
``POST /screen`` on the serving API (small synchronous screens).
"""

from deepinteract_tpu.screening.embcache import (  # noqa: F401
    EmbeddingCache,
    chain_hash,
)
from deepinteract_tpu.screening.library import (  # noqa: F401
    ChainEntry,
    ChainLibrary,
    enumerate_pairs,
)
from deepinteract_tpu.screening.manifest import (  # noqa: F401
    ScreenManifest,
    pair_id,
)
from deepinteract_tpu.screening.runner import (  # noqa: F401
    ScreenConfig,
    ScreenResult,
    ScreenRunner,
)
from deepinteract_tpu.screening.scoring import (  # noqa: F401
    pair_summary,
    rank_records,
)
