"""On-disk proteome-index format: partitioned npz shards + manifest.

Layout (one directory per index)::

    <index_dir>/
        index_manifest.json            # partition table (+ sidecar)
        build_ledger.json              # exactly-once build state (PR-6)
        partitions/
            part-b0064-0000.npz        # padded embeddings (+ sidecar)
            part-b0064-0001.npz
            part-b0128-0000.npz

Every shard holds the padded per-chain encoder embeddings for one
(bucket, sequence) partition — exactly what ``ScreenRunner``'s decode
phase consumes — plus the mean-pooled prefilter vectors so a query can
rank the whole partition without touching the full feature tensors'
semantics. All writes go through ``robustness/artifacts.py``: tmp +
fsync + rename with an integrity sidecar whose ``extra`` carries the
``weights_signature`` the embeddings were computed under, so
``verify_read(..., expect={"weights_signature": ...})`` turns version
drift into a typed :class:`StaleArtifact` for free (cli/fsck.py's
stale-partition report and the server's serve-time refusal both lean on
this).

Shard npz keys::

    feats      float32 [k, bucket, C]   padded encoder embeddings
    pooled     float32 [k, C]           l2-normalized masked mean-pool
    lengths    int64   [k]              true residue counts
    chain_ids  str     [k]              library chain ids

The manifest is the partition table: which chains live in which shard,
under which bucket, computed under which weights/library signatures.
The embedding identity fields (``weights_signature``, ``input_indep``,
``compute_dtype``) mirror ``ScreenRunner._chain_key`` so an index is
bound to the same cache-key space as the live embedding cache.
"""

from __future__ import annotations

import io
import json
import os
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deepinteract_tpu.robustness import artifacts

INDEX_FORMAT_VERSION = 1
INDEX_MANIFEST_KIND = "index-manifest"
INDEX_SHARD_KIND = "index-shard"
MANIFEST_BASENAME = "index_manifest.json"
LEDGER_BASENAME = "build_ledger.json"
PARTITIONS_DIRNAME = "partitions"

# Manifest keys every reader validates before trusting the table.
_MANIFEST_REQUIRED = ("format_version", "weights_signature",
                      "library_signature", "input_indep", "compute_dtype",
                      "feat_dim", "partition_size", "num_chains",
                      "partitions")
_PARTITION_REQUIRED = ("partition_id", "file", "bucket", "chains",
                       "lengths")


def partition_id(bucket: int, seq: int) -> str:
    return f"part-b{bucket:04d}-{seq:04d}"


def shard_path(index_dir: str, pid: str) -> str:
    return os.path.join(index_dir, PARTITIONS_DIRNAME, f"{pid}.npz")


def manifest_path(index_dir: str) -> str:
    return os.path.join(index_dir, MANIFEST_BASENAME)


def ledger_path(index_dir: str) -> str:
    return os.path.join(index_dir, LEDGER_BASENAME)


def write_partition(index_dir: str, pid: str, bucket: int,
                    chain_ids: Sequence[str], lengths: Sequence[int],
                    feats: np.ndarray, pooled: np.ndarray,
                    weights_signature: str) -> str:
    """Serialize one shard and land it durably (atomic + sidecar)."""
    if feats.shape[0] != len(chain_ids) or pooled.shape[0] != len(chain_ids):
        raise ValueError(
            f"shard {pid}: {len(chain_ids)} chains but feats "
            f"{feats.shape} / pooled {pooled.shape}")
    path = shard_path(index_dir, pid)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf,
             feats=np.asarray(feats, np.float32),
             pooled=np.asarray(pooled, np.float32),
             lengths=np.asarray(lengths, np.int64),
             chain_ids=np.asarray(list(chain_ids)))
    artifacts.atomic_write_artifact(
        path, buf.getvalue(), INDEX_SHARD_KIND,
        version=INDEX_FORMAT_VERSION,
        extra={"weights_signature": weights_signature,
               "partition_id": pid, "bucket": int(bucket),
               "num_chains": len(chain_ids)})
    return path


def read_partition(path: str,
                   expect_signature: Optional[str] = None
                   ) -> Dict[str, Any]:
    """Verified shard read: sidecar first, then a pickle-free np.load.

    Raises :class:`artifacts.CorruptArtifact` on byte damage or a
    structurally invalid payload, :class:`artifacts.StaleArtifact` when
    ``expect_signature`` no longer matches the sidecar."""
    expect = ({"weights_signature": expect_signature}
              if expect_signature is not None else None)
    raw = artifacts.verify_read(path, kind=INDEX_SHARD_KIND, expect=expect)
    try:
        with np.load(io.BytesIO(raw), allow_pickle=False) as data:
            out = {"feats": data["feats"], "pooled": data["pooled"],
                   "lengths": data["lengths"],
                   "chain_ids": [str(c) for c in data["chain_ids"]]}
    except (ValueError, KeyError, OSError) as exc:
        raise artifacts.CorruptArtifact(path, f"undecodable shard: {exc}")
    k = len(out["chain_ids"])
    if (out["feats"].ndim != 3 or out["pooled"].ndim != 2
            or out["feats"].shape[0] != k or out["pooled"].shape[0] != k
            or out["lengths"].shape != (k,)):
        raise artifacts.CorruptArtifact(
            path, f"inconsistent shard shapes for {k} chains: "
                  f"feats {out['feats'].shape} pooled {out['pooled'].shape}"
                  f" lengths {out['lengths'].shape}")
    return out


def write_manifest(index_dir: str, manifest: Dict[str, Any]) -> str:
    missing = [k for k in _MANIFEST_REQUIRED if k not in manifest]
    if missing:
        raise ValueError(f"index manifest missing keys {missing}")
    path = manifest_path(index_dir)
    os.makedirs(index_dir, exist_ok=True)
    artifacts.atomic_write_artifact(
        path, json.dumps(manifest, indent=1, sort_keys=True).encode(),
        INDEX_MANIFEST_KIND, version=INDEX_FORMAT_VERSION,
        extra={"weights_signature": manifest["weights_signature"],
               "library_signature": manifest["library_signature"]})
    return path


def read_manifest(index_dir: str,
                  require_sidecar: bool = True) -> Dict[str, Any]:
    """Verified manifest read + structural validation."""
    path = manifest_path(index_dir)
    manifest = artifacts.verify_json(path, kind=INDEX_MANIFEST_KIND,
                                     require_sidecar=require_sidecar)
    missing = [k for k in _MANIFEST_REQUIRED if k not in manifest]
    if missing:
        raise artifacts.CorruptArtifact(
            path, f"manifest missing keys {missing}")
    for part in manifest["partitions"]:
        bad = [k for k in _PARTITION_REQUIRED if k not in part]
        if bad:
            raise artifacts.CorruptArtifact(
                path, f"partition entry missing keys {bad}: "
                      f"{part.get('partition_id', '?')}")
    return manifest


class ChainIndex:
    """Read-side handle: manifest table + lazily loaded, verified shards.

    Shard loads are cached (an index partition is immutable once built);
    a shard that fails verification is quarantined on the spot and the
    typed error propagates, so a serving worker answers 500/400 instead
    of ranking against garbage embeddings."""

    def __init__(self, index_dir: str, manifest: Dict[str, Any]):
        self.index_dir = index_dir
        self.manifest = manifest
        self._parts = {p["partition_id"]: p
                       for p in manifest["partitions"]}
        self._loaded: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._chain_loc: Dict[str, Tuple[str, int]] = {}
        for p in manifest["partitions"]:
            for row, cid in enumerate(p["chains"]):
                self._chain_loc[cid] = (p["partition_id"], row)

    @classmethod
    def open(cls, index_dir: str) -> "ChainIndex":
        return cls(index_dir, read_manifest(index_dir))

    # -- manifest views ----------------------------------------------------

    @property
    def weights_signature(self) -> str:
        return str(self.manifest["weights_signature"])

    @property
    def library_signature(self) -> str:
        return str(self.manifest["library_signature"])

    @property
    def num_chains(self) -> int:
        return int(self.manifest["num_chains"])

    @property
    def feat_dim(self) -> int:
        return int(self.manifest["feat_dim"])

    def partition_ids(self) -> List[str]:
        return sorted(self._parts)

    def partition(self, pid: str) -> Dict[str, Any]:
        return self._parts[pid]

    def buckets(self) -> List[int]:
        return sorted({int(p["bucket"]) for p in self._parts.values()})

    def chain_ids(self) -> List[str]:
        return sorted(self._chain_loc)

    def __contains__(self, chain_id: str) -> bool:
        return chain_id in self._chain_loc

    # -- shard access ------------------------------------------------------

    def load_partition(self, pid: str) -> Dict[str, Any]:
        """Verified shard payload, cached; quarantines on corruption."""
        with self._lock:
            hit = self._loaded.get(pid)
        if hit is not None:
            return hit
        path = shard_path(self.index_dir, pid)
        try:
            data = read_partition(
                path, expect_signature=self.weights_signature)
        except FileNotFoundError as exc:
            # The manifest promises this shard; its absence (lost or
            # already quarantined) is damage, not a lookup miss.
            raise artifacts.CorruptArtifact(
                path, "manifest lists this shard but it is missing on "
                "disk; rebuild the partition") from exc
        except artifacts.CorruptArtifact:
            artifacts.quarantine(path, INDEX_SHARD_KIND,
                                 "failed verification on read")
            raise
        if data["chain_ids"] != list(self._parts[pid]["chains"]):
            artifacts.quarantine(path, INDEX_SHARD_KIND,
                                 "chain ids disagree with manifest")
            raise artifacts.CorruptArtifact(
                path, "shard chain ids disagree with the manifest")
        with self._lock:
            self._loaded[pid] = data
        return data

    def iter_pooled(self, partitions: Optional[Iterable[str]] = None):
        """Yield (pid, chain_ids, lengths, pooled) per selected shard —
        the prefilter's scan surface."""
        for pid in (sorted(partitions) if partitions is not None
                    else self.partition_ids()):
            if pid not in self._parts:
                raise KeyError(f"unknown index partition {pid!r}")
            data = self.load_partition(pid)
            yield pid, data["chain_ids"], data["lengths"], data["pooled"]

    def chain_feats(self, chain_id: str) -> Tuple[np.ndarray, int, int]:
        """(padded feats [bucket, C], n, bucket) for an indexed chain —
        lets a query that already lives in the index skip its encoder
        pass entirely."""
        if chain_id not in self._chain_loc:
            raise KeyError(f"chain {chain_id!r} is not in the index")
        pid, row = self._chain_loc[chain_id]
        data = self.load_partition(pid)
        return (data["feats"][row], int(data["lengths"][row]),
                int(self._parts[pid]["bucket"]))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            resident = len(self._loaded)
        return {"index_dir": self.index_dir,
                "chains": self.num_chains,
                "partitions": len(self._parts),
                "buckets": self.buckets(),
                "feat_dim": self.feat_dim,
                "weights_signature": self.weights_signature,
                "library_signature": self.library_signature,
                "partitions_resident": resident}
