"""Proteome index: a persistent, sharded embedding index (ISSUE-17).

The layer between the PR-6 embedding cache and the PR-13/16 fleet: a
durable, versioned on-disk index over an entire chain library, plus the
query funnel that ranks every library chain against a query with a cheap
embedding-space pre-filter and streams only the top-M survivors into the
expensive contact decoder.

    format.py    on-disk shard/manifest format + ChainIndex reader
    builder.py   resumable exactly-once index builds, verify, merge
    prefilter.py pooled-embedding bilinear pre-filter (the funnel mouth)
    funnel.py    IndexedQueryRunner: encode query -> prefilter -> decode
"""

from deepinteract_tpu.index.builder import (
    BuildResult,
    build_index,
    merge_indexes,
    plan_partitions,
    verify_index,
)
from deepinteract_tpu.index.format import (
    INDEX_MANIFEST_KIND,
    INDEX_SHARD_KIND,
    MANIFEST_BASENAME,
    PARTITIONS_DIRNAME,
    ChainIndex,
    manifest_path,
    read_manifest,
    read_partition,
    shard_path,
    write_manifest,
    write_partition,
)
from deepinteract_tpu.index.funnel import (
    IndexedQueryRunner,
    QueryConfig,
    QueryResult,
)
from deepinteract_tpu.index.prefilter import (
    bilinear_scores,
    pooled_embedding,
    prefilter,
)

__all__ = [
    "INDEX_MANIFEST_KIND",
    "INDEX_SHARD_KIND",
    "MANIFEST_BASENAME",
    "PARTITIONS_DIRNAME",
    "BuildResult",
    "ChainIndex",
    "IndexedQueryRunner",
    "QueryConfig",
    "QueryResult",
    "bilinear_scores",
    "build_index",
    "manifest_path",
    "merge_indexes",
    "plan_partitions",
    "pooled_embedding",
    "prefilter",
    "read_manifest",
    "read_partition",
    "shard_path",
    "verify_index",
    "write_manifest",
    "write_partition",
]
