"""The indexed query funnel: encode query -> pre-filter -> decode top-M.

One :class:`IndexedQueryRunner` turns "what does this chain bind?" into
a ranked-partner list against a :class:`~deepinteract_tpu.index.format.
ChainIndex`, paying the docking-funnel cost shape: ONE encoder pass for
the query (zero when the query is index-resident), one GEMV over pooled
embeddings for the whole library, and decode micro-batches over only
the top-M pre-filter survivors.

Decode dispatch mirrors ``screening/runner.py`` exactly — canonical
``bucket1 <= bucket2`` orientation, power-of-two slot padding, the same
AOT decode executables — so an index query and a live screen share the
engine's compiled inventory. The ``di_index_pairs_decoded_total``
counter (and per-result ``pairs_decoded``) is the testable proof that
the decoder runs on survivors only, never the full library.

Deadline semantics: the serving path (``on_deadline="partial"``) flushes
what is already ranked with ``partial=True`` at the next batch boundary
instead of burning the budget's corpse; CLI paths keep the raising
behavior (their work is not latency-bound).
"""

from __future__ import annotations

import dataclasses
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepinteract_tpu.index.prefilter import pooled_embedding, prefilter
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs import spans as obs_spans
from deepinteract_tpu.screening.embcache import EmbeddingCache
from deepinteract_tpu.screening.library import ChainEntry, ChainLibrary
from deepinteract_tpu.screening.manifest import pair_id
from deepinteract_tpu.screening.runner import (
    ScreenConfig,
    ScreenRunner,
    _slots,
)
from deepinteract_tpu.screening.scoring import pair_summary, rank_records
from deepinteract_tpu.serving.admission import (
    DeadlineExceeded,
    expired_counter,
)

_QUERIES = obs_metrics.counter(
    "di_index_queries_total", "Ranked-partner queries served")
_DECODED = obs_metrics.counter(
    "di_index_pairs_decoded_total",
    "Pre-filter survivors decoded by index queries (the funnel neck)")
_DECODE_BATCHES = obs_metrics.counter(
    "di_index_decode_batches_total", "Index-query decode dispatches")
_PARTIAL = obs_metrics.counter(
    "di_index_partial_results_total",
    "Index queries flushed partially at deadline expiry")


@dataclasses.dataclass(frozen=True)
class QueryConfig:
    """Funnel knobs (CLI surface: ``cli/query.py``)."""

    top_m: int = 32        # pre-filter survivors fed to the decoder
    top_k: int = 10        # contacts kept per pair summary
    decode_batch: int = 8  # survivor pairs per decode dispatch


@dataclasses.dataclass
class QueryResult:
    """One ranked-partner query's outcome."""

    query: str
    records: List[Dict]      # decode-ranked survivors (rank_records)
    prefilter_ranked: List[Dict]  # survivors in prefilter order
    candidates: int          # chains scanned by the prefilter
    survivors: int
    pairs_decoded: int
    decode_batches: int
    encodes_executed: int
    partial: bool
    encode_seconds: float
    decode_seconds: float

    @property
    def prefilter_survivor_frac(self) -> float:
        return self.survivors / max(1, self.candidates)

    def summary(self) -> Dict:
        return {
            "candidates": self.candidates,
            "survivors": self.survivors,
            "pairs_decoded": self.pairs_decoded,
            "decode_batches": self.decode_batches,
            "encodes_executed": self.encodes_executed,
            "prefilter_survivor_frac": round(
                self.prefilter_survivor_frac, 4),
            "partial": self.partial,
            "encode_seconds": round(self.encode_seconds, 3),
            "decode_seconds": round(self.decode_seconds, 3),
        }


class IndexedQueryRunner:
    """Schedules ranked-partner queries over a resident engine + index.

    Refuses to run when the index was built under different weights
    than the engine serves (the sidecar-backed ``weights_signature``
    check) unless ``allow_stale`` — a stale ranking is worse than a
    refused one."""

    def __init__(self, engine, index,
                 cfg: QueryConfig = QueryConfig(),
                 cache: Optional[EmbeddingCache] = None,
                 allow_stale: bool = False):
        self.engine = engine
        self.index = index
        self.cfg = cfg
        self._runner = ScreenRunner(
            engine, cache=cache,
            cfg=ScreenConfig(top_k=cfg.top_k,
                             decode_batch=cfg.decode_batch,
                             encode_batch=cfg.decode_batch))
        if not allow_stale and (index.weights_signature
                                != engine.weights_signature()):
            raise ValueError(
                f"stale index: built under weights "
                f"{index.weights_signature!r} but the engine serves "
                f"{engine.weights_signature()!r} (rebuild the index or "
                f"pass allow_stale)")

    # -- query embedding sources ------------------------------------------

    def query_from_raw(self, chain_id: str, raw: Dict[str, np.ndarray],
                       **kw) -> QueryResult:
        """Query with a chain supplied as a raw graph (one encoder
        pass, embedding-cache backed)."""
        n = int(raw["node_feats"].shape[0])
        lib = ChainLibrary([ChainEntry(chain_id, raw, n)])
        t0 = time.perf_counter()
        with obs_spans.span("index_query_encode", chains=1):
            emb, executed, _, _ = self._runner.ensure_embeddings(
                lib, [chain_id], deadline=kw.get("deadline"))
        feats, nq, bq = emb[chain_id]
        return self._query(chain_id, feats, nq, bq,
                           encode_seconds=time.perf_counter() - t0,
                           encodes_executed=executed, **kw)

    def query_from_index(self, chain_id: str, **kw) -> QueryResult:
        """Query with an index-resident chain: zero encoder passes."""
        feats, nq, bq = self.index.chain_feats(chain_id)
        return self._query(chain_id, feats, nq, bq,
                           encode_seconds=0.0, encodes_executed=0, **kw)

    # -- the funnel --------------------------------------------------------

    def _query(self, chain_id: str, q_feats: np.ndarray, nq: int,
               bq: int, encode_seconds: float, encodes_executed: int,
               partitions=None, deadline=None,
               on_deadline: str = "raise") -> QueryResult:
        if on_deadline not in ("raise", "partial"):
            raise ValueError(f"on_deadline must be 'raise' or 'partial',"
                             f" got {on_deadline!r}")
        _QUERIES.inc()
        q_vec = pooled_embedding(q_feats, nq)
        survivors, candidates = prefilter(
            self.index, q_vec, self.cfg.top_m, partitions=partitions,
            exclude=(chain_id,))

        # Group survivors by decode signature, canonical b1 <= b2 with
        # chain-id tie-break on equal buckets — the exact orientation
        # ScreenRunner.screen uses (swap only on strictly greater
        # bucket, enumeration order otherwise). The decoder is not
        # bit-symmetric under swapping its arguments, so matching the
        # screen's orientation is what makes funnel and bulk-screen
        # scores byte-identical for the same pair.
        groups = defaultdict(list)  # (b1, b2, query_is_1) -> [survivor]
        for s in survivors:
            bc = s["bucket"]
            if bq < bc or (bq == bc and chain_id <= s["chain_id"]):
                groups[(bq, bc, True)].append(s)
            else:
                groups[(bc, bq, False)].append(s)

        records: List[Dict] = []
        decoded = 0
        decode_batches = 0
        partial = False
        t0 = time.perf_counter()
        with obs_spans.span("index_query_decode", survivors=len(survivors)):
            for (b1, b2, q_first), items in sorted(
                    groups.items(), key=lambda kv: kv[0][:2]):
                if partial:
                    break
                for lo in range(0, len(items), self.cfg.decode_batch):
                    if deadline is not None and deadline.expired:
                        expired_counter("index_query")
                        if on_deadline == "partial":
                            partial = True
                            _PARTIAL.inc()
                            break
                        raise DeadlineExceeded(
                            "index query deadline "
                            f"({deadline.budget_s * 1e3:.0f}ms) expired "
                            f"during decode ({decoded}/{len(survivors)} "
                            "survivors decoded)")
                    chunk = items[lo:lo + self.cfg.decode_batch]
                    slots = _slots(len(chunk), self.cfg.decode_batch)
                    rows = chunk + [chunk[0]] * (slots - len(chunk))
                    cand = [self.index.chain_feats(s["chain_id"])
                            for s in rows]
                    if q_first:
                        feats1 = np.stack([q_feats] * slots)
                        feats2 = np.stack([c[0] for c in cand])
                        n1s = [nq] * slots
                        n2s = [c[1] for c in cand]
                    else:
                        feats1 = np.stack([c[0] for c in cand])
                        feats2 = np.stack([q_feats] * slots)
                        n1s = [c[1] for c in cand]
                        n2s = [nq] * slots
                    mask1 = np.stack([np.arange(b1) < n for n in n1s])
                    mask2 = np.stack([np.arange(b2) < n for n in n2s])
                    compiled = self.engine.decode_executable(
                        b1, b2, slots, (feats1, feats2, mask1, mask2))
                    probs = np.asarray(compiled(
                        self.engine.params, self.engine.batch_stats,
                        feats1, feats2, mask1, mask2))
                    for i, s in enumerate(chunk):
                        n1, n2 = n1s[i], n2s[i]
                        records.append({
                            # Canonical (sorted) pair id: the same pair
                            # names the same record whether it came from
                            # a query funnel or a bulk screen.
                            "pair_id": pair_id(
                                *sorted((chain_id, s["chain_id"]))),
                            "chain1": chain_id if q_first
                            else s["chain_id"],
                            "chain2": s["chain_id"] if q_first
                            else chain_id,
                            "query": chain_id,
                            "partner": s["chain_id"],
                            "n1": n1, "n2": n2, "bucket": [b1, b2],
                            "prefilter_score": s["score"],
                            "partition_id": s["partition_id"],
                            **pair_summary(probs[i, :n1, :n2],
                                           self.cfg.top_k),
                        })
                    decoded += len(chunk)
                    decode_batches += 1
                    _DECODED.inc(len(chunk))
                    _DECODE_BATCHES.inc()
        return QueryResult(
            query=chain_id,
            records=rank_records(records),
            prefilter_ranked=survivors,
            candidates=candidates,
            survivors=len(survivors),
            pairs_decoded=decoded,
            decode_batches=decode_batches,
            encodes_executed=encodes_executed,
            partial=partial,
            encode_seconds=encode_seconds,
            decode_seconds=time.perf_counter() - t0)
