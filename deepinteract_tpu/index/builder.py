"""Index builds: resumable exactly-once encodes, verify, and merge.

A build encodes a whole chain library once through the engine's AOT
encode executables and lands it as partitioned shards. The unit of work
is one PARTITION (a bucket-homogeneous slice of the library), and the
PR-6 :class:`~deepinteract_tpu.screening.manifest.ScreenManifest`
machinery is reused verbatim as the build ledger: shard write first,
then ``mark_done`` + atomic ``flush``, so a kill -9 anywhere re-encodes
at most the one partition whose shard landed but whose ledger entry did
not — every partition is COMPLETED exactly once across runs.

Resume re-verifies every ledger-complete shard against its integrity
sidecar before trusting it: a corrupt or missing shard is quarantined
and its ledger entry discarded, so a rebuild re-encodes ONLY the lost
partition (pinned in tests/test_index.py).

``verify`` and ``merge`` are the fsck-shaped companions: verify walks
every shard against the manifest; merge splices disjoint same-version
indexes into one (shards are re-verified, renumbered, and re-written
through the same atomic path — never byte-copied unaudited).
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepinteract_tpu.index import format as idx_format
from deepinteract_tpu.index.prefilter import pooled_embedding
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import artifacts
from deepinteract_tpu.screening.embcache import EmbeddingCache
from deepinteract_tpu.screening.library import ChainLibrary
from deepinteract_tpu.screening.manifest import ScreenManifest
from deepinteract_tpu.screening.runner import ScreenConfig, ScreenRunner

_PARTITIONS_BUILT = obs_metrics.counter(
    "di_index_partitions_built_total", "Index partitions encoded+landed")
_PARTITIONS_REBUILT = obs_metrics.counter(
    "di_index_partitions_rebuilt_total",
    "Ledger-complete partitions re-encoded after shard corruption")


@dataclasses.dataclass
class BuildResult:
    """One build run's outcome (counters cover THIS run; the manifest
    covers the whole index)."""

    index_dir: str
    partitions_total: int
    partitions_built: int
    partitions_resumed: int
    partitions_rebuilt: int
    chains: int
    encodes_executed: int
    encode_batches: int
    preempted: bool
    resumed: bool
    elapsed_s: float
    weights_signature: str
    library_signature: str

    def summary(self) -> Dict:
        return {
            "index_dir": self.index_dir,
            "partitions": self.partitions_total,
            "partitions_built": self.partitions_built,
            "partitions_resumed": self.partitions_resumed,
            "partitions_rebuilt": self.partitions_rebuilt,
            "chains": self.chains,
            "encodes_executed": self.encodes_executed,
            "encode_batches": self.encode_batches,
            "preempted": self.preempted,
            "resumed": self.resumed,
            "elapsed_s": round(self.elapsed_s, 3),
            "weights_signature": self.weights_signature,
            "library_signature": self.library_signature,
        }


def plan_partitions(engine, library: ChainLibrary,
                    partition_size: int
                    ) -> List[Tuple[str, int, List[str]]]:
    """Deterministic partition plan: chains grouped by engine bucket
    (library order preserved within a bucket), chunked to
    ``partition_size``, ids numbered per bucket — the same plan on every
    resume, which is what makes the ledger's partition ids stable."""
    if partition_size < 1:
        raise ValueError(f"partition_size must be >= 1, "
                         f"got {partition_size}")
    by_bucket: Dict[int, List[str]] = {}
    for cid in library.ids():
        by_bucket.setdefault(engine.chain_bucket(library[cid].n),
                             []).append(cid)
    plan = []
    for bucket in sorted(by_bucket):
        cids = by_bucket[bucket]
        for seq, lo in enumerate(range(0, len(cids), partition_size)):
            plan.append((idx_format.partition_id(bucket, seq), bucket,
                         cids[lo:lo + partition_size]))
    return plan


def _build_signature(engine, library: ChainLibrary,
                     partition_size: int) -> str:
    """What the build ledger is bound to: same identity fields as
    ``ScreenRunner._chain_key`` plus the partition plan shape."""
    return "|".join([
        "index-build", library.signature(), engine.weights_signature(),
        str(bool(engine.cfg.input_indep)),
        str(engine.model.cfg.gnn.compute_dtype),
        f"ps{int(partition_size)}"])


def build_index(engine, library: ChainLibrary, index_dir: str,
                partition_size: int = 64, encode_batch: int = 8,
                cache: Optional[EmbeddingCache] = None, guard=None,
                deadline=None, after_partition=None) -> BuildResult:
    """Encode ``library`` into a durable index at ``index_dir``.

    ``guard`` is a PR-1 PreemptionGuard polled at partition boundaries;
    a preempted build exits cleanly with the ledger durable and resumes
    exactly-once. ``after_partition(num_done)`` is a test hook."""
    t0 = time.perf_counter()
    plan = plan_partitions(engine, library, partition_size)
    signature = _build_signature(engine, library, partition_size)
    ledger, resumed = ScreenManifest.load_or_create(
        idx_format.ledger_path(index_dir), signature, len(plan))

    # Trust-but-verify resume: a ledger-complete partition whose shard
    # is gone or corrupt is quarantined + discarded, so ONLY it rebuilds.
    rebuilt = 0
    if resumed:
        for pid, _, _ in plan:
            if pid not in ledger.completed:
                continue
            path = idx_format.shard_path(index_dir, pid)
            try:
                idx_format.read_partition(
                    path, expect_signature=engine.weights_signature())
            except artifacts.ArtifactError as exc:
                artifacts.quarantine(path, idx_format.INDEX_SHARD_KIND,
                                     f"resume verification: {exc}")
                ledger.discard(pid)
                rebuilt += 1
                _PARTITIONS_REBUILT.inc()
        if rebuilt:
            ledger.flush()
    resumed_parts = len([pid for pid, _, _ in plan
                         if pid in ledger.completed])

    runner = ScreenRunner(
        engine, cache=cache,
        cfg=ScreenConfig(encode_batch=encode_batch,
                         decode_batch=encode_batch))
    built = 0
    encodes = 0
    enc_batches = 0
    preempted = False
    for pid, bucket, cids in plan:
        if pid in ledger.completed:
            continue
        if guard is not None and getattr(guard, "requested", False):
            preempted = True
            break
        emb, executed, _, batches = runner.ensure_embeddings(
            library, cids, deadline=deadline)
        encodes += executed
        enc_batches += batches
        feats = np.stack([emb[cid][0] for cid in cids])
        pooled = np.stack([pooled_embedding(emb[cid][0], emb[cid][1])
                           for cid in cids])
        lengths = [library[cid].n for cid in cids]
        path = idx_format.write_partition(
            index_dir, pid, bucket, cids, lengths, feats, pooled,
            engine.weights_signature())
        # Shard durable BEFORE the ledger entry: a kill between the two
        # re-encodes this one partition into an identical shard — never
        # a ledger entry pointing at nothing.
        ledger.mark_done(pid, {
            "partition_id": pid, "file": path, "bucket": bucket,
            "chains": list(cids), "lengths": [int(n) for n in lengths]})
        ledger.flush()
        built += 1
        _PARTITIONS_BUILT.inc()
        if after_partition is not None:
            after_partition(built)

    if ledger.done:
        _write_manifest_from_ledger(engine, library, index_dir,
                                    partition_size, plan, ledger)
    return BuildResult(
        index_dir=index_dir,
        partitions_total=len(plan),
        partitions_built=built,
        partitions_resumed=resumed_parts,
        partitions_rebuilt=rebuilt,
        chains=len(library),
        encodes_executed=encodes,
        encode_batches=enc_batches,
        preempted=preempted,
        resumed=resumed,
        elapsed_s=time.perf_counter() - t0,
        weights_signature=engine.weights_signature(),
        library_signature=library.signature())


def _write_manifest_from_ledger(engine, library, index_dir,
                                partition_size, plan, ledger) -> None:
    parts = []
    feat_dim = 0
    for pid, bucket, _ in plan:
        rec = ledger.completed[pid]
        rel = idx_format.shard_path("", pid).lstrip("/")
        parts.append({"partition_id": pid, "file": rel,
                      "bucket": int(bucket),
                      "chains": list(rec["chains"]),
                      "lengths": [int(n) for n in rec["lengths"]]})
    if plan:
        first = idx_format.read_partition(
            idx_format.shard_path(index_dir, plan[0][0]),
            expect_signature=engine.weights_signature())
        feat_dim = int(first["feats"].shape[-1])
    idx_format.write_manifest(index_dir, {
        "format_version": idx_format.INDEX_FORMAT_VERSION,
        "weights_signature": engine.weights_signature(),
        "library_signature": library.signature(),
        "input_indep": bool(engine.cfg.input_indep),
        "compute_dtype": str(engine.model.cfg.gnn.compute_dtype),
        "feat_dim": feat_dim,
        "partition_size": int(partition_size),
        "num_chains": len(library),
        "partitions": parts})


def verify_index(index_dir: str, quarantine: bool = False) -> Dict:
    """Walk every shard against the manifest + sidecars. Returns a
    report; never raises for per-shard damage (that is the report's
    job)."""
    report = {"index_dir": index_dir, "ok": False, "partitions": 0,
              "verified": 0, "corrupt": 0, "corrupt_paths": [],
              "chains": 0, "weights_signature": "",
              "library_signature": ""}
    manifest = idx_format.read_manifest(index_dir)
    report["partitions"] = len(manifest["partitions"])
    report["chains"] = int(manifest["num_chains"])
    report["weights_signature"] = manifest["weights_signature"]
    report["library_signature"] = manifest["library_signature"]
    for part in manifest["partitions"]:
        path = idx_format.shard_path(index_dir, part["partition_id"])
        try:
            data = idx_format.read_partition(
                path, expect_signature=manifest["weights_signature"])
            if data["chain_ids"] != list(part["chains"]):
                raise artifacts.CorruptArtifact(
                    path, "shard chain ids disagree with the manifest")
            report["verified"] += 1
        except artifacts.ArtifactError as exc:
            report["corrupt"] += 1
            report["corrupt_paths"].append(path)
            if quarantine:
                artifacts.quarantine(path, idx_format.INDEX_SHARD_KIND,
                                     str(exc))
    report["ok"] = report["corrupt"] == 0
    return report


def merge_indexes(sources: Sequence[str], out_dir: str) -> Dict:
    """Splice disjoint same-version indexes into one at ``out_dir``.

    Every source shard is re-verified and re-written through the atomic
    artifact path under a renumbered partition id. The merged
    ``library_signature`` is derived from the sorted source signatures
    (the raw chains are not on hand to re-derive a ChainLibrary one)."""
    if len(sources) < 2:
        raise ValueError("merge needs at least two source indexes")
    manifests = [(src, idx_format.read_manifest(src)) for src in sources]
    head = manifests[0][1]
    for src, m in manifests[1:]:
        for key in ("weights_signature", "input_indep", "compute_dtype",
                    "feat_dim"):
            if m[key] != head[key]:
                raise ValueError(
                    f"cannot merge {src}: {key} {m[key]!r} != "
                    f"{head[key]!r} (indexes must share the embedding "
                    "identity)")
    seen: Dict[str, str] = {}
    for src, m in manifests:
        for part in m["partitions"]:
            for cid in part["chains"]:
                if cid in seen:
                    raise ValueError(
                        f"cannot merge: chain {cid!r} appears in both "
                        f"{seen[cid]} and {src}")
                seen[cid] = src

    parts = []
    seq_by_bucket: Dict[int, int] = {}
    for src, m in manifests:
        for part in m["partitions"]:
            data = idx_format.read_partition(
                idx_format.shard_path(src, part["partition_id"]),
                expect_signature=head["weights_signature"])
            bucket = int(part["bucket"])
            seq = seq_by_bucket.get(bucket, 0)
            seq_by_bucket[bucket] = seq + 1
            pid = idx_format.partition_id(bucket, seq)
            idx_format.write_partition(
                out_dir, pid, bucket, data["chain_ids"],
                [int(n) for n in data["lengths"]], data["feats"],
                data["pooled"], head["weights_signature"])
            parts.append({
                "partition_id": pid,
                "file": idx_format.shard_path("", pid).lstrip("/"),
                "bucket": bucket, "chains": list(data["chain_ids"]),
                "lengths": [int(n) for n in data["lengths"]]})
    merged_sig = "merge-" + hashlib.sha256("|".join(
        sorted(m["library_signature"] for _, m in manifests)).encode()
    ).hexdigest()[:16]
    idx_format.write_manifest(out_dir, {
        "format_version": idx_format.INDEX_FORMAT_VERSION,
        "weights_signature": head["weights_signature"],
        "library_signature": merged_sig,
        "input_indep": head["input_indep"],
        "compute_dtype": head["compute_dtype"],
        "feat_dim": head["feat_dim"],
        "partition_size": int(head["partition_size"]),
        "num_chains": len(seen),
        "partitions": parts})
    return {"index_dir": out_dir, "ok": True, "sources": list(sources),
            "partitions": len(parts), "chains": len(seen),
            "weights_signature": head["weights_signature"],
            "library_signature": merged_sig}
