"""Embedding-space pre-filter: the mouth of the docking funnel.

Ranks every indexed chain against a query with one matrix-vector
product over cached pooled embeddings, so the expensive contact decoder
only ever sees the top-M survivors. The score is the cosine between
l2-normalized masked mean-pools of the encoder embeddings — a bilinear
form ``pool(q)^T pool(c)`` that is symmetric in its arguments, the same
transpose-invariance contract ``screening/scoring.py``'s
``pair_summary`` keeps for the full decode score (which chain is "1"
and which is "2" must never change a ranking).

Cost shape (the FlashAttention lesson applied at the storage tier):
the resident working set is ``[N, C]`` pooled vectors, the scan is one
GEMV, and only ``M << N`` chains pay the ``[bucket1 x bucket2]`` decode.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from deepinteract_tpu.obs import metrics as obs_metrics

_PREFILTERED = obs_metrics.counter(
    "di_index_prefilter_chains_total",
    "Library chains ranked by the embedding-space pre-filter")


def pooled_embedding(feats: np.ndarray, n: int) -> np.ndarray:
    """l2-normalized masked mean-pool of one chain's padded embeddings
    (``feats [bucket, C]``, true length ``n``). Padding rows are
    excluded so two chains differing only in bucket pad agree."""
    n = max(1, min(int(n), feats.shape[0]))
    vec = np.asarray(feats[:n], np.float32).mean(axis=0)
    norm = float(np.linalg.norm(vec))
    if norm > 0.0:
        vec = vec / norm
    return vec


def bilinear_scores(query_vec: np.ndarray,
                    pooled: np.ndarray) -> np.ndarray:
    """Cosine scores of a ``[k, C]`` pooled block against the query
    vector — symmetric (score(q, c) == score(c, q)) by construction."""
    return np.asarray(pooled, np.float32) @ np.asarray(query_vec,
                                                       np.float32)


def prefilter(index, query_vec: np.ndarray, top_m: int,
              partitions: Optional[Iterable[str]] = None,
              exclude: Tuple[str, ...] = (),
              ) -> Tuple[List[Dict], int]:
    """Rank the selected partitions' chains against ``query_vec``.

    Returns (survivors, candidates): the top-``top_m`` chains as
    ``{"chain_id", "score", "partition_id", "row", "bucket", "n"}``
    dicts in deterministic ``(-score, chain_id)`` order, and the total
    number of candidates scanned (``exclude`` drops the query itself
    when it is index-resident). ``top_m <= 0`` means uncapped — every
    candidate survives; the router's partition-scoped fan-out relies on
    this to gather a globally exact ranking from per-worker shards."""
    ranked: List[Dict] = []
    candidates = 0
    skip = set(exclude)
    for pid, chain_ids, lengths, pooled in index.iter_pooled(partitions):
        scores = bilinear_scores(query_vec, pooled)
        bucket = int(index.partition(pid)["bucket"])
        for row, cid in enumerate(chain_ids):
            if cid in skip:
                continue
            candidates += 1
            ranked.append({"chain_id": cid, "score": float(scores[row]),
                           "partition_id": pid, "row": row,
                           "bucket": bucket, "n": int(lengths[row])})
    ranked.sort(key=lambda r: (-r["score"], r["chain_id"]))
    _PREFILTERED.inc(candidates)
    if int(top_m) > 0:
        ranked = ranked[:int(top_m)]
    return ranked, candidates
