"""Overload-safe serving: admission control, deadlines, load shedding.

The scheduler's per-bucket FIFOs used to accept unboundedly: a traffic
spike queued arbitrarily deep, every accepted request eventually burned a
device dispatch (even after its client hung up), and the only
client-visible bound was the server's blanket ``request_timeout_s``.
Under sustained overload that is the worst possible policy — unbounded
p99 for everyone and zero feedback to clients about when to retry. This
module is the serving-plane counterpart of the PR-1 training
fault-tolerance layer:

* :class:`AdmissionController` — bounded per-bucket queues plus a global
  in-flight cap, enforced at submit time. Excess load is rejected
  *immediately* with a typed :class:`Overloaded` carrying a computed
  ``retry_after_s`` (queue backlog over the observed service rate), so
  clients back off instead of piling on.
* :class:`Deadline` — a monotonic-clock request deadline (client
  ``X-Request-Deadline-Ms`` header / ``deadline_s`` JSON field, default
  from ``--default_deadline_ms``). Checked at admission, again at batch
  assembly (an expired request is failed with :class:`DeadlineExceeded`
  *before* it occupies a padded batch slot), and bounded in
  ``predict()``'s wait — a request never hangs past its deadline.
* :class:`LoadShedder` — an adaptive degraded-mode switch driven by the
  same ``obs`` signals ``/metrics`` serves (admission utilization, queue
  depth, ``di_request_*`` p99, compile in-flight). While degraded the
  server answers ``POST /predict``/``POST /screen`` with 429 +
  ``Retry-After`` and ``/healthz`` reports ``overloaded`` — but
  ``/stats``/``/metrics`` stay live, because observability during an
  incident is the point. Hysteresis (separate enter/exit thresholds plus
  a minimum dwell) keeps it from flapping.

Client retry contract: 429 (``Overloaded`` / shedding) means *retry
after* ``Retry-After`` seconds — the work was never accepted; 504
(``DeadlineExceeded``) means the deadline passed — retrying with the
same deadline will likely fail again; 503 (draining /
:class:`ShuttingDown`) means *retry against another replica*.

Everything here is host-side stdlib guarded by per-object locks; no
device work, no new dependencies.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import defaultdict
from typing import Any, Callable, Dict, Hashable, Optional

from deepinteract_tpu.obs import metrics as obs_metrics

_ACCEPTED = obs_metrics.counter(
    "di_admission_accepted_total",
    "Requests admitted into the bounded serving queues")
_REJECTED = obs_metrics.counter(
    "di_admission_rejected_total",
    "Requests rejected at admission", labelnames=("reason",))
_DEADLINE_EXPIRED = obs_metrics.counter(
    "di_admission_deadline_expired_total",
    "Requests failed because their deadline passed", labelnames=("where",))
_SHED_DEGRADED = obs_metrics.gauge(
    "di_shed_degraded", "1 while the load shedder holds the server degraded")
_SHED_TRANSITIONS = obs_metrics.counter(
    "di_shed_transitions_total",
    "Load-shedder state changes", labelnames=("to",))
_SHED_REJECTED = obs_metrics.counter(
    "di_shed_rejected_total",
    "Requests answered 429 while the shedder held the server degraded")


# ---------------------------------------------------------------------------
# Typed errors (the serving plane's failure vocabulary — servers map these
# onto HTTP statuses; engine callers catch them by type)
# ---------------------------------------------------------------------------


class Overloaded(RuntimeError):
    """Rejected at admission: queues are full (or shedding is active).

    ``retry_after_s`` is the server's backlog-drain estimate — the
    ``Retry-After`` header value, so a well-behaved client retries when
    capacity plausibly exists instead of immediately."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = max(0.0, float(retry_after_s))


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it could be (fully) served.

    ``trace`` optionally carries the request's PR-7 decomposition (the
    phases it DID complete — always with ``device_ms == 0`` when the
    request was dropped before dispatch)."""

    def __init__(self, message: str, trace: Optional[Dict] = None):
        super().__init__(message)
        self.trace = trace


class ShuttingDown(RuntimeError):
    """Accepted work failed because the server is going away (drain
    timeout): the client gets an answer instead of hanging on a future
    whose worker is gone. Retry against another replica."""


class BatchExecutionError(RuntimeError):
    """A coalesced batch failed at assembly or device dispatch. Fails
    every future in its group; the scheduler worker survives and the
    engine keeps serving subsequent batches."""

    def __init__(self, message: str, stage: str = "dispatch"):
        super().__init__(message)
        self.stage = stage


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Deadline:
    """A monotonic-clock expiry. Constructed ONCE at the server edge from
    the client's budget; everything downstream (admission, the scheduler
    sweep, ``predict``'s wait bound) compares against the same instant,
    so clock skew between layers cannot exist."""

    expires_at: float  # time.monotonic() instant
    budget_s: float    # original budget (trace/reporting only)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        seconds = float(seconds)
        return cls(expires_at=time.monotonic() + seconds, budget_s=seconds)

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.expires_at

    def remaining_s(self) -> float:
        return max(0.0, self.expires_at - time.monotonic())


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------


def _estimate_retry_after(inflight: int, rate_rps: float) -> float:
    """Backlog over observed service rate, clamped to [0.1, 60] s. With
    no rate evidence yet (cold start, first compile still running) answer
    1 s — retrying into a compile stampede is the failure mode this
    avoids. Pure function of its arguments so callers holding the
    controller lock can use it on their consistent snapshot."""
    if rate_rps <= 0.0:
        return 1.0
    return min(60.0, max(0.1, inflight / rate_rps))


class AdmissionController:
    """Bounded per-bucket queues + global in-flight cap, with a service-
    rate estimate for ``Retry-After``.

    The scheduler reports every request transition: ``try_admit`` at
    submit (raises :class:`Overloaded` over either bound), ``on_dequeue``
    when entries leave a bucket queue (into a flush group, an expired
    drop, or a drain sweep), ``on_done`` when their futures resolve, and
    ``observe_batch`` after each completed flush (feeds the EWMA service
    rate). In-flight = admitted and not yet answered, so it covers both
    queued and executing work — the thing a capacity bound must cover.
    """

    def __init__(self, max_queue_depth: int = 64, max_inflight: int = 256):
        if max_queue_depth < 1 or max_inflight < 1:
            raise ValueError(
                "max_queue_depth and max_inflight must be >= 1, got "
                f"{max_queue_depth}/{max_inflight}")
        self.max_queue_depth = int(max_queue_depth)
        self.max_inflight = int(max_inflight)
        self._lock = threading.Lock()
        self._queued: Dict[Hashable, int] = defaultdict(int)
        self._inflight = 0
        self._admitted = 0
        self._rejected_queue = 0
        self._rejected_inflight = 0
        # EWMA requests/second over completed flushes; 0 = no evidence yet.
        self._rate = 0.0

    # -- lifecycle hooks (called by the scheduler) -------------------------

    def try_admit(self, bucket: Hashable) -> None:
        """Admit one request into ``bucket``'s queue or raise
        :class:`Overloaded` with a computed ``retry_after_s``."""
        with self._lock:
            if self._inflight >= self.max_inflight:
                self._rejected_inflight += 1
                retry = _estimate_retry_after(self._inflight, self._rate)
                label = "inflight_full"
                reason = (f"{self._inflight} requests in flight >= "
                          f"max_inflight {self.max_inflight}")
            elif self._queued[bucket] >= self.max_queue_depth:
                self._rejected_queue += 1
                retry = _estimate_retry_after(self._inflight, self._rate)
                label = "queue_full"
                reason = (f"bucket {bucket!r} queue depth "
                          f"{self._queued[bucket]} >= max_queue_depth "
                          f"{self.max_queue_depth}")
            else:
                self._queued[bucket] += 1
                self._inflight += 1
                self._admitted += 1
                _ACCEPTED.inc()
                return
        _REJECTED.inc(reason=label)
        raise Overloaded(f"overloaded: {reason}", retry_after_s=retry)

    def on_dequeue(self, bucket: Hashable, n: int = 1) -> None:
        """``n`` entries left ``bucket``'s queue (flush group / expired
        drop / drain sweep); they remain in flight until ``on_done``."""
        with self._lock:
            left = self._queued[bucket] - int(n)
            if left > 0:
                self._queued[bucket] = left
            else:
                self._queued.pop(bucket, None)

    def on_done(self, n: int = 1) -> None:
        """``n`` admitted requests got their answer (result OR typed
        failure) — capacity is free again."""
        with self._lock:
            self._inflight = max(0, self._inflight - int(n))

    def cancel(self, bucket: Hashable) -> None:
        """Undo one ``try_admit`` that never actually enqueued (e.g. the
        scheduler turned out to be closed)."""
        self.on_dequeue(bucket, 1)
        self.on_done(1)

    def observe_batch(self, n_requests: int, seconds: float) -> None:
        """Feed one completed flush into the service-rate EWMA."""
        if n_requests <= 0 or seconds <= 0:
            return
        rate = n_requests / seconds
        with self._lock:
            self._rate = rate if self._rate == 0.0 else (
                0.7 * self._rate + 0.3 * rate)

    # -- retry-after -------------------------------------------------------

    def retry_after_s(self) -> float:
        with self._lock:
            return _estimate_retry_after(self._inflight, self._rate)

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "max_queue_depth": self.max_queue_depth,
                "max_inflight": self.max_inflight,
                "inflight": self._inflight,
                "queued": sum(self._queued.values()),
                "queued_by_bucket": {str(k): v
                                     for k, v in self._queued.items()},
                "admitted": self._admitted,
                "rejected_queue_full": self._rejected_queue,
                "rejected_inflight_full": self._rejected_inflight,
                "service_rate_rps": round(self._rate, 3),
                "retry_after_s": round(
                    _estimate_retry_after(self._inflight, self._rate), 3),
            }


# ---------------------------------------------------------------------------
# Load shedding
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShedderConfig:
    """Degraded-mode policy (CLI surface: ``cli/serve.py``).

    Utilization = admitted-in-flight / ``max_inflight`` — the leading
    indicator (it saturates before latency does). Two more triggers read
    the other overload signals: ``enter_queue_depth`` (total queued
    across buckets; 0 disables) and the compile-stall rule — a cold
    compile in flight WHILE utilization is already past the exit
    threshold degrades immediately, because one long compile stalls
    every flush behind the exec lock and queueing behind it only makes
    the spike worse. The p99 trigger reads the same registry histogram
    ``/metrics`` serves; 0 disables it (the histogram is cumulative-
    since-start, so it is a confirming signal, not the fast path).
    Enter on ANY trigger; exit only when EVERY signal is back under its
    exit threshold AND the minimum dwell has passed — classic
    hysteresis so a boundary load cannot flap the server between
    modes."""

    enabled: bool = True
    enter_utilization: float = 0.9
    exit_utilization: float = 0.5
    enter_queue_depth: int = 0  # 0 disables the queue-depth trigger
    shed_on_compile_stall: bool = True
    enter_p99_ms: float = 0.0  # 0 disables the latency trigger
    exit_p99_ms: float = 0.0
    min_degraded_s: float = 2.0

    def __post_init__(self):
        if not 0.0 < self.exit_utilization <= self.enter_utilization:
            raise ValueError(
                "need 0 < exit_utilization <= enter_utilization, got "
                f"{self.exit_utilization}/{self.enter_utilization}")


class LoadShedder:
    """Two-state (healthy/degraded) switch over live overload signals.

    ``signals_fn`` returns the current ``{"utilization", "queue_depth",
    "p99_ms", "compile_inflight"}`` snapshot (the server wires it to the
    admission controller + the ``obs`` registry). ``evaluate()`` is
    called on every POST and every ``/healthz`` — it is a handful of
    float compares, so polling it per-request costs nothing and keeps
    the mode current without a background thread to manage."""

    def __init__(self, cfg: ShedderConfig,
                 signals_fn: Callable[[], Dict[str, float]],
                 now_fn: Callable[[], float] = time.monotonic):
        self.cfg = cfg
        self._signals_fn = signals_fn
        self._now = now_fn
        self._lock = threading.Lock()
        self._degraded = False
        self._since = self._now()
        self._transitions = 0
        self._last_signals: Dict[str, float] = {}
        self._last_reason = ""

    # -- state machine -----------------------------------------------------

    def _enter_reason(self, sig: Dict[str, float]) -> str:
        cfg = self.cfg
        util = float(sig.get("utilization", 0.0))
        if util >= cfg.enter_utilization:
            return (f"utilization {util:.2f} >= {cfg.enter_utilization:.2f}")
        queued = float(sig.get("queue_depth", 0.0))
        if cfg.enter_queue_depth > 0 and queued >= cfg.enter_queue_depth:
            return (f"queue depth {queued:.0f} >= {cfg.enter_queue_depth}")
        compiling = float(sig.get("compile_inflight", 0.0))
        if (cfg.shed_on_compile_stall and compiling > 0
                and util >= cfg.exit_utilization):
            return (f"cold compile in flight at utilization {util:.2f} "
                    "(flushes stalled behind the exec lock)")
        p99 = float(sig.get("p99_ms", 0.0))
        if cfg.enter_p99_ms > 0 and p99 >= cfg.enter_p99_ms:
            return f"p99 {p99:.0f}ms >= {cfg.enter_p99_ms:.0f}ms"
        return ""

    def _can_exit(self, sig: Dict[str, float]) -> bool:
        cfg = self.cfg
        if float(sig.get("utilization", 0.0)) > cfg.exit_utilization:
            return False
        if (cfg.enter_queue_depth > 0
                and float(sig.get("queue_depth", 0.0))
                >= cfg.enter_queue_depth):
            return False
        # No compile-inflight exit clause: the utilization check above
        # already holds recovery until load is genuinely low, and pinning
        # degraded on ANY compile would strand a warmup-compiling but
        # idle server in degraded mode.
        return not (cfg.exit_p99_ms > 0
                    and float(sig.get("p99_ms", 0.0)) > cfg.exit_p99_ms)

    def evaluate(self) -> bool:
        """Refresh state from the live signals; True while degraded."""
        if not self.cfg.enabled:
            return False
        sig = self._signals_fn()
        now = self._now()
        with self._lock:
            self._last_signals = dict(sig)
            if not self._degraded:
                reason = self._enter_reason(sig)
                if reason:
                    self._degraded = True
                    self._since = now
                    self._transitions += 1
                    self._last_reason = reason
                    _SHED_TRANSITIONS.inc(to="degraded")
                    _SHED_DEGRADED.set(1.0)
            else:
                dwell = now - self._since
                if dwell >= self.cfg.min_degraded_s and self._can_exit(sig):
                    self._degraded = False
                    self._since = now
                    self._transitions += 1
                    self._last_reason = "recovered"
                    _SHED_TRANSITIONS.inc(to="healthy")
                    _SHED_DEGRADED.set(0.0)
            return self._degraded

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def count_rejection(self) -> None:
        """One 429 answered while degraded (kept here so every shedder
        consumer shares the ``di_shed_rejected_total`` series)."""
        _SHED_REJECTED.inc()

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.cfg.enabled,
                "degraded": self._degraded,
                "since_s": round(self._now() - self._since, 3),
                "transitions": self._transitions,
                "reason": self._last_reason,
                "signals": dict(self._last_signals),
                "enter_utilization": self.cfg.enter_utilization,
                "exit_utilization": self.cfg.exit_utilization,
                "min_degraded_s": self.cfg.min_degraded_s,
            }


def expired_counter(where: str) -> None:
    """Count one deadline expiry at ``where`` (admission / queue /
    screen) — one helper so every layer shares the same series."""
    _DEADLINE_EXPIRED.inc(where=where)


def overload_signals() -> Dict[str, float]:
    """Process-local overload evidence in one readout — the capacity
    controller's (``serving/autoscaler.py``) admission-layer inputs.
    ``admission_rejected`` / ``shed_rejected`` are CUMULATIVE counts
    (pollers diff between reads); ``shed_degraded`` is the live 0/1
    shedder state."""
    rejected = sum(value for _, _, value in _REJECTED.samples())
    return {
        "admission_rejected": float(rejected),
        "shed_rejected": _SHED_REJECTED.value(),
        "shed_degraded": _SHED_DEGRADED.value(),
    }
