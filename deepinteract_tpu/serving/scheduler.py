"""Micro-batching scheduler: coalesce concurrent requests per shape bucket.

The engine's compiled executables are keyed on padded bucket shapes, so
only same-bucket requests can share a device dispatch. This scheduler
holds a per-bucket pending queue and flushes a bucket's group when either

* it reaches ``max_batch`` requests (a full batch is ready now), or
* its oldest request has waited ``max_delay_ms`` (latency bound: a lone
  request never waits longer than the delay budget for company).

All flushes run on ONE worker thread, which serializes device dispatch —
correct for a single-accelerator process (concurrent dispatches would just
queue inside the runtime) and keeps the engine's executable cache free of
execution races. HTTP handler threads block on the returned futures.

The queue discipline is per-bucket FIFO with oldest-deadline-first
selection across buckets, so a hot bucket cannot starve a cold one beyond
the delay budget.

Overload discipline (serving/admission.py): when an
:class:`~deepinteract_tpu.serving.admission.AdmissionController` is
attached, ``submit`` enforces its bounded per-bucket queues and global
in-flight cap (typed ``Overloaded`` rejection at submit time, never a
silent unbounded queue), and per-request deadlines are swept at batch
assembly — an expired request is failed with ``DeadlineExceeded``
*before* it occupies a padded batch slot or a device dispatch. A flush
failure (assembly or dispatch) fails only its own group's futures and is
counted on ``di_serving_batch_failures_total``; the worker thread
survives by construction, so one poisoned batch cannot wedge the engine.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict, deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.serving.admission import (
    AdmissionController,
    Deadline,
    DeadlineExceeded,
    ShuttingDown,
    expired_counter,
)

logger = logging.getLogger(__name__)

_FLUSHES = obs_metrics.counter(
    "di_serving_flushes_total", "Coalesced groups handed to the flush fn")
_GROUP_SIZE = obs_metrics.histogram(
    "di_serving_coalesced_group_size", "Requests per coalesced flush",
    buckets=(1, 2, 4, 8, 16, 32, 64))
_BATCH_FAILURES = obs_metrics.counter(
    "di_serving_batch_failures_total",
    "Coalesced flushes that failed their whole group (worker survived)")


class SchedulerClosed(RuntimeError):
    """submit() after drain(): the serving process is shutting down."""


class MicroBatchScheduler:
    """Groups pending requests by bucket key and flushes on ``max_batch``
    or ``max_delay_ms``.

    ``flush_fn(key, payloads) -> results`` executes one coalesced batch
    and must return one result per payload (in order); it runs on the
    worker thread. An exception from ``flush_fn`` fails every future in
    the group (the batch shares one dispatch, so there is no per-item
    failure to attribute) — and ONLY that group: the worker loop is
    exception-proof and keeps serving subsequent groups.

    ``admission`` (optional) bounds the queues; ``on_expired(payload,
    deadline) -> Exception`` (optional) lets the owner build the typed
    failure for a deadline-swept entry (the engine attaches the request's
    trace decomposition there)."""

    def __init__(
        self,
        flush_fn: Callable[[Hashable, List[Any]], List[Any]],
        max_batch: int = 8,
        max_delay_ms: float = 5.0,
        admission: Optional[AdmissionController] = None,
        on_expired: Optional[Callable[[Any, Deadline], Exception]] = None,
        flush_quantum: int = 1,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._flush_fn = flush_fn
        self.max_batch = int(max_batch)
        # A group this size is already a "full" device dispatch even
        # below max_batch — a mesh engine sets it to the data-axis
        # device count, whose batch slots lift to that floor anyway, so
        # waiting out max_delay_ms past it buys padding, not coalescing.
        self.flush_quantum = max(1, min(int(flush_quantum), self.max_batch))
        self.max_delay_s = max(0.0, float(max_delay_ms)) / 1e3
        self.admission = admission
        self._on_expired = on_expired
        self._cv = threading.Condition()
        # key -> deque[(payload, future, enqueue_time, deadline|None)]
        self._pending: Dict[Hashable, deque] = defaultdict(deque)
        self._closed = False
        self._flushes = 0
        self._coalesced: Dict[int, int] = defaultdict(int)  # batch size -> count
        self._submitted = 0
        self._expired = 0
        self._batch_failures = 0
        self._worker = threading.Thread(
            target=self._loop, name="microbatch-flush", daemon=True
        )
        self._worker.start()

    # -- producer side ----------------------------------------------------

    def submit(self, key: Hashable, payload: Any,
               deadline: Optional[Deadline] = None) -> Future:
        """Enqueue one request. Raises :class:`Overloaded` when the
        admission controller's bounds are hit (typed, with
        ``retry_after_s``) and :class:`SchedulerClosed` after drain."""
        fut: Future = Future()
        if self.admission is not None:
            # Admission BEFORE the queue lock: the controller has its own
            # lock and never takes _cv, so the two never nest.
            self.admission.try_admit(key)
        try:
            with self._cv:
                if self._closed:
                    raise SchedulerClosed(
                        "scheduler is draining; no new requests")
                self._pending[key].append(
                    (payload, fut, time.monotonic(), deadline))
                self._submitted += 1
                self._cv.notify()
        except BaseException:
            if self.admission is not None:
                self.admission.cancel(key)
            raise
        return fut

    def drain(self, timeout: float = 60.0) -> bool:
        """Stop accepting requests, flush everything pending, and join the
        worker. Idempotent; safe to call from any thread (SIGTERM drain).

        Returns False when the worker is still flushing at the timeout —
        but never silently: every request still QUEUED at that point is
        failed with a typed :class:`ShuttingDown` (clients get an answer
        instead of hanging on ``.result()`` after the process exits), and
        the stranded-work situation is logged loudly. The one group the
        worker is actively flushing keeps its futures pending — failing
        them would race a flush that may still complete."""
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._worker.join(timeout=timeout)
        if self._worker.is_alive():
            with self._cv:
                leftovers = [(key, entry)
                             for key, q in self._pending.items()
                             for entry in q]
                self._pending.clear()
            for key, (payload, fut, _, _) in leftovers:
                if not fut.cancelled():
                    fut.set_exception(ShuttingDown(
                        "server shutting down before this request could "
                        "be served; retry against another replica"))
                if self.admission is not None:
                    self.admission.on_dequeue(key, 1)
                    self.admission.on_done(1)
            logger.error(
                "drain timed out after %.0fs with %d queued request(s) "
                "failed ShuttingDown (plus any group still in flight) — "
                "exiting now drops accepted work",
                timeout, len(leftovers))
            return False
        return True

    # -- worker side ------------------------------------------------------

    def _take_ready_group(self) -> Tuple[List, Optional[Hashable], Any]:
        """Sweep expired-deadline entries out of every queue, then pop
        the group that should flush now. Returns ``(expired_entries,
        key, group)`` or ``(expired, None, wait_seconds)`` when nothing
        is ready. Expired entries never enter a group — they are failed
        by the caller BEFORE the batch they would have padded is
        assembled. Ready-bucket choice and the wake-up time are tracked
        SEPARATELY: a not-yet-ready bucket's earlier deadline must
        influence when to wake, but never which ready bucket flushes
        first (conflating them let a pending bucket shadow an
        older-deadline ready one). The Condition's lock is an RLock, so
        the explicit ``with`` below is a no-cost re-entry under _loop's
        hold — and makes the guarding verifiable instead of asserted."""
        now = time.monotonic()
        expired: List[Tuple[Hashable, Tuple]] = []
        ready_key = None
        ready_deadline = None
        wake_deadline = None
        with self._cv:
            for key in list(self._pending):
                q = self._pending[key]
                if any(e[3] is not None and now >= e[3].expires_at
                       for e in q):
                    kept = deque()
                    for entry in q:
                        dl = entry[3]
                        if dl is not None and now >= dl.expires_at:
                            expired.append((key, entry))
                        else:
                            kept.append(entry)
                    self._pending[key] = q = kept
                if not q:
                    del self._pending[key]
                    continue
                deadline = q[0][2] + self.max_delay_s
                if (len(q) >= self.max_batch or now >= deadline
                        or (self.flush_quantum > 1
                            and len(q) >= self.flush_quantum)
                        or self._closed):
                    # Oldest-deadline-first across READY buckets.
                    if ready_key is None or deadline < ready_deadline:
                        ready_key, ready_deadline = key, deadline
                elif wake_deadline is None or deadline < wake_deadline:
                    wake_deadline = deadline
                # A queued request's own deadline must also bound the
                # sleep: its expiry sweep (and typed failure) should
                # happen near the deadline, not at the next flush-delay
                # wake-up.
                for entry in q:
                    dl = entry[3]
                    if dl is not None and (wake_deadline is None
                                           or dl.expires_at < wake_deadline):
                        wake_deadline = dl.expires_at
            if ready_key is not None:
                q = self._pending[ready_key]
                group = [q.popleft()
                         for _ in range(min(len(q), self.max_batch))]
                if not q:
                    del self._pending[ready_key]
                return expired, ready_key, group
        wait = None if wake_deadline is None else max(0.0, wake_deadline - now)
        return expired, None, wait

    def _fail_expired(self, entries: List[Tuple[Hashable, Tuple]]) -> None:
        """Outside the lock: answer every deadline-swept entry with a
        typed failure (the owner's on_expired hook may attach the
        request's trace) and release its admission slot. Every step is
        per-entry exception-guarded — this runs on the ONE worker
        thread, and a hook surprise or a future state race must cost at
        most that entry, never the worker (the same survival contract
        the flush catch-all gives batches)."""
        for key, (payload, fut, t_enq, dl) in entries:
            with self._cv:
                self._expired += 1
            expired_counter("queue")
            exc: Exception
            try:
                if self._on_expired is not None:
                    exc = self._on_expired(payload, dl)
                else:
                    exc = DeadlineExceeded(
                        f"deadline expired after {dl.budget_s * 1e3:.0f}ms "
                        "while queued; the request was dropped before batch "
                        "assembly")
            except BaseException:  # noqa: BLE001 - worker must survive
                logger.exception("on_expired hook failed; failing the "
                                 "future with a plain DeadlineExceeded")
                exc = DeadlineExceeded(
                    f"deadline expired after {dl.budget_s * 1e3:.0f}ms "
                    "while queued")
            try:
                if not fut.cancelled():
                    fut.set_exception(exc)
            except BaseException:  # noqa: BLE001 - future state race
                logger.exception("failing an expired future raised")
            if self.admission is not None:
                self.admission.on_dequeue(key, 1)
                self.admission.on_done(1)

    def _loop(self) -> None:
        while True:
            with self._cv:
                expired, key, group_or_wait = self._take_ready_group()
                if not expired and key is None:
                    if self._closed and not self._pending:
                        return
                    self._cv.wait(timeout=group_or_wait)
                    continue
            if expired:
                self._fail_expired(expired)
            if key is None:
                continue
            group = group_or_wait
            if self.admission is not None:
                self.admission.on_dequeue(key, len(group))
            payloads = [p for p, _, _, _ in group]
            t0 = time.perf_counter()
            try:
                results = self._flush_fn(key, payloads)
                if len(results) != len(payloads):
                    raise RuntimeError(
                        f"flush_fn returned {len(results)} results for "
                        f"{len(payloads)} payloads"
                    )
            except BaseException as exc:  # noqa: BLE001 - fanned out to futures
                # The group fails; the WORKER survives. Before this
                # catch-all counted failures, an exception escaping the
                # future fan-out below could kill the thread silently and
                # wedge every subsequent request behind a dead worker.
                with self._cv:
                    self._batch_failures += 1
                _BATCH_FAILURES.inc()
                logger.exception(
                    "flush of %d request(s) for bucket %r failed; failing "
                    "the group's futures, worker continues", len(group), key)
                for _, fut, _, _ in group:
                    try:
                        if not fut.cancelled():
                            fut.set_exception(exc)
                    except BaseException:  # noqa: BLE001 - state race
                        logger.exception("failing a group future raised")
                if self.admission is not None:
                    self.admission.on_done(len(group))
                continue
            finally:
                with self._cv:
                    self._flushes += 1
                    self._coalesced[len(group)] += 1
                _FLUSHES.inc()
                _GROUP_SIZE.observe(len(group))
            try:
                for (_, fut, _, _), result in zip(group, results):
                    if not fut.cancelled():
                        fut.set_result(result)
            except BaseException:  # noqa: BLE001 - worker must survive
                logger.exception("result fan-out failed for bucket %r", key)
            if self.admission is not None:
                self.admission.observe_batch(
                    len(group), time.perf_counter() - t0)
                self.admission.on_done(len(group))

    # -- observability ----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            depth = {str(k): len(q) for k, q in self._pending.items() if q}
            return {
                "queue_depth": sum(len(q) for q in self._pending.values()),
                "queue_depth_by_bucket": depth,
                "submitted": self._submitted,
                "flushes": self._flushes,
                "batch_size_histogram": dict(sorted(self._coalesced.items())),
                "max_batch": self.max_batch,
                "max_delay_ms": self.max_delay_s * 1e3,
                "draining": self._closed,
                "deadline_expired": self._expired,
                "batch_failures": self._batch_failures,
            }
