"""Micro-batching scheduler: coalesce concurrent requests per shape bucket.

The engine's compiled executables are keyed on padded bucket shapes, so
only same-bucket requests can share a device dispatch. This scheduler
holds a per-bucket pending queue and flushes a bucket's group when either

* it reaches ``max_batch`` requests (a full batch is ready now), or
* its oldest request has waited ``max_delay_ms`` (latency bound: a lone
  request never waits longer than the delay budget for company).

All flushes run on ONE worker thread, which serializes device dispatch —
correct for a single-accelerator process (concurrent dispatches would just
queue inside the runtime) and keeps the engine's executable cache free of
execution races. HTTP handler threads block on the returned futures.

The queue discipline is per-bucket FIFO with oldest-deadline-first
selection across buckets, so a hot bucket cannot starve a cold one beyond
the delay budget.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict, deque
from concurrent.futures import Future
from typing import Any, Callable, Dict, Hashable, List, Tuple

from deepinteract_tpu.obs import metrics as obs_metrics

logger = logging.getLogger(__name__)

_FLUSHES = obs_metrics.counter(
    "di_serving_flushes_total", "Coalesced groups handed to the flush fn")
_GROUP_SIZE = obs_metrics.histogram(
    "di_serving_coalesced_group_size", "Requests per coalesced flush",
    buckets=(1, 2, 4, 8, 16, 32, 64))


class SchedulerClosed(RuntimeError):
    """submit() after drain(): the serving process is shutting down."""


class MicroBatchScheduler:
    """Groups pending requests by bucket key and flushes on ``max_batch``
    or ``max_delay_ms``.

    ``flush_fn(key, payloads) -> results`` executes one coalesced batch
    and must return one result per payload (in order); it runs on the
    worker thread. An exception from ``flush_fn`` fails every future in
    the group (the batch shares one dispatch, so there is no per-item
    failure to attribute).
    """

    def __init__(
        self,
        flush_fn: Callable[[Hashable, List[Any]], List[Any]],
        max_batch: int = 8,
        max_delay_ms: float = 5.0,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._flush_fn = flush_fn
        self.max_batch = int(max_batch)
        self.max_delay_s = max(0.0, float(max_delay_ms)) / 1e3
        self._cv = threading.Condition()
        # key -> deque[(payload, future, enqueue_time)]
        self._pending: Dict[Hashable, deque] = defaultdict(deque)
        self._closed = False
        self._flushes = 0
        self._coalesced: Dict[int, int] = defaultdict(int)  # batch size -> count
        self._submitted = 0
        self._worker = threading.Thread(
            target=self._loop, name="microbatch-flush", daemon=True
        )
        self._worker.start()

    # -- producer side ----------------------------------------------------

    def submit(self, key: Hashable, payload: Any) -> Future:
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise SchedulerClosed("scheduler is draining; no new requests")
            self._pending[key].append((payload, fut, time.monotonic()))
            self._submitted += 1
            self._cv.notify()
        return fut

    def drain(self, timeout: float = 60.0) -> bool:
        """Stop accepting requests, flush everything pending, and join the
        worker. Idempotent; safe to call from any thread (SIGTERM drain).

        Returns False (and logs loudly) when the worker is still flushing
        at the timeout — the caller is about to exit with accepted work
        in flight (e.g. several cold-bucket compiles queued behind a
        SIGTERM), which must not pass silently as a clean drain."""
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._worker.join(timeout=timeout)
        if self._worker.is_alive():
            logger.error(
                "drain timed out after %.0fs with %d request(s) still "
                "pending — exiting now would drop accepted work",
                timeout, self.stats()["queue_depth"])
            return False
        return True

    # -- worker side ------------------------------------------------------

    def _take_ready_group(self) -> Tuple[Hashable, List]:
        """Under the lock: pop the group that should flush now, or
        (None, wait_seconds) if nothing is ready yet. Ready-bucket choice
        and the wake-up time are tracked SEPARATELY: a not-yet-ready
        bucket's earlier deadline must influence when to wake, but never
        which ready bucket flushes first (conflating them let a pending
        bucket shadow an older-deadline ready one)."""
        now = time.monotonic()
        ready_key = None
        ready_deadline = None
        wake_deadline = None
        for key, q in self._pending.items():
            if not q:
                continue
            deadline = q[0][2] + self.max_delay_s
            # di: allow[lock-discipline] caller holds _cv (see _loop/docstring)
            if len(q) >= self.max_batch or now >= deadline or self._closed:
                # Oldest-deadline-first across READY buckets.
                if ready_key is None or deadline < ready_deadline:
                    ready_key, ready_deadline = key, deadline
            elif wake_deadline is None or deadline < wake_deadline:
                wake_deadline = deadline
        if ready_key is not None:
            q = self._pending[ready_key]
            group = [q.popleft() for _ in range(min(len(q), self.max_batch))]
            if not q:
                del self._pending[ready_key]
            return ready_key, group
        wait = None if wake_deadline is None else max(0.0, wake_deadline - now)
        return None, wait

    def _loop(self) -> None:
        while True:
            with self._cv:
                key, group_or_wait = self._take_ready_group()
                if key is None:
                    if self._closed and not self._pending:
                        return
                    self._cv.wait(timeout=group_or_wait)
                    continue
            group = group_or_wait
            payloads = [p for p, _, _ in group]
            try:
                results = self._flush_fn(key, payloads)
                if len(results) != len(payloads):
                    raise RuntimeError(
                        f"flush_fn returned {len(results)} results for "
                        f"{len(payloads)} payloads"
                    )
            except BaseException as exc:  # noqa: BLE001 - fanned out to futures
                for _, fut, _ in group:
                    if not fut.cancelled():
                        fut.set_exception(exc)
                continue
            finally:
                with self._cv:
                    self._flushes += 1
                    self._coalesced[len(group)] += 1
                _FLUSHES.inc()
                _GROUP_SIZE.observe(len(group))
            for (_, fut, _), result in zip(group, results):
                if not fut.cancelled():
                    fut.set_result(result)

    # -- observability ----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._cv:
            depth = {str(k): len(q) for k, q in self._pending.items() if q}
            return {
                "queue_depth": sum(len(q) for q in self._pending.values()),
                "queue_depth_by_bucket": depth,
                "submitted": self._submitted,
                "flushes": self._flushes,
                "batch_size_histogram": dict(sorted(self._coalesced.items())),
                "max_batch": self.max_batch,
                "max_delay_ms": self.max_delay_s * 1e3,
                "draining": self._closed,
            }
