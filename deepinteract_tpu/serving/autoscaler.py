"""Elastic capacity control: turn the fleet's overload signals into
worker-count policy.

PR 13 built the *mechanisms* (supervisor, router, warm rollover) and
PR 11 the *signals* (admission rejections, shed state, request
latency); this controller closes the loop. Every ``interval_s`` it
reads one consolidated signal snapshot —

* **queue depth** — mean in-flight per routable worker, from the
  ``inflight`` field the supervisor's health probes cache in each
  worker snapshot;
* **p99 latency** — the router-side ``di_router_request_seconds``
  histogram (:meth:`FleetRouter.request_p99_ms`), failovers included;
* **shed / admission pressure** — :func:`admission.overload_signals`
  deltas plus any worker whose health reports degraded/shedding;

— and decides **up**, **down**, or **hold**:

* *Hysteresis*: a breach must persist for ``breach_polls`` consecutive
  polls before any action — one slow request never spawns a worker, one
  idle poll never drains one.
* *Cooldown*: after any action the controller holds for ``cooldown_s``
  regardless of signals, so a scale-up's own warm-up window (when
  latency is still settling) cannot trigger the next action. Flapping
  is structurally impossible: action requires breach_polls consecutive
  breaches of the SAME direction *and* an expired cooldown.
* *Scale-up* pre-warms through the rollover machinery: the new worker
  is adopted into the routing table only after it reports warm
  (``status: ok`` + the router's required warm-bucket prefixes), so a
  cold worker never eats live traffic.
* *Scale-down* releases the youngest worker from the routing table
  FIRST, then SIGTERM-drains it through its own drain path — in-flight
  requests finish or fail over; nothing is dropped.
* *Preemption* is the supervisor's own first-class capacity event
  (``WorkerSupervisor.preempt_worker``): an expected loss with no
  circuit penalty and an immediate replacement. The autoscaler does
  not react to it — capacity self-heals one layer below.

Chaos: the ``autoscale.decision`` fault site raises at the moment a
decision would commit; the tick swallows it, counts it
(``di_autoscale_decisions_total{decision="error"}``), and leaves the
fleet unchanged — a broken controller must degrade to "no policy",
never to "random policy".

The controller's target and counters persist through the supervisor's
atomic ``fleet_state.json`` (``set_extra_state("autoscale", ...)``);
after a kill -9 the next controller resumes the persisted target and
*reconciles* the respawned fleet up or down to it.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import faults
from deepinteract_tpu.serving import admission
from deepinteract_tpu.serving.fleet import WorkerSupervisor
from deepinteract_tpu.serving.router import FleetRouter

logger = logging.getLogger(__name__)

_DECISIONS = obs_metrics.counter(
    "di_autoscale_decisions_total",
    "Autoscaler control decisions by kind",
    labelnames=("decision",))
_TARGET = obs_metrics.gauge(
    "di_autoscale_target_workers",
    "The autoscaler's current worker-count target")


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Capacity policy (CLI surface: ``cli/serve.py --autoscale``)."""

    min_workers: int = 1
    max_workers: int = 4
    # Control period; signals are sampled and streaks advanced per tick.
    interval_s: float = 1.0
    # Mean in-flight per routable worker above which the fleet is
    # under-provisioned / below which it is over-provisioned. The gap
    # between the two thresholds is the hysteresis band.
    queue_high: float = 2.0
    queue_low: float = 0.25
    # Router-side p99 (ms) that also counts as a high-pressure breach;
    # 0 disables the latency trigger (the histogram is cumulative, so
    # this is a scale-UP signal only).
    p99_high_ms: float = 0.0
    # Consecutive breaching polls required before any action.
    breach_polls: int = 3
    # Hold-down after ANY action, in seconds.
    cooldown_s: float = 10.0
    # Bound on the new worker's warm-up before a scale-up aborts.
    warm_timeout_s: float = 60.0
    # SIGTERM-drain grace for scale-down victims.
    drain_timeout_s: float = 30.0


class Autoscaler:
    """One control loop over a (supervisor, router) pair (module
    docstring). ``overrides`` seed new workers' spawn knobs (e.g. the
    primary ``weights_signature``) so scaled-up capacity joins the
    version the traffic actually wants."""

    def __init__(self, supervisor: WorkerSupervisor, router: FleetRouter,
                 cfg: AutoscalerConfig = AutoscalerConfig(),
                 overrides: Optional[Dict[str, Any]] = None):
        if cfg.min_workers < 1 or cfg.max_workers < cfg.min_workers:
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"[{cfg.min_workers}, {cfg.max_workers}]")
        self.sup = supervisor
        self.router = router
        self.cfg = cfg
        self.overrides = dict(overrides or {})
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._target = self._clamp(supervisor.cfg.num_workers)
        self._high_streak = 0
        self._low_streak = 0
        self._last_action_ts = 0.0  # monotonic; 0 = never acted
        self._scale_ups = 0
        self._scale_downs = 0
        self._errors = 0
        self._last_signals: Dict[str, Any] = {}
        self._prev_pressure = 0.0  # cumulative shed+admission rejects
        self._restore()
        _TARGET.set(float(self._target))

    def _clamp(self, n: int) -> int:
        return max(self.cfg.min_workers, min(self.cfg.max_workers, n))

    def _restore(self) -> None:
        """Resume the persisted target after a control-plane kill -9 —
        the fleet reconciles back to it instead of resetting to the
        static ``num_workers``."""
        record = self.sup.recovered_state().get("autoscale")
        if not isinstance(record, dict):
            return
        target = record.get("target_workers")
        if isinstance(target, int) and not isinstance(target, bool):
            with self._lock:
                self._target = self._clamp(target)
        for key in ("scale_ups", "scale_downs"):
            value = record.get(key)
            if isinstance(value, int) and not isinstance(value, bool):
                with self._lock:
                    setattr(self, f"_{key}", value)
        logger.info("autoscale: restored state from fleet_state.json: "
                    "%s", record)
        self._persist()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._run, name="autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("autoscale: tick failed")

    # -- signals -----------------------------------------------------------

    def signals(self) -> Dict[str, float]:
        """One consolidated overload snapshot (module docstring)."""
        infos = self.sup.routable_workers()
        inflight = []
        degraded = 0
        for w in infos:
            health = w.get("health") or {}
            q = health.get("inflight")
            if isinstance(q, (int, float)) and not isinstance(q, bool):
                inflight.append(float(q))
            if health.get("degraded") or health.get("shedding"):
                degraded += 1
        shed = admission.overload_signals()
        pressure = shed["admission_rejected"] + shed["shed_rejected"]
        with self._lock:
            pressure_delta = max(0.0, pressure - self._prev_pressure)
            self._prev_pressure = pressure
        return {
            "workers": float(len(infos)),
            "mean_inflight": (sum(inflight) / len(inflight)
                              if inflight else 0.0),
            "degraded_workers": float(degraded),
            "p99_ms": round(self.router.request_p99_ms(), 3),
            "shed_degraded": shed["shed_degraded"],
            "pressure_delta": pressure_delta,
        }

    # -- control -----------------------------------------------------------

    def poll_once(self) -> Optional[str]:
        """One control decision; returns the action taken (``"up"``,
        ``"down"``, ``"reconcile_up"``, ``"reconcile_down"``) or None.
        The ``autoscale.decision`` fault raises BEFORE any mutation —
        an injected failure is counted and the fleet stays unchanged."""
        sig = self.signals()
        decision, target = self._decide(sig)
        with self._lock:
            self._last_signals = dict(sig)
        if decision is None:
            return None
        try:
            faults.maybe_raise(
                "autoscale.decision",
                lambda: RuntimeError("injected autoscale.decision fault"))
            if decision.endswith("up"):
                self._scale_up(target)
            else:
                self._scale_down(target)
        except Exception as exc:  # noqa: BLE001 - chaos containment
            with self._lock:
                self._errors += 1
            _DECISIONS.inc(decision="error")
            logger.warning("autoscale: %s -> %d failed (%s) — fleet "
                           "unchanged", decision, target, exc)
            return None
        with self._lock:
            self._target = target
            self._last_action_ts = time.monotonic()
            self._high_streak = 0
            self._low_streak = 0
        _TARGET.set(float(target))
        _DECISIONS.inc(decision=decision)
        self._persist()
        logger.info("autoscale: %s -> target %d (signals %s)", decision,
                    target, sig)
        return decision

    def _decide(self, sig: Dict[str, float],
                ) -> Tuple[Optional[str], int]:
        """(decision, new_target). Streaks advance every poll; actions
        additionally require an expired cooldown. Reconciliation (the
        live fleet disagrees with the persisted target after a restart)
        bypasses hysteresis — the decision was already made — but still
        honors cooldown."""
        cfg = self.cfg
        high = (sig["mean_inflight"] >= cfg.queue_high
                or sig["degraded_workers"] > 0
                or sig["shed_degraded"] > 0
                or sig["pressure_delta"] > 0
                or (cfg.p99_high_ms > 0
                    and sig["p99_ms"] >= cfg.p99_high_ms))
        low = (sig["mean_inflight"] <= cfg.queue_low
               and sig["degraded_workers"] == 0
               and sig["shed_degraded"] == 0
               and sig["pressure_delta"] == 0)
        now = time.monotonic()
        with self._lock:
            self._high_streak = self._high_streak + 1 if high else 0
            self._low_streak = self._low_streak + 1 if low else 0
            target = self._target
            cooling = (self._last_action_ts > 0
                       and now - self._last_action_ts < cfg.cooldown_s)
            high_streak, low_streak = self._high_streak, self._low_streak
        if cooling:
            return None, target
        workers = int(sig["workers"])
        if workers and workers < target:
            return "reconcile_up", target
        if workers > self.cfg.max_workers or (
                workers and workers > target):
            return "reconcile_down", target
        if high_streak >= cfg.breach_polls and target < cfg.max_workers:
            return "up", target + 1
        if low_streak >= cfg.breach_polls and target > cfg.min_workers:
            return "down", target - 1
        return None, target

    def _scale_up(self, target: int) -> None:
        """Spawn one worker, wait until it is WARM (the rollover bar:
        healthy + status ok + required warm-bucket prefixes), then adopt
        it into the routing table. A worker that never warms is drained
        and the scale-up fails — cold capacity is not capacity."""
        worker_id = self.sup.spawn_worker(dict(self.overrides))
        target_sig = self.overrides.get("weights_signature")
        deadline = time.monotonic() + self.cfg.warm_timeout_s
        wait_s = min(max(self.sup.cfg.probe_interval_s, 0.05), 0.25)
        while time.monotonic() < deadline:
            self.sup.poll_once()
            if self.router._is_warm(worker_id, target_sig):
                self.router.adopt_worker(worker_id)
                logger.info("autoscale: scale-up adopted %s", worker_id)
                with self._lock:
                    self._scale_ups += 1
                return
            time.sleep(wait_s)
        self.sup.drain_many([worker_id], timeout_s=5.0)
        raise RuntimeError(
            f"scale-up worker {worker_id} not warm after "
            f"{self.cfg.warm_timeout_s:.0f}s — drained, fleet unchanged")

    def _scale_down(self, target: int) -> None:
        """Retire the YOUNGEST routable worker above the target: release
        it from routing first (new picks stop instantly), then SIGTERM-
        drain it through its own drain path — zero dropped requests."""
        routable = sorted(
            (w["worker_id"] for w in self.sup.routable_workers()),
            key=lambda wid: int(wid.lstrip("w") or 0))
        if len(routable) <= self.cfg.min_workers:
            raise RuntimeError(
                f"scale-down refused: {len(routable)} routable "
                f"worker(s) <= min_workers={self.cfg.min_workers}")
        victim = routable[-1]
        self.router.release_worker(victim)
        self.sup.drain_worker(victim,
                              timeout_s=self.cfg.drain_timeout_s)
        with self._lock:
            self._scale_downs += 1
        logger.info("autoscale: scale-down drained %s", victim)

    # -- observability -----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "target_workers": self._target,
                "min_workers": self.cfg.min_workers,
                "max_workers": self.cfg.max_workers,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "errors": self._errors,
                "high_streak": self._high_streak,
                "low_streak": self._low_streak,
                "last_signals": dict(self._last_signals),
            }

    def _persist(self) -> None:
        with self._lock:
            record = {
                "target_workers": self._target,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "errors": self._errors,
            }
        try:
            self.sup.set_extra_state("autoscale", record)
        except (OSError, ValueError) as exc:
            logger.warning("autoscale: persist failed: %s", exc)
