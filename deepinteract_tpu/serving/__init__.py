"""Persistent serving layer: resident engine, micro-batching, HTTP API.

The production counterpart of the one-shot ``cli/predict.py`` path:
compile once per shape bucket, batch concurrent requests into shared
device dispatches, cache repeated complexes, and drain cleanly on
preemption. See ``engine.py`` for the amortization model and
``server.py`` for the wire protocol.
"""

from deepinteract_tpu.serving.admission import (
    AdmissionController,
    BatchExecutionError,
    Deadline,
    DeadlineExceeded,
    LoadShedder,
    Overloaded,
    ShedderConfig,
    ShuttingDown,
)
from deepinteract_tpu.serving.cache import ResultCache, content_hash
from deepinteract_tpu.serving.engine import EngineConfig, InferenceEngine
from deepinteract_tpu.serving.scheduler import MicroBatchScheduler, SchedulerClosed
from deepinteract_tpu.serving.server import ServingServer

__all__ = [
    "AdmissionController",
    "BatchExecutionError",
    "Deadline",
    "DeadlineExceeded",
    "EngineConfig",
    "InferenceEngine",
    "LoadShedder",
    "MicroBatchScheduler",
    "Overloaded",
    "ResultCache",
    "SchedulerClosed",
    "ShedderConfig",
    "ShuttingDown",
    "ServingServer",
    "content_hash",
]
