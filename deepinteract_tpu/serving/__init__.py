"""Persistent serving layer: resident engine, micro-batching, HTTP API,
and the multi-worker fleet (supervisor + router).

The production counterpart of the one-shot ``cli/predict.py`` path:
compile once per shape bucket, batch concurrent requests into shared
device dispatches, cache repeated complexes, and drain cleanly on
preemption. See ``engine.py`` for the amortization model, ``server.py``
for the wire protocol, and ``fleet.py``/``router.py`` for the
multi-worker supervision/rollover layer.

Exports resolve LAZILY (PEP 562): importing the package does not pull
``engine`` (and with it jax) until an engine-side name is touched. The
fleet control plane and the ``worker_stub`` rehearsal worker live in
this package but are deliberately jax-free — ``python -m
deepinteract_tpu.serving.worker_stub`` starts in a fraction of a second
BECAUSE this module stays import-light, and every supervisor restart in
a chaos run pays that startup cost again.
"""

# name -> submodule it lazily resolves from.
_EXPORTS = {
    "AdmissionController": "admission",
    "BatchExecutionError": "admission",
    "Deadline": "admission",
    "DeadlineExceeded": "admission",
    "LoadShedder": "admission",
    "Overloaded": "admission",
    "ShedderConfig": "admission",
    "ShuttingDown": "admission",
    "ResultCache": "cache",
    "content_hash": "cache",
    "EngineConfig": "engine",
    "InferenceEngine": "engine",
    "FleetConfig": "fleet",
    "WorkerSupervisor": "fleet",
    "stub_worker_cmd": "fleet",
    "watch_parent": "fleet",
    "FleetRouter": "router",
    "RolloverBusy": "router",
    "RolloverFailed": "router",
    "RouterConfig": "router",
    "MicroBatchScheduler": "scheduler",
    "SchedulerClosed": "scheduler",
    "ServingServer": "server",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    try:
        modname = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    module = importlib.import_module(f"{__name__}.{modname}")
    value = getattr(module, name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
