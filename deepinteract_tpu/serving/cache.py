"""LRU result cache keyed on a content hash of the featurized complex.

Serving traffic is heavy-tailed: popular complexes (reference structures,
benchmark sets, retried uploads) recur, and a contact map is a pure
function of the featurized inputs plus the loaded weights — so an exact
content hash is a sound cache key. The hash covers every input array the
model consumes (both chains' node/edge features, coordinates, topology)
plus any engine-level flags that change the math (``input_indep``), so two
uploads that differ anywhere in the features can never collide onto one
entry short of a SHA-256 collision.

The cache stores *depadded* host results (``[n1, n2]`` float32 maps), so
hits cost zero device work and are bucket-policy independent: the same
complex served under a different bucketing configuration still hits.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, Iterable, Optional

import numpy as np

# The single source of truth for which arrays the model consumes per
# chain — importing it (rather than copying the list) keeps the cache
# key covering every input array even if the schema grows.
from deepinteract_tpu.data.io import GRAPH_KEYS as _HASHED_GRAPH_KEYS


def content_hash(raw: Dict, extra: Iterable = ()) -> str:
    """SHA-256 over the featurized complex's model-visible arrays.

    ``extra`` mixes in engine-level knobs that change the output for the
    same input (e.g. ``input_indep``); shapes and dtypes are hashed
    alongside the bytes so e.g. a [N,K] int32 and an [N*K] int32 with the
    same payload cannot alias.
    """
    h = hashlib.sha256()
    for graph_key in ("graph1", "graph2"):
        g = raw[graph_key]
        for key in _HASHED_GRAPH_KEYS:
            a = np.ascontiguousarray(g[key])
            h.update(f"{graph_key}.{key}:{a.dtype.str}:{a.shape}".encode())
            h.update(a.tobytes())
    for item in extra:
        h.update(repr(item).encode())
    return h.hexdigest()


class ResultCache:
    """Thread-safe LRU of prediction results.

    ``capacity <= 0`` disables caching (every ``get`` misses, ``put`` is a
    no-op) so one code path serves both configurations.
    """

    def __init__(self, capacity: int = 256):
        self.capacity = int(capacity)
        self._entries: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            if self.capacity <= 0 or key not in self._entries:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return self._entries[key]

    def put(self, key: str, value: Any) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self._hits + self._misses
            return {
                "capacity": self.capacity,
                "size": len(self._entries),
                "hits": self._hits,
                "misses": self._misses,
                "hit_rate": (self._hits / total) if total else 0.0,
            }
