"""Persistent inference engine: restore once, compile per bucket, serve many.

The one-shot ``cli/predict.py`` path pays checkpoint restore + a full
trace/compile of the Geometric Transformer + decoder per process — ~80 s
of compile for one complex on the benched TPU config (BENCH_r05.json).
A serving process must pay those costs once, then answer every request at
device-execution latency. The engine owns exactly that amortization:

* **weights resident**: the checkpoint is restored once at construction
  (``best/`` by default, matching ``cli/predict.py``) and kept on device;
* **shape-bucketed executable cache**: requests are padded to the loader's
  chain-length buckets (``data/loader.py`` ``make_bucket_fn`` — the same
  policy training uses, so serving inherits its compile economics), and
  one AOT-compiled executable is kept per ``(bucket_n1, bucket_n2,
  per-graph shape signature, batch)`` key (the signature covers each
  graph's knn/geo/feature widths independently). A warm request triggers
  ZERO new traces — pinned by a trace-count test;
* **bounded batch inventory**: coalesced groups are padded up to the next
  power-of-two batch size (duplicating a row, results discarded), so the
  executable inventory grows O(log max_batch) per bucket instead of one
  executable per observed group size;
* **over-bucket complexes**: chains beyond the top bucket pad to
  top-bucket multiples (``pick_bucket``) with BOTH sides lifted to
  tile-size multiples, and the model is built with ``tile_pair_map`` so
  the decoder runs blockwise (``models/tiled.py``) instead of
  materializing the full pair map;
* **micro-batching**: concurrent ``submit()`` futures of the same bucket
  share one device dispatch (``serving/scheduler.py``), and an LRU result
  cache (``serving/cache.py``) short-circuits repeated complexes.

``predict()`` is the blocking convenience wrapper over ``submit()``.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from deepinteract_tpu import constants
from deepinteract_tpu.data.graph import stack_complexes
from deepinteract_tpu.data.io import complex_lengths, to_paired_complex
from deepinteract_tpu.data.loader import make_bucket_fn
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import faults
from deepinteract_tpu.serving.admission import (
    AdmissionController,
    BatchExecutionError,
    Deadline,
    DeadlineExceeded,
    Overloaded,
    expired_counter,
)
from deepinteract_tpu.serving.cache import ResultCache, content_hash
from deepinteract_tpu.serving.fleet import (
    batch_slots as fleet_batch_slots,
    mesh_label,
    mesh_label_prefix,
    mesh_placement,
    parse_mesh_shape,
)
from deepinteract_tpu.serving.scheduler import MicroBatchScheduler

logger = logging.getLogger(__name__)

# Registry counters are PROCESS-wide (/metrics scope) and deliberately
# parallel to the engine's per-instance attributes (/stats scope): two
# engines in one process sum here but stay separate in their own stats(),
# and a test's registry.reset() must not blank a live engine's /stats.
_EXECUTED_REQUESTS = obs_metrics.counter(
    "di_serving_executed_requests_total",
    "Requests answered by a device dispatch (cache hits excluded)")
_EXECUTED_BATCHES = obs_metrics.counter(
    "di_serving_executed_batches_total", "Coalesced device dispatches")
_PADDED_SLOTS = obs_metrics.counter(
    "di_serving_padded_slots_total",
    "Batch slots filled with padding rows (discarded work)")
_CACHE_HITS = obs_metrics.counter(
    "di_serving_result_cache_hits_total",
    "Requests short-circuited by the result cache")
_COMPILES = obs_metrics.counter(
    "di_serving_compiles_total",
    "Cold executable compiles (one per new bucket/batch key)")
_COMPILE_SECONDS = obs_metrics.histogram(
    "di_serving_compile_seconds", "Wall time of each cold compile")
# Load-shedder signal: >0 while a cold compile holds the exec lock (a
# long compile stalls every flush behind it — exactly when shedding is
# cheaper than queueing).
_COMPILE_INFLIGHT = obs_metrics.gauge(
    "di_serving_compile_inflight", "Cold compiles currently in progress")


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Serving knobs (CLI surface: ``cli/serve.py``)."""

    # Micro-batching: flush a bucket's pending group at this many requests
    # or once its oldest request has waited max_delay_ms.
    max_batch: int = 8
    max_delay_ms: float = 5.0
    # Buckets compiled at startup, each (bucket_n1, bucket_n2, batch) —
    # first requests then hit warm executables instead of paying a trace.
    warmup_buckets: Tuple[Tuple[int, int, int], ...] = ()
    # LRU result-cache entries (depadded probability maps); <= 0 disables.
    result_cache_size: int = 256
    # Bucket policy — same semantics as the loader flags (cli/args.py):
    # diagonal pads both chains to the larger chain's bucket (at most L
    # compiled shape pairs instead of L^2).
    diagonal_buckets: bool = False
    pad_to_max_bucket: bool = False
    # Zero all input features (the scientific-control path); part of the
    # result-cache key since it changes the output for the same upload.
    input_indep: bool = False
    # Overload bounds (serving/admission.py): per-bucket pending-queue
    # cap and global admitted-in-flight cap. Submits beyond either raise
    # a typed Overloaded with a computed retry_after_s instead of
    # queueing unboundedly.
    max_queue_depth: int = 64
    max_inflight: int = 256
    # Pin the model's configured interaction_stem / compute_dtype against
    # tuned-entry adoption (cli/serve.py sets these when the operator
    # typed the flags explicitly — a stored trial must not silently
    # override them; the dtype additionally changes numerics).
    pin_interaction_stem: bool = False
    pin_compute_dtype: bool = False
    # Tuning-store path (tuning/store.py): when set, the engine resolves
    # the tuned config for its ACTIVE bucket (first warmup spec, else the
    # top bucket) BEFORE any AOT compile. Forward-relevant knobs are
    # applied to the (model-wide) config: the decoder chunk scan when no
    # checkpoint pins the param layout, and the Pallas block grid only
    # when it is legal for EVERY warmup bucket — a grid tuned for one
    # bucket must not degrade the others. The full tuned tuple is logged
    # either way.
    tuning_store: Optional[str] = None
    # Serving mesh topology as (num_data, num_pair) device counts (the
    # worker's slice; CLI surface ``--mesh_shape``). None/(1, 1) keeps
    # the single-device AOT path byte-identical. With a mesh, batch
    # slots shard over the data axis (throughput) and over-threshold
    # buckets row-shard over the pair axis (single-complex latency) —
    # see :meth:`InferenceEngine.placement_for`.
    mesh_shape: Optional[Tuple[int, int]] = None
    # Bucket pad at/above which a mesh with a pair axis decodes one
    # complex row-sharded instead of replicating it per data shard.
    pair_shard_threshold: int = 512


class InferenceEngine:
    """Resident model + shape-bucketed compile cache + micro-batcher.

    ``model_cfg`` defaults to the flagship ``ModelConfig`` with
    ``tile_pair_map`` forced on (a no-op for in-bucket shapes; required
    for the over-bucket long-context tier). ``ckpt_dir=None`` serves the
    untrained init — the smoke-test convention ``cli/predict.py`` uses.
    """

    def __init__(
        self,
        model_cfg=None,
        ckpt_dir: Optional[str] = None,
        cfg: EngineConfig = EngineConfig(),
        seed: int = 42,
        metric_to_track: str = "val_ce",
    ):
        import jax

        from deepinteract_tpu.models.model import DeepInteract, ModelConfig

        self.cfg = cfg
        base = model_cfg or ModelConfig()
        # Mesh topology is fixed before tuned-config adoption: the
        # tuning-store bucket key carries it, and a stored trial may
        # override the per-bucket placement policy.
        self._mesh_shape = parse_mesh_shape(cfg.mesh_shape)
        self._placement_overrides: Dict[Tuple[int, int], str] = {}
        # Tuned-config adoption happens on the UN-tiled config (the
        # signature the tuner measured under); tiling is forced after.
        self.adopted_tuning = None
        if cfg.tuning_store:
            base = self._adopt_tuned(base, ckpt_dir)
        if not base.tile_pair_map:
            base = dataclasses.replace(base, tile_pair_map=True)
        self.model = DeepInteract(base)
        self._mesh = None
        self._pair_model = None
        if self._mesh_shape != (1, 1):
            from deepinteract_tpu.parallel.mesh import serving_mesh

            self._mesh = serving_mesh(self._mesh_shape)
            if self._mesh_shape[1] > 1:
                # Pair-placement sibling: SAME param tree (shard_pair_map
                # only adds sharding constraints — models/stem.py keeps
                # one tree for both stems), separate traced functions so
                # the row-sharded decode gets its own AOT entries.
                self._pair_model = DeepInteract(dataclasses.replace(
                    base, shard_pair_map=True))
        self._tile = int(base.tile_size)
        self._base_bucket_fn = make_bucket_fn(
            cfg.pad_to_max_bucket, cfg.diagonal_buckets)
        # Tuned placement overrides were recorded against raw warmup
        # specs; re-key them onto the buckets the request path computes.
        self._placement_overrides = {
            self.bucket_for(*k): v
            for k, v in self._placement_overrides.items()}

        # Executable cache: the bucket/signature/batch key PLUS the mesh
        # topology and placement (appended by _compiled) -> AOT-compiled
        # fn.
        self._executables: Dict[Tuple, Any] = {}
        self._compile_seconds: Dict[str, float] = {}
        # Per-entry provenance for /stats.compile_inventory: seconds +
        # the topology/placement the entry compiled under.
        self._compile_info: Dict[str, Dict[str, Any]] = {}
        self._exec_lock = threading.Lock()
        # Compile-inventory labels mirrored under their OWN tiny lock:
        # /healthz reads them every supervisor probe tick and must
        # never block behind _exec_lock, which a cold compile holds for
        # its full lower+compile duration — a compiling-but-alive
        # worker that fails health probes would drop out of routing
        # fleet-wide. Nesting order is _exec_lock -> _labels_lock only.
        self._warm_labels: Tuple[str, ...] = ()
        self._labels_lock = threading.Lock()
        # Incremented by a Python side effect inside the traced function,
        # so it counts TRACES (not calls): the warm-path zero-retrace
        # guarantee is asserted on this counter, not inferred.
        self.trace_count = 0
        self._executed_batches = 0
        self._executed_requests = 0
        self._padded_slots = 0
        self._started = time.time()

        self.cache = ResultCache(cfg.result_cache_size)
        self._seed = int(seed)
        self._init_weights(seed, ckpt_dir, metric_to_track)
        if self._mesh is not None:
            from deepinteract_tpu.parallel.mesh import replicate

            # Jitted init committed the weights to device 0; a
            # mesh-compiled executable expects them replicated across
            # its slice — committed arrays with a mismatched sharding
            # would raise at the first warm call.
            self.params = replicate(self.params, self._mesh)
            self.batch_stats = replicate(self.batch_stats, self._mesh)
        self._jit_forward = jax.jit(self._forward)
        # Split-phase executables (bulk screening, deepinteract_tpu/
        # screening): one encoder pass per CHAIN, one decode per pair over
        # cached embeddings — registered in the same bucketed cache.
        self._jit_encode = jax.jit(self._encode)
        self._jit_decode = jax.jit(self._decode)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from deepinteract_tpu.parallel.mesh import batch_sharding

            # Placement-specific jit handles, each baking its
            # in_shardings (PR-15 constructors verbatim): "data" shards
            # batch slots over the data axis, "repl" replicates a group
            # whose slot count the data axis does not divide, "pair"
            # broadcasts the per-chain factors and row-shards inside the
            # decode (models/stem.py pair_row_spec constraints).
            repl = NamedSharding(self._mesh, PartitionSpec())
            data = batch_sharding(self._mesh)
            self._jit_forward_data = jax.jit(
                self._forward, in_shardings=(repl, repl, data, data))
            self._jit_forward_repl = jax.jit(
                self._forward, in_shardings=(repl, repl, repl, repl))
            self._jit_encode_data = jax.jit(
                self._encode, in_shardings=(repl, repl, data))
            self._jit_encode_repl = jax.jit(
                self._encode, in_shardings=(repl, repl, repl))
            self._jit_decode_data = jax.jit(
                self._decode,
                in_shardings=(repl, repl, data, data, data, data))
            self._jit_decode_repl = jax.jit(
                self._decode,
                in_shardings=(repl, repl, repl, repl, repl, repl))
            if self._pair_model is not None:
                from deepinteract_tpu.models.stem import pair_row_sharding

                rows = pair_row_sharding(self._mesh)
                self._jit_forward_pair = jax.jit(
                    self._forward_pair,
                    in_shardings=(repl, repl, repl, repl))
                # Chain-1 embeddings/masks arrive row-sharded (they ARE
                # the sharded dim); chain-2 factors broadcast per-shard.
                self._jit_decode_pair = jax.jit(
                    self._decode_pair,
                    in_shardings=(repl, repl, rows, repl, rows, repl))
        if cfg.warmup_buckets:
            self.warmup(cfg.warmup_buckets)
        self.admission = AdmissionController(
            max_queue_depth=cfg.max_queue_depth,
            max_inflight=cfg.max_inflight)
        self.scheduler = MicroBatchScheduler(
            self._flush, max_batch=cfg.max_batch,
            max_delay_ms=cfg.max_delay_ms,
            admission=self.admission,
            on_expired=self._expired_in_queue,
            # A data-axis-full group is already a complete mesh dispatch
            # (slot lift pads to D regardless): flush it immediately
            # instead of waiting out max_delay_ms for stragglers.
            flush_quantum=self._mesh_shape[0])

    # -- autotuning --------------------------------------------------------

    def _adopt_tuned(self, base, ckpt_dir: Optional[str]):
        """Resolve the tuned config for the engine's active bucket (first
        warmup spec, else the top bucket at batch 1) and apply the
        forward-relevant knobs. ``scan_chunks`` changes the PARAM TREE, so
        it is adopted only when no checkpoint pins the layout; remat and
        scan_k are training-side knobs — logged as part of the tuple but
        not applicable to the inference graph."""
        from deepinteract_tpu.tuning import consume

        if self.cfg.warmup_buckets:
            b1, b2, bs = self.cfg.warmup_buckets[0]
        else:
            b1 = b2 = constants.CHAIN_LENGTH_BUCKETS[-1]
            bs = 1
        pad = max(b1, b2)
        # Derived from cfg, not self._mesh_shape: this helper's contract
        # is cfg-only (test_tuning drives it on a bare shell).
        mesh_shape = parse_mesh_shape(self.cfg.mesh_shape)
        adopted = consume.lookup_path(self.cfg.tuning_store, base, bs, pad,
                                      mesh_shape=mesh_shape)
        if adopted is None:
            logger.info(
                "autotune: no tuning-store entry for bucket b%d_p%d in %s; "
                "serving with default configs", bs, pad,
                self.cfg.tuning_store)
            return base
        if (adopted.config.mesh_placement in ("data", "pair")
                and mesh_shape != (1, 1)):
            # Per-bucket autotuner override of the placement policy
            # (re-keyed through bucket_for once the bucket fn exists).
            self._placement_overrides[(int(b1), int(b2))] = \
                adopted.config.mesh_placement
        # The Pallas grid is a MODEL-wide setting but the entry was tuned
        # at one symmetric bucket: the kernel runs at each chain's OWN
        # pad, so the grid applies only when legal at every padded length
        # this engine will compile (BOTH dims of every warmup bucket).
        adopted = consume.respect_explicit(
            adopted, stem=self.cfg.pin_interaction_stem,
            dtype=self.cfg.pin_compute_dtype)
        warmup_pads = {p for spec in (self.cfg.warmup_buckets
                                      or ((b1, b2, bs),))
                       for p in spec[:2]}
        adopted, blocks_note = consume.restrict_pallas_blocks(
            adopted, warmup_pads, knn=constants.KNN)
        trial = adopted.config
        if (trial.pallas_fwd_blocks is not None
                or trial.pallas_bwd_blocks is not None):
            # Gen-2 warmup legality: a tuned Pallas grid is only
            # meaningful where the KERNEL itself is legal for every
            # warmup bucket under the dtype policy this engine will
            # actually compile with — supports_config threads
            # hidden/num_heads/compute_dtype (dtype-aware since the
            # gen-2 kernel; ops/pallas_attention.py).
            from deepinteract_tpu.ops.pallas_attention import supports_config

            gnn_probe = base.gnn
            if trial.compute_dtype is not None:
                gnn_probe = dataclasses.replace(
                    gnn_probe, compute_dtype=trial.compute_dtype)
            illegal = sorted(p for p in warmup_pads
                             if not supports_config(gnn_probe, p, batch=bs))
            if illegal:
                adopted = dataclasses.replace(
                    adopted, config=dataclasses.replace(
                        trial, pallas_fwd_blocks=None,
                        pallas_bwd_blocks=None))
                trial = adopted.config
                blocks_note += (
                    " (tuned Pallas grid NOT applied: kernel unsupported "
                    f"at warmup pad(s) {illegal} for this model/dtype)")
        gnn = dataclasses.replace(
            base.gnn,
            pallas_fwd_blocks=trial.pallas_fwd_blocks,
            pallas_bwd_blocks=trial.pallas_bwd_blocks,
        )
        decoder = base.decoder
        scan_note = ""
        if trial.scan_chunks != base.decoder.scan_chunks:
            if ckpt_dir is None:
                decoder = dataclasses.replace(
                    base.decoder, scan_chunks=trial.scan_chunks)
            else:
                scan_note = (" (tuned scan_chunks NOT applied: the "
                             "checkpoint pins the param layout)")
        self.adopted_tuning = adopted
        logger.info("autotune: serving adopts (%s) for bucket b%d_p%d%s%s",
                    adopted.summary(), bs, pad, scan_note, blocks_note)
        # Stem + compute-dtype are forward-relevant AND param-tree-
        # preserving (models/stem.py keeps one tree for both stems; the
        # dtype policy keeps params float32), so they adopt safely even
        # under a pinned checkpoint. None = the trial left the knob at
        # "caller's config" (tuning/space.py) — keep the engine's own.
        base = dataclasses.replace(base, gnn=gnn, decoder=decoder)
        if trial.interaction_stem is not None:
            base = dataclasses.replace(
                base, interaction_stem=trial.interaction_stem)
        if trial.compute_dtype is not None:
            base = dataclasses.replace(
                base, compute_dtype=trial.compute_dtype)
        return base

    # -- weights -----------------------------------------------------------

    def _init_weights(self, seed: int, ckpt_dir: Optional[str],
                      metric_to_track: str) -> None:
        """Initialize parameters once (jitted init — eager flax init costs
        thousands of dispatches, training/steps.py:create_train_state) and
        overwrite them from the checkpoint's ``best/`` tree if given."""
        import jax

        from deepinteract_tpu.data.synthetic import random_complex

        # Param shapes are input-shape independent (node/edge feature
        # widths are fixed by the schema), so a small synthetic example at
        # the bottom bucket initializes the exact serving tree. knn=4
        # keeps the featurization trivial; it does not affect params.
        example = stack_complexes([random_complex(
            12, 10, rng=np.random.default_rng(seed),
            n_pad1=constants.CHAIN_LENGTH_BUCKETS[0],
            n_pad2=constants.CHAIN_LENGTH_BUCKETS[0],
            knn=4, geo_nbrhd_size=2,
        )])
        root = jax.random.PRNGKey(seed)
        params_rng, dropout_rng = jax.random.split(root)
        init_fn = jax.jit(self.model.init, static_argnames=("train",))
        variables = init_fn({"params": params_rng, "dropout": dropout_rng},
                            example.graph1, example.graph2, train=False)
        self.params = variables["params"]
        self.batch_stats = variables.get("batch_stats", {})
        self.restored_from = None
        if ckpt_dir:
            from deepinteract_tpu.training.checkpoint import (
                Checkpointer,
                CheckpointConfig,
            )

            def absify(x):
                arr = x if isinstance(x, jax.Array) else np.asarray(x)
                return jax.ShapeDtypeStruct(arr.shape, arr.dtype)

            ckpt = Checkpointer(CheckpointConfig(
                directory=ckpt_dir, metric_to_track=metric_to_track))
            template = jax.tree_util.tree_map(
                absify, {"params": self.params,
                         "batch_stats": self.batch_stats})
            restored = ckpt.restore(template, which="best", partial=True)
            ckpt.close()
            self.params = jax.device_put(restored["params"])
            self.batch_stats = jax.device_put(restored["batch_stats"])
            self.restored_from = ckpt_dir

    # -- shape policy ------------------------------------------------------

    def bucket_for(self, n1: int, n2: int) -> Tuple[int, int]:
        """Padded (bucket_n1, bucket_n2) for a request.

        In-bucket chains follow the loader's policy verbatim. Once either
        chain exceeds the top bucket the decoder must run tiled, and
        ``models/tiled.py:tile_grid`` requires BOTH padded lengths to be
        tile multiples — so the partner chain's bucket is lifted to the
        next tile multiple too (e.g. (300, 40) -> (512, 256) at tile 256,
        not (512, 64), which the tiled scan would reject)."""
        b1, b2 = self._base_bucket_fn(n1, n2)
        if b1 > self._tile or b2 > self._tile:
            lift = lambda b: ((b + self._tile - 1) // self._tile) * self._tile
            return lift(b1), lift(b2)
        return b1, b2

    def _batch_slots(self, n_requests: int,
                     bucket: Optional[Tuple[int, int]] = None) -> int:
        """Coalesced groups pad to the next power of two (capped at
        max_batch) so the per-bucket executable inventory stays
        O(log max_batch) instead of one compile per observed group
        size. Delegates to the shared policy the fleet's rollover
        readiness check also uses (serving/fleet.batch_slots).

        On a data-parallel mesh the floor lifts to the data-axis size so
        every chip holds at least one slot (pair-placement buckets skip
        the lift: one huge complex row-shards instead of replicating)."""
        lift = 1
        if (self._mesh is not None and self._mesh_shape[0] > 1
                and (bucket is None
                     or self.placement_for(*bucket) != "pair")):
            lift = self._mesh_shape[0]
        return fleet_batch_slots(n_requests, self.cfg.max_batch,
                                 lift_to=lift)

    def placement_for(self, b1: int, b2: int) -> str:
        """Mesh placement for one bucket: the shared policy
        (serving/fleet.mesh_placement — small buckets replicate
        data-parallel, over-threshold buckets pair-shard) unless the
        adopted tuning entry pinned this bucket explicitly. Reads are
        lock-free: the override map is frozen at construction."""
        if self._mesh is None:
            return "single"
        placement = self._placement_overrides.get((int(b1), int(b2)))
        if placement is None:
            placement = mesh_placement(
                self._mesh_shape, b1, b2, self.cfg.pair_shard_threshold)
        if placement == "pair" and self._pair_model is None:
            placement = "data"
        return placement

    def _effective_placement(self, b1: int, b2: int, slots: int) -> str:
        """What actually compiles for one (bucket, slots) key: a "data"
        group whose slot count the data axis does not divide degrades to
        "repl" (replicated execution) — deterministic per key, since
        slots is part of the key."""
        placement = self.placement_for(b1, b2)
        if placement == "data" and slots % self._mesh_shape[0] != 0:
            placement = "repl"
        return placement

    # -- compile cache -----------------------------------------------------

    def _forward(self, params, batch_stats, graph1, graph2):
        # Python side effect: executes once per TRACE, never per call —
        # and every trace runs inside _compiled's lower(), under
        # _exec_lock.
        self.trace_count += 1  # di: allow[lock-discipline] traces run under _exec_lock via _compiled
        import jax

        logits = self.model.apply(
            {"params": params, "batch_stats": batch_stats},
            graph1, graph2, train=False,
        )
        return jax.nn.softmax(logits, axis=-1)[..., 1]

    def _forward_pair(self, params, batch_stats, graph1, graph2):
        # Pair-placement twin of _forward: same params, but the apply
        # goes through the shard_pair_map sibling so the interaction
        # map row-shards over the mesh's 'pair' axis (models/stem.py
        # constraints; XLA inserts the halo exchange / gather at dilated
        # conv boundaries). Separate traced fn => its own cache entries.
        self.trace_count += 1  # di: allow[lock-discipline] traces run under _exec_lock via _compiled
        import jax

        logits = self._pair_model.apply(
            {"params": params, "batch_stats": batch_stats},
            graph1, graph2, train=False,
        )
        return jax.nn.softmax(logits, axis=-1)[..., 1]

    # -- split-phase forward (bulk screening) ------------------------------
    #
    # The model is siamese (one shared-weight encoder leg per chain), so an
    # N-chain all-vs-all screen needs N encoder passes and N^2 cheap
    # decodes — NOT N^2 full forwards. These two executables are the
    # monolithic ``_forward`` split at ``DeepInteract.encode``/``decode``
    # (models/model.py): composing them reproduces its probabilities
    # exactly (parity-tested in tests/test_screening.py).

    def _encode(self, params, batch_stats, graph):
        # Python side effect: executes once per TRACE, never per call.
        self.trace_count += 1  # di: allow[lock-discipline] traces run under _exec_lock via _compiled
        import jax.numpy as jnp

        feats, _ = self.model.apply(
            {"params": params, "batch_stats": batch_stats}, graph,
            train=False, method="encode")
        # Cached embeddings are dtype-stable float32 regardless of the
        # compute policy (bf16 -> f32 is exact; decode re-casts to the
        # policy dtype — models/model.py:decode).
        return jnp.asarray(feats, dtype=jnp.float32)

    def _decode(self, params, batch_stats, feats1, feats2, mask1, mask2):
        self.trace_count += 1  # di: allow[lock-discipline] traces run under _exec_lock via _compiled
        import jax

        logits = self.model.apply(
            {"params": params, "batch_stats": batch_stats},
            feats1, feats2, mask1, mask2, train=False, method="decode")
        return jax.nn.softmax(logits, axis=-1)[..., 1]

    def _decode_pair(self, params, batch_stats, feats1, feats2, mask1,
                     mask2):
        self.trace_count += 1  # di: allow[lock-discipline] traces run under _exec_lock via _compiled
        import jax

        logits = self._pair_model.apply(
            {"params": params, "batch_stats": batch_stats},
            feats1, feats2, mask1, mask2, train=False, method="decode")
        return jax.nn.softmax(logits, axis=-1)[..., 1]

    def chain_bucket(self, n: int) -> int:
        """Padded bucket for a LONE chain under this engine's bucket
        policy (the split-phase analog of :meth:`bucket_for`)."""
        return self.bucket_for(n, n)[0]

    def encode_executable(self, bucket: int, sig: Tuple, slots: int,
                          graph_batch):
        """AOT-compiled per-chain-bucket encoder over a ``[slots, bucket,
        ...]`` stacked graph batch; cached under the same inventory as the
        monolithic executables. The encoder is per-chain (no pair map),
        so mesh placement is data-axis only: slots shard when the data
        axis divides them, else the batch replicates."""
        placement = "single"
        jit_fn = self._jit_encode
        if self._mesh is not None:
            if slots % self._mesh_shape[0] == 0:
                placement, jit_fn = "data", self._jit_encode_data
            else:
                placement, jit_fn = "repl", self._jit_encode_repl
        key = ("enc", bucket, sig, slots)
        return self._compiled(
            key, f"enc:{bucket}/b{slots}/k{sig[0]}g{sig[1]}",
            jit_fn, (self.params, self.batch_stats, graph_batch),
            placement=placement)

    def decode_executable(self, b1: int, b2: int, slots: int, args: Tuple):
        """AOT-compiled per-(bucket1, bucket2, batch) interaction-stem +
        decoder over cached embeddings. ``args`` is (feats1, feats2,
        mask1, mask2) at the padded bucket shapes. Placement follows
        :meth:`placement_for`: an over-threshold bucket on a pair-axis
        mesh decodes row-sharded (this is the p512+ single-complex
        path), everything else data-shards or replicates."""
        placement = self._effective_placement(b1, b2, slots)
        jit_fn = {
            "single": self._jit_decode,
            "data": getattr(self, "_jit_decode_data", None),
            "repl": getattr(self, "_jit_decode_repl", None),
            "pair": getattr(self, "_jit_decode_pair", None),
        }[placement]
        key = ("dec", b1, b2, slots)
        return self._compiled(
            key, f"dec:{b1}x{b2}/b{slots}", jit_fn,
            (self.params, self.batch_stats) + tuple(args),
            placement=placement)

    def weights_signature(self) -> str:
        """Identity of the served weights — part of the embedding-cache
        key (an embedding is a function of chain features AND weights)."""
        return self.restored_from or f"init-seed{self._seed}"

    @property
    def mesh_shape(self) -> Tuple[int, int]:
        """(num_data, num_pair) of the worker's serving mesh; (1, 1)
        when serving single-device."""
        return self._mesh_shape

    def mesh_shape_label(self) -> str:
        """Canonical ``"DxP"`` topology label — what /healthz advertises
        for the router's topology-aware placement and warm proofs."""
        return mesh_label(self._mesh_shape)

    def warm_bucket_labels(self) -> list:
        """Sorted compile-inventory labels (the ``compiled_buckets``
        keys of :meth:`stats`) from the NON-BLOCKING mirror —
        ``/healthz`` is probed every supervisor tick and must answer
        while a cold compile holds ``_exec_lock`` for minutes."""
        with self._labels_lock:
            return list(self._warm_labels)

    def _compiled(self, key: Tuple, label: str, jit_fn, args,
                  placement: str = "single"):
        """Warm path: dict hit, zero traces. Cold path: one explicit
        lower+compile, recorded in the per-bucket inventory. Shared by the
        monolithic forward and the split-phase encode/decode executables
        (one cache, one lock, one compile counter).

        The mesh topology and placement ride EVERY key and the topology
        prefixes every label (serving/fleet.mesh_label_prefix): a 1-chip
        and a 4-chip entry for the same bucket can never collide in the
        cache, and a replacement worker on a different topology can
        never satisfy this worker's rollover warm proof. Single-device
        engines keep their existing keys/labels verbatim. Mesh compiles
        lower under mesh_context: the interior with_sharding_constraint
        annotations resolve their bare PartitionSpecs against the
        ambient mesh at trace time."""
        key = key + (self._mesh_shape, placement)
        label = mesh_label_prefix(self._mesh_shape) + label
        if placement in ("pair", "repl"):
            # Suffix (never a prefix: warm-readiness matches on label
            # prefixes) so the inventory shows WHICH mesh path compiled.
            label = f"{label}/{placement}"
        with self._exec_lock:
            cached = self._executables.get(key)
            if cached is not None:
                return cached
            t0 = time.perf_counter()
            _COMPILE_INFLIGHT.inc()
            try:
                if self._mesh is not None:
                    from deepinteract_tpu.parallel.mesh import mesh_context

                    with mesh_context(self._mesh):
                        compiled = jit_fn.lower(*args).compile()
                else:
                    compiled = jit_fn.lower(*args).compile()
            finally:
                _COMPILE_INFLIGHT.dec()
            self._executables[key] = compiled
            elapsed = time.perf_counter() - t0
            self._compile_seconds[label] = elapsed
            self._compile_info[label] = {
                "seconds": round(elapsed, 3),
                "mesh_shape": mesh_label(self._mesh_shape),
                "placement": placement,
            }
            with self._labels_lock:
                self._warm_labels = tuple(sorted(self._compile_seconds))
            _COMPILES.inc()
            _COMPILE_SECONDS.observe(elapsed)
            return compiled

    def _forward_executable(self, key: Tuple, batch, placement: str):
        jit_fn = {
            "single": self._jit_forward,
            "data": getattr(self, "_jit_forward_data", None),
            "repl": getattr(self, "_jit_forward_repl", None),
            "pair": getattr(self, "_jit_forward_pair", None),
        }[placement]
        return self._compiled(
            key, self._key_label(key), jit_fn,
            (self.params, self.batch_stats, batch.graph1, batch.graph2),
            placement=placement)

    def _executable_for(self, key: Tuple[int, int, int, int, int], batch):
        b1, b2, slots = key[0], key[1], key[-1]
        return self._forward_executable(
            key, batch, self._effective_placement(b1, b2, slots))

    @staticmethod
    def _key_label(key: Tuple) -> str:
        b1, b2, sig1, sig2, bs = key
        label = f"{b1}x{b2}/b{bs}/k{sig1[0]}g{sig1[1]}"
        if sig2 != sig1:
            label += f"/k2_{sig2[0]}g2_{sig2[1]}"
        return label

    def normalize_warmup(self, b1: int, b2: int, bs: int) -> Tuple[int, int, int]:
        """Map an operator warmup spec onto a key the REQUEST PATH can
        actually hit: buckets through :meth:`bucket_for` (requests never
        see un-bucketed pads) and batch through :meth:`_batch_slots`
        (flushes only ever use power-of-two sizes capped at max_batch).
        Without this, ``--warmup_buckets 128x128x6`` would compile an
        executable no request could look up — paying startup compile AND
        the first client's cold trace."""
        nb1, nb2 = self.bucket_for(b1, b2)
        return nb1, nb2, self._batch_slots(bs, bucket=(nb1, nb2))

    def warmup(self, buckets: Sequence[Tuple[int, int, int]],
               knn: int = constants.KNN,
               geo: int = constants.GEO_NBRHD_SIZE) -> None:
        """Compile the given (bucket_n1, bucket_n2, batch) shapes now, so
        startup (not the first unlucky client) pays the traces. Specs are
        normalized onto reachable keys (see :meth:`normalize_warmup`)."""
        from deepinteract_tpu.data.synthetic import random_complex

        rng = np.random.default_rng(0)
        for spec in buckets:
            b1, b2, bs = self.normalize_warmup(*spec)
            # Chains must exceed knn for the synthetic featurizer; the
            # compiled shapes depend only on the padded sizes.
            one = random_complex(min(b1, knn + 1), min(b2, knn + 1),
                                 rng=rng, n_pad1=b1, n_pad2=b2, knn=knn,
                                 geo_nbrhd_size=geo)
            batch = stack_complexes([one] * bs)
            sig = tuple(
                (int(g.nbr_idx.shape[-1]), int(g.src_nbr_eids.shape[-1]),
                 int(g.node_feats.shape[-1]), int(g.edge_feats.shape[-1]))
                for g in (one.graph1, one.graph2))
            self._executable_for((b1, b2) + sig + (bs,), batch)

    # -- request path ------------------------------------------------------

    @staticmethod
    def _shape_signature(raw: Dict) -> Tuple:
        """Everything BESIDES the padded lengths that determines the
        compiled avals, per graph: (knn, geo, node-feature width,
        edge-feature width). graph2's dims are included independently —
        deriving the key from graph1 alone would alias an asymmetric
        upload (e.g. g2 featurized at a different K) onto a mismatched
        executable and fail its whole coalesced group."""
        sig = []
        for g in (raw["graph1"], raw["graph2"]):
            sig.append((int(g["nbr_idx"].shape[1]),
                        int(g["src_nbr_eids"].shape[2]),
                        int(g["node_feats"].shape[1]),
                        int(g["edge_feats"].shape[2])))
        return tuple(sig)

    def _expired_in_queue(self, payload: Dict, deadline) -> Exception:
        """Scheduler ``on_expired`` hook: build the typed failure for a
        deadline-swept request, with its trace decomposition attached
        (``device_ms == 0`` by construction — it never dispatched)."""
        trace = None
        rt = payload.get("reqtrace")
        if rt is not None:
            rt.set_phase("queue_wait", rt.since("submit"))
            trace = rt.finish(deadline=deadline.budget_s,
                              deadline_remaining=0.0)
        return DeadlineExceeded(
            f"deadline ({deadline.budget_s * 1e3:.0f}ms) expired while "
            "queued; dropped before batch assembly", trace=trace)

    def submit(self, raw: Dict, reqtrace=None,
               deadline: Optional[Deadline] = None) -> Future:
        """Future-returning enqueue. ``raw`` is a loaded complex dict
        (``data/io.py`` schema: graph1/graph2/examples). ``reqtrace`` is
        an optional :class:`deepinteract_tpu.obs.reqtrace.RequestTrace`
        carried through the scheduler queue to the flush; when given, the
        result dict gains a ``trace`` decomposition (queue-wait /
        assembly / compile / device) under the request's ``trace_id``.
        ``deadline`` (serving/admission.py) is checked here, at the
        scheduler's batch-assembly sweep, and bounds ``predict``'s wait.

        Raises ``Overloaded`` (bounded queues full — typed, with
        ``retry_after_s``) or ``DeadlineExceeded`` (already expired at
        admission); the returned future can additionally fail with either
        plus ``BatchExecutionError``/``ShuttingDown``.

        Result contract: ``probs`` is a READ-ONLY array (it may be shared
        with the result cache) — ``.copy()`` it before mutating."""
        faults.maybe_raise(
            "serving.admission",
            lambda: Overloaded("injected admission fault",
                               retry_after_s=self.admission.retry_after_s()))
        if deadline is not None and deadline.expired:
            # Dead on arrival — even a cache hit is wasted bytes for a
            # client that already gave up.
            expired_counter("admission")
            raise DeadlineExceeded(
                f"deadline ({deadline.budget_s * 1e3:.0f}ms) already "
                "expired at admission")
        key = None
        if self.cache.capacity > 0:  # don't hash MBs for a disabled cache
            key = content_hash(raw,
                               extra=("input_indep", self.cfg.input_indep))
            hit = self.cache.get(key)
            if hit is not None:
                _CACHE_HITS.inc()
                fut: Future = Future()
                result = dict(hit, cached=True)
                if reqtrace is not None:
                    # A hit never queues or touches the device: every
                    # phase is legitimately zero.
                    result["trace"] = reqtrace.finish(cached=True)
                fut.set_result(result)
                return fut
        n1, n2 = complex_lengths(raw)
        b1, b2 = self.bucket_for(n1, n2)
        if reqtrace is not None:
            reqtrace.mark("submit")
        return self.scheduler.submit(
            (b1, b2) + self._shape_signature(raw),
            {"raw": raw, "n1": n1, "n2": n2, "cache_key": key,
             "reqtrace": reqtrace, "deadline": deadline},
            deadline=deadline,
        )

    def predict(self, raw: Dict, timeout: Optional[float] = None,
                reqtrace=None, deadline: Optional[Deadline] = None) -> Dict:
        """Blocking single-complex prediction through the same batched
        path (so even sequential callers share warm executables). With a
        ``deadline``, the wait is bounded by it (plus a small grace for
        the scheduler's sweep to answer) — a caller never hangs past its
        deadline even if the flush worker is stuck in a long compile."""
        fut = self.submit(raw, reqtrace=reqtrace, deadline=deadline)
        if deadline is not None:
            bound = deadline.remaining_s() + 0.25
            timeout = bound if timeout is None else min(timeout, bound)
            try:
                return fut.result(timeout=timeout)
            except FuturesTimeout:
                # The future is still pending (e.g. its group is mid-
                # dispatch); the client's budget is spent either way.
                expired_counter("wait")
                raise DeadlineExceeded(
                    f"deadline ({deadline.budget_s * 1e3:.0f}ms) expired "
                    "while waiting for the result") from None
        return fut.result(timeout=timeout)

    def _flush(self, bucket_key, items) -> list:
        """One coalesced device dispatch for same-bucket requests — runs on
        the scheduler's worker thread. ``bucket_key`` is (b1, b2) plus the
        per-graph shape signature (see :meth:`_shape_signature`).

        Request-trace phase boundaries (batch-shared; each traced request
        records the batch's value with its ``coalesced`` count): dequeue
        closes queue_wait, then assembly (featurize/pad/stack), then
        executable acquisition (compile — ≈0 warm), then dispatch+fetch
        (device)."""
        traces = [it.get("reqtrace") for it in items]
        t_dequeue = time.perf_counter()
        for rt in traces:
            if rt is not None:
                rt.set_phase("queue_wait", rt.since("submit"))
        b1, b2 = bucket_key[0], bucket_key[1]
        try:
            faults.maybe_raise(
                "serving.assembly",
                lambda: BatchExecutionError("injected batch-assembly fault",
                                            stage="assembly"))
            complexes = [
                to_paired_complex(it["raw"], n_pad1=b1, n_pad2=b2,
                                  input_indep=self.cfg.input_indep)
                for it in items
            ]
            slots = self._batch_slots(len(complexes), bucket=(b1, b2))
            pad_slots = slots - len(complexes)
            complexes.extend([complexes[0]] * pad_slots)
            batch = stack_complexes(complexes)
        except BatchExecutionError:
            raise
        except Exception as exc:
            raise BatchExecutionError(
                f"batch assembly failed: {exc}", stage="assembly") from exc
        t_assembled = time.perf_counter()
        compiled = self._executable_for(tuple(bucket_key) + (slots,), batch)
        t_compiled = time.perf_counter()
        try:
            faults.maybe_raise(
                "serving.dispatch",
                lambda: BatchExecutionError("injected device-dispatch fault",
                                            stage="dispatch"))
            probs = np.asarray(
                compiled(self.params, self.batch_stats,
                         batch.graph1, batch.graph2)
            )
        except BatchExecutionError:
            raise
        except Exception as exc:
            # Typed so clients (and tests) can tell "your batch died" from
            # "your upload was bad"; the scheduler fails ONLY this group
            # and its worker keeps serving (di_serving_batch_failures).
            raise BatchExecutionError(
                f"device dispatch failed: {exc}", stage="dispatch") from exc
        t_fetched = time.perf_counter()
        for rt in traces:
            if rt is not None:
                rt.set_phase("batch_assembly", t_assembled - t_dequeue)
                rt.set_phase("compile", t_compiled - t_assembled)
                rt.set_phase("device", t_fetched - t_compiled)
        # Under _exec_lock: mutated on the scheduler worker thread, read
        # by HTTP handler threads via stats() — a bare += is a
        # read-modify-write race (lint: lock-discipline).
        with self._exec_lock:
            self._executed_batches += 1
            self._executed_requests += len(items)
            self._padded_slots += pad_slots
        _EXECUTED_BATCHES.inc()
        _EXECUTED_REQUESTS.inc(len(items))
        _PADDED_SLOTS.inc(pad_slots)
        results = []
        for i, it in enumerate(items):
            depadded = probs[i, : it["n1"], : it["n2"]].copy()
            # The array may be shared with the cache (hits return it
            # again): read-only, so a client mutating in place fails
            # loudly instead of silently corrupting later cache hits.
            depadded.setflags(write=False)
            result = {
                "probs": depadded,
                "n1": it["n1"],
                "n2": it["n2"],
                "bucket": (b1, b2),
                "batch_slots": slots,
                "coalesced": len(items),
                "cached": False,
            }
            if it["cache_key"] is not None:
                # The cache holds its OWN dict (sharing only the
                # immutable array), so key-level mutations by the first
                # caller cannot reach later hits either. The cached copy
                # is snapshotted BEFORE the trace block is attached — a
                # later hit is a different request with its own trace.
                self.cache.put(it["cache_key"], dict(result))
            rt = traces[i]
            if rt is not None:
                extra = {}
                dl = it.get("deadline")
                if dl is not None:
                    # Per-request deadline accounting in the PR-7
                    # decomposition: the budget and what was left of it
                    # when the result came back.
                    extra = {"deadline": dl.budget_s,
                             "deadline_remaining": dl.remaining_s()}
                result["trace"] = rt.finish(coalesced=len(items), **extra)
            results.append(result)
        return results

    # -- lifecycle / observability ----------------------------------------

    def close(self, timeout: float = 60.0) -> bool:
        """Drain the scheduler: flush every pending request, then stop
        accepting. Called by the server's SIGTERM path. False = the drain
        timed out with work still in flight (already logged loudly)."""
        return self.scheduler.drain(timeout=timeout)

    def stats(self) -> Dict[str, Any]:
        with self._exec_lock:
            compiled = dict(self._compile_seconds)
            inventory = {label: dict(info)
                         for label, info in self._compile_info.items()}
            executed_batches = self._executed_batches
            executed_requests = self._executed_requests
            padded_slots = self._padded_slots
        return {
            "uptime_seconds": time.time() - self._started,
            "restored_from": self.restored_from,
            "mesh_shape": self.mesh_shape_label(),
            # The served model's stem/precision configuration: what the
            # AOT executables were actually compiled with.
            "interaction_stem": self.model.cfg.interaction_stem,
            "compute_dtype": {
                "gnn": self.model.cfg.gnn.compute_dtype,
                "decoder": self.model.cfg.decoder.compute_dtype,
            },
            "tuning": {
                "store": self.cfg.tuning_store,
                "adopted": (self.adopted_tuning.summary()
                            if self.adopted_tuning is not None else None),
            },
            "trace_count": self.trace_count,
            "compiled_buckets": compiled,
            # Topology-stamped inventory (satellite of the mesh-native
            # engine): each entry records the mesh shape + placement it
            # compiled under, so operators can SEE that 1-chip and mesh
            # entries are distinct, not just trust the cache key.
            "compile_inventory": inventory,
            "num_compiled_executables": len(compiled),
            "executed_batches": executed_batches,
            "executed_requests": executed_requests,
            "padded_slots": padded_slots,
            "scheduler": self.scheduler.stats(),
            "admission": self.admission.stats(),
            "result_cache": self.cache.stats(),
        }
