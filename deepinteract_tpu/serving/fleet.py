"""Worker supervision: spawn, watch, restart, and retire engine workers.

One serving process (PR 2/11/12) is overload-safe and durable, but it is
still ONE process: a crash, a preemption, or a weights update is
client-visible downtime. The fleet layer splits serving into a
supervisor/router pair (this module + ``serving/router.py``) in front of
N single-engine worker processes (``cli/serve.py`` with ``--workers 0``,
or the ``serving/worker_stub.py`` rehearsal double):

* **spawn** — each worker is a child process with its own port,
  heartbeat file, and log, built by an injectable ``cmd_fn`` (the CLI
  provides the real engine-worker command line; tests and the bench
  ``rollover`` section provide :func:`stub_worker_cmd`);
* **watch** — a monitor thread polls every worker: process liveness
  (``Popen.poll``), heartbeat freshness
  (:func:`deepinteract_tpu.obs.heartbeat.read_heartbeat` — the SAME
  staleness check ``cli/fsck.py`` uses), and a ``GET /healthz`` probe
  whose payload (``weights_signature``, ``warm_buckets``) the router
  reads for routing and rollover-readiness decisions. A live process
  with a wedged beat (stale past ``wedge_kill_factor`` times the max
  age) is SIGKILLed so the normal crash-restart path recovers it;
* **restart** — a crashed worker is respawned with PR-1 exponential
  backoff (``robustness/retry.compute_delay``: jittered, capped), and a
  flapping worker — more than ``circuit_max_restarts`` restarts inside
  ``circuit_window_s`` — opens a circuit breaker: the supervisor stops
  feeding it restarts (a poisoned checkpoint or bad flag would otherwise
  crash-loop forever), keeps the rest of the fleet serving, and reports
  the open circuit on ``/stats`` + ``di_fleet_circuit_open``;
* **retire** — rollover and shutdown drain workers through their own
  SIGTERM path (PR-1/PR-11 discipline: finish in-flight, exit 0) and
  mark them retired so an expected exit is never misread as a crash.

* **preempt** — spot/preemptible capacity loss is a FIRST-CLASS event,
  not a crash: :meth:`WorkerSupervisor.preempt_worker` marks the worker
  ``preempted`` and SIGTERMs it (the worker's own drain path finishes
  in-flight work), and when the process exits the supervisor retires it
  with NO circuit-breaker penalty and spawns a replacement immediately
  (no backoff — the capacity is wanted back now). ``fleet.preempt`` is
  the chaos site: a planned firing inside :meth:`poll_once` preempts the
  newest healthy worker, so the chaos suite and the bench ``elasticity``
  section inject preemptions deterministically.

Chaos sites (``robustness/faults.py``): ``fleet.spawn`` fails a worker
spawn (exercises the backoff path), ``fleet.probe`` poisons a health
probe (worker looks unreachable), ``fleet.kill`` fails the SIGTERM of a
drain (the SIGKILL fallback must still retire the worker),
``fleet.preempt`` injects a preemption event at a supervision tick.

Supervisor state (worker states, restart counts, exit codes) is
persisted to ``<state_dir>/fleet_state.json`` through
``robustness/artifacts.atomic_write`` after every transition, so an
operator (or fsck) reading mid-crash never sees torn JSON. Control-plane
records ride the same file: :meth:`WorkerSupervisor.set_extra_state`
merges e.g. the autoscaler's target and the router's version weights
into the payload, and a restarted supervisor recovers them (plus reaps
any still-alive workers the dead supervisor left behind) via
:func:`load_persisted_state` before spawning its own fleet — kill -9
mid-scale-event recovers to a consistent fleet.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from http.server import ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs.heartbeat import HeartbeatStatus, read_heartbeat
from deepinteract_tpu.robustness import artifacts, faults
from deepinteract_tpu.robustness.retry import compute_delay

logger = logging.getLogger(__name__)

_RESTARTS = obs_metrics.counter(
    "di_fleet_worker_restarts_total",
    "Crashed workers respawned by the supervisor", labelnames=("worker",))
_SPAWN_FAILURES = obs_metrics.counter(
    "di_fleet_spawn_failures_total",
    "Worker spawn attempts that failed (retried with backoff)",
    labelnames=("worker",))
_PROBE_FAILURES = obs_metrics.counter(
    "di_fleet_probe_failures_total",
    "Health probes that errored or timed out", labelnames=("worker",))
_WEDGE_KILLS = obs_metrics.counter(
    "di_fleet_wedge_kills_total",
    "Live-but-wedged workers (stale heartbeat) SIGKILLed for restart",
    labelnames=("worker",))
_UP = obs_metrics.gauge(
    "di_fleet_worker_up", "1 while the worker process is alive and probed "
    "healthy", labelnames=("worker",))
_CIRCUIT = obs_metrics.gauge(
    "di_fleet_circuit_open",
    "1 while the worker's restart circuit breaker is open",
    labelnames=("worker",))
_WORKERS_TOTAL = obs_metrics.gauge(
    "di_fleet_workers_total", "Workers under supervision (not retired)")
_WORKERS_HEALTHY = obs_metrics.gauge(
    "di_fleet_workers_healthy", "Workers currently probed healthy")
_PREEMPTIONS = obs_metrics.counter(
    "di_fleet_preemptions_total",
    "Workers lost to preemption (expected capacity loss: no circuit "
    "penalty, immediate replacement)")
_ORPHANS_REAPED = obs_metrics.counter(
    "di_fleet_orphans_reaped_total",
    "Still-alive workers of a dead supervisor killed at startup")

# Retired worker records kept around for /stats & fleet_state.json
# visibility; older ones are GC'd so a long-lived fleet's daily
# rollovers cannot grow supervisor memory, gauge cardinality, and the
# state file without bound.
RETIRED_RETENTION = 8

# Worker command factory: (worker_id, port, heartbeat_path, overrides) ->
# argv. ``overrides`` carries rollover-time replacements (e.g. a new
# ``ckpt_name`` / target ``weights_signature``) interpreted by the
# factory, so the supervisor never needs to know a worker's flag surface.
CmdFn = Callable[[str, int, str, Dict[str, Any]], List[str]]


def fan_out(tasks: Dict[str, Callable[[], Any]],
            join_timeout_s: Optional[float] = None,
            name: str = "fanout") -> Dict[str, Any]:
    """Run named thunks concurrently (one thread each) and return the
    results of those that finished — the ONE fan-out the parallel
    drains, health probes, and the router's aggregation fetches share,
    so their join/timeout semantics cannot drift.

    ``join_timeout_s`` is a COLLECTIVE deadline (None = wait forever):
    each join consumes the remaining budget, so N hung thunks cost one
    timeout total, not N. Threads are daemon — a thunk wedged past the
    deadline (hung NFS stat, a worker dribbling bytes forever) is
    abandoned, its key absent from the result, and it can never block
    interpreter exit. Callers decide what a missing key means. The
    RETURNED dict is a post-join snapshot the worker threads never
    touch — a late completion writes into its own pre-created slot and
    can never resize a dict the caller is iterating."""
    _PENDING = object()
    slots: Dict[str, Any] = {key: _PENDING for key in tasks}
    threads = [threading.Thread(
        target=lambda k=key, thunk=fn: slots.__setitem__(k, thunk()),
        name=f"{name}-{key}", daemon=True) for key, fn in tasks.items()]
    for t in threads:
        t.start()
    deadline = (None if join_timeout_s is None
                else time.monotonic() + join_timeout_s)
    for t in threads:
        t.join(timeout=None if deadline is None
               else max(0.0, deadline - time.monotonic()))
    return {key: value for key, value in slots.items()
            if value is not _PENDING}


def watch_parent(parent_pid: int, on_orphan: Callable[[], None],
                 interval_s: float = 1.0) -> Optional[threading.Thread]:
    """Daemon thread firing ``on_orphan`` ONCE when ``parent_pid`` stops
    being this process's parent.

    A SIGKILLed (or otherwise hard-killed) supervisor cannot drain its
    workers — without this, they would keep serving as orphans forever,
    invisible to any router. Workers run it against the supervisor pid
    (``--parent_pid``, set by the worker command factories) and route
    the orphan event into their own drain path, so supervisor death
    degrades to the same clean exit a rollover drain produces. No-op
    (returns None) when ``parent_pid <= 0``."""
    if parent_pid <= 0:
        return None

    def _loop():
        while True:
            if os.getppid() != parent_pid:
                logger.error(
                    "parent %d is gone (ppid now %d): draining — an "
                    "orphaned worker must not serve forever",
                    parent_pid, os.getppid())
                try:
                    on_orphan()
                except Exception:  # noqa: BLE001 - watcher must not crash
                    logger.exception("orphan hook failed")
                return
            time.sleep(interval_s)

    thread = threading.Thread(target=_loop, name="parent-watch",
                              daemon=True)
    thread.start()
    return thread


def endpoint_label(path: str, routes: Sequence[str]) -> str:
    """Metric label for a request path: the matched route, else
    ``"other"`` — unknown client paths (scanners, typos) must not mint
    unbounded label series. Shared by the router and the worker stub
    (the real server has its own pre-fleet copy)."""
    route = path.partition("?")[0]
    return route if route in routes else "other"


class QuietHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer whose handler-thread errors go to debug
    logging instead of stderr tracebacks: routine client disconnects
    (a router abandoning a SIGKILLed sibling's keep-alive socket, a
    drain tearing idle connections) are not incidents. Shared by the
    router and the worker stub; real failures are answered as 4xx/5xx
    JSON by the handlers themselves."""

    def handle_error(self, request, client_address):  # noqa: N802
        logger.debug("connection error from %s", client_address,
                     exc_info=True)


def batch_slots(n_requests: int, max_batch: int,
                lift_to: int = 1) -> int:
    """Coalesced-group padding policy: next power of two, capped at
    ``max_batch``. ONE implementation shared by the engine's executable
    inventory (``InferenceEngine._batch_slots``) and the rollover
    readiness prefixes (``cli/serve.warm_bucket_prefixes``) — if these
    drifted, replacements would compile labels the router's warm check
    no longer matches and every rollover would abort on timeout.

    ``lift_to`` raises the floor (rounded up to a power of two): a
    data-parallel mesh worker lifts slots to its data-axis size so every
    chip holds at least one sample; the ``max_batch`` cap still wins —
    an operator's batch ceiling outranks shard occupancy (the engine
    then falls back to replicated execution for the indivisible group).
    """
    slots = 1 << (max(1, int(n_requests)) - 1).bit_length()
    floor = 1 << (max(1, int(lift_to)) - 1).bit_length()
    return min(max(slots, floor), max(1, int(max_batch)))


def parse_mesh_shape(spec) -> "tuple[int, int]":
    """``"DxP"`` (e.g. ``"4x1"``, ``"2x4"``) -> ``(data, pair)`` device
    counts. Accepts an already-parsed 2-tuple/list verbatim and ``None``
    / ``""`` as the single-device shape ``(1, 1)``. The ONE parser the
    engine config, CLI plumbing, router placement, and stub health
    payloads share, so a topology label can never mean two things."""
    if spec is None or spec == "":
        return (1, 1)
    if isinstance(spec, (tuple, list)):
        if len(spec) != 2:
            raise ValueError(f"mesh shape needs 2 axes, got {spec!r}")
        data, pair = int(spec[0]), int(spec[1])
    else:
        parts = str(spec).lower().split("x")
        if len(parts) != 2:
            raise ValueError(
                f"mesh shape must look like 'DATAxPAIR' (e.g. '4x1'), "
                f"got {spec!r}")
        try:
            data, pair = int(parts[0]), int(parts[1])
        except ValueError:
            raise ValueError(
                f"mesh shape must be two integers 'DATAxPAIR', got "
                f"{spec!r}") from None
    if data < 1 or pair < 1:
        raise ValueError(f"mesh axes must be >= 1, got {data}x{pair}")
    return (data, pair)


def mesh_label(shape) -> str:
    """Canonical ``"DxP"`` topology label for health payloads, compile
    inventory, and the fleet contract (``(1, 1)``/None -> ``"1x1"``)."""
    data, pair = parse_mesh_shape(shape)
    return f"{data}x{pair}"


def mesh_label_prefix(shape) -> str:
    """Compile-label prefix carrying the topology: ``""`` for the
    single-device shape (existing labels, warm prefixes, and rollover
    specs stay valid verbatim), ``"mesh<D>x<P>/"`` otherwise. A PREFIX,
    not a suffix, because the router's warm-readiness check is
    ``label.startswith(required)`` — a 1-chip replacement can never
    satisfy a mesh worker's warm proof, and vice versa."""
    data, pair = parse_mesh_shape(shape)
    if (data, pair) == (1, 1):
        return ""
    return f"mesh{data}x{pair}/"


def mesh_placement(shape, bucket1: int, bucket2: int,
                   pair_threshold: int) -> str:
    """Placement policy for one bucket on one worker topology:

    * ``"single"`` — no mesh (shape ``(1, 1)``): today's one-device AOT
      entries, byte-identical behavior.
    * ``"pair"`` — the mesh has a pair axis and the bucket's longer side
      reaches ``pair_threshold``: one huge complex row-shards across
      chips (latency scaling for p512+ antibody/spike-scale maps).
    * ``"data"`` — everything else on a mesh: batch slots shard over the
      data axis (throughput scaling for small-bucket traffic).

    Pure and jax-free so the engine, ``cli/serve.warm_bucket_prefixes``,
    and the router's topology-aware routing share ONE policy; the
    autotuner may override it per bucket (``TrialConfig.mesh_placement``).
    """
    data, pair = parse_mesh_shape(shape)
    if (data, pair) == (1, 1):
        return "single"
    if pair > 1 and pair_threshold > 0 and \
            max(int(bucket1), int(bucket2)) >= pair_threshold:
        return "pair"
    return "data"


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-0 probe). Racy in principle;
    in practice the child binds it within milliseconds, and a lost race
    surfaces as a spawn-then-crash the restart path already handles."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return int(s.getsockname()[1])


def request_json(host: str, port: int, method: str, path: str,
                 body: Optional[bytes] = None, timeout_s: float = 2.0):
    """One HTTP round trip returning ``(status, parsed_json_or_text)``.
    The ONE http.client block the supervisor probe, the router's
    aggregation fetches, and the rollover client share — transport
    errors propagate to the caller for classification."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        headers = {"Content-Type": "application/json"} if body else {}
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        text = resp.read().decode()
        ctype = resp.getheader("Content-Type", "")
        if ctype.startswith("application/json"):
            return resp.status, json.loads(text)
        return resp.status, text
    finally:
        conn.close()


def probe_healthz(host: str, port: int, timeout_s: float = 2.0) -> Dict:
    """One ``GET /healthz`` against a worker; raises on any transport or
    parse failure (the caller counts and classifies). ``fleet.probe`` is
    the chaos hook that makes a healthy worker look unreachable."""
    faults.maybe_raise(
        "fleet.probe",
        lambda: ConnectionError("injected fleet.probe fault"))
    status, payload = request_json(host, port, "GET", "/healthz",
                                   timeout_s=timeout_s)
    if status != 200:
        raise ConnectionError(f"/healthz answered {status}")
    if not isinstance(payload, dict):
        raise ConnectionError("/healthz payload is not an object")
    return payload


def stub_worker_cmd(worker_id: str, port: int, heartbeat_path: str,
                    overrides: Dict[str, Any]) -> List[str]:
    """Command factory for ``serving/worker_stub.py`` rehearsal workers
    (fleet chaos tests, ``cli/serve.py --fleet_stub_workers``, bench's
    ``rollover`` section). ``overrides`` keys map onto stub flags;
    ``ckpt_name`` aliases onto the stub's weights signature so rollover
    requests written against real workers rehearse unchanged."""
    cmd = [sys.executable, "-m", "deepinteract_tpu.serving.worker_stub",
           "--worker_id", worker_id, "--port", str(port),
           "--parent_pid", str(os.getpid())]
    if heartbeat_path:
        cmd += ["--heartbeat_file", heartbeat_path]
    # ckpt_name outranks a base weights_signature: a rollover that only
    # names the new checkpoint must repoint the stub's identity even
    # when the fleet was configured with a baseline signature.
    sig = overrides.get("ckpt_name") or overrides.get("weights_signature")
    if sig:
        cmd += ["--weights_signature", str(sig)]
    for key in ("warm_buckets", "delay_ms", "warm_after_s",
                "crash_after_s", "heartbeat_interval_s", "probs_value",
                "mesh_shape"):
        if key in overrides:
            cmd += [f"--{key}", str(overrides[key])]
    return cmd


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Supervision policy (CLI surface: ``cli/serve.py`` fleet flags)."""

    num_workers: int = 2
    # Monitor cadence + probe transport bound.
    probe_interval_s: float = 1.0
    probe_timeout_s: float = 2.0
    # Heartbeat staleness: past max_age the worker is unroutable; past
    # wedge_kill_factor * max_age with a LIVE process it is wedged (beat
    # thread or event loop stuck) and gets SIGKILLed into the restart
    # path. 0 disables heartbeat checks (probe-only supervision).
    heartbeat_max_age_s: float = 15.0
    wedge_kill_factor: float = 3.0
    # PR-1 exponential backoff between restart attempts.
    restart_backoff_s: float = 0.5
    restart_backoff_max_s: float = 30.0
    # Circuit breaker: more than this many restarts inside the window
    # stops the restart loop for that worker (operator action required).
    circuit_max_restarts: int = 5
    circuit_window_s: float = 60.0
    # A worker still not probing healthy this long after its spawn is
    # stuck BEFORE it could even start beating (deadlocked import,
    # wedged checkpoint mount): SIGKILL it into the restart path. Must
    # comfortably exceed a real worker's restore+AOT warmup; 0
    # disables.
    start_grace_s: float = 600.0
    # Heartbeats, per-worker logs, and fleet_state.json live here.
    state_dir: str = ""
    # SIGTERM-drain grace before the SIGKILL fallback at stop/retire.
    drain_timeout_s: float = 30.0


def load_persisted_state(state_path: str) -> Dict[str, Any]:
    """Tolerant read of a (possibly previous-life) ``fleet_state.json``:
    ``{}`` when missing or malformed — recovery must never crash on the
    state it is recovering from (``cli/fsck.py`` owns quarantine and
    reporting for malformed state)."""
    try:
        with open(state_path) as fh:
            state = json.load(fh)
    except (OSError, ValueError):
        return {}
    return state if isinstance(state, dict) else {}


def _pid_runs_worker(pid: int) -> bool:
    """True when ``/proc/<pid>/cmdline`` looks like one of OUR worker
    processes — the guard that makes startup orphan reaping safe against
    pid reuse. Conservative: an unreadable/absent cmdline (non-Linux,
    already-gone process) is False; the worker's own parent-watcher
    remains the self-draining fallback."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as fh:
            cmd = fh.read().replace(b"\x00", b" ").decode("utf-8",
                                                          "replace")
    except OSError:
        return False
    return "deepinteract_tpu" in cmd


class _Worker:
    """Mutable per-worker record. Every field is guarded by the owning
    supervisor's ``_lock``; the Popen handle itself is only ever driven
    (signal/wait) outside the lock via a snapshot reference."""

    def __init__(self, worker_id: str, port: int, heartbeat_path: str,
                 log_path: str, overrides: Dict[str, Any]):
        self.worker_id = worker_id
        self.port = port
        self.heartbeat_path = heartbeat_path
        self.log_path = log_path
        self.overrides = dict(overrides)
        self.proc: Optional[subprocess.Popen] = None
        # spawning -> starting -> healthy <-> unhealthy; dead ->
        # restarting -> spawning; circuit_open, draining, retired are
        # terminal-ish. Registered as "spawning" (not "starting"): the
        # monitor must not classify a worker whose FIRST Popen is still
        # in flight as dead and double-spawn it.
        self.state = "spawning"
        self.restarts = 0
        self.restart_times: deque = deque()
        self.backoff_attempt = 0
        self.next_restart_at = 0.0
        self.last_exit_code: Optional[int] = None
        self.last_error = ""
        self.health: Dict[str, Any] = {}
        self.heartbeat = "unknown"
        self.spawned_at = 0.0  # monotonic stamp of the last spawn

    def snapshot(self) -> Dict[str, Any]:
        return {
            "worker_id": self.worker_id,
            "port": self.port,
            "pid": self.proc.pid if self.proc is not None else None,
            "state": self.state,
            "restarts": self.restarts,
            "last_exit_code": self.last_exit_code,
            "last_error": self.last_error,
            "heartbeat": self.heartbeat,
            "health": dict(self.health),
            "log_path": self.log_path,
        }


class WorkerSupervisor:
    """Spawn/monitor/restart N worker processes (module docstring)."""

    def __init__(self, cmd_fn: CmdFn, cfg: FleetConfig = FleetConfig(),
                 host: str = "127.0.0.1",
                 overrides: Optional[Dict[str, Any]] = None):
        if cfg.num_workers < 0:
            raise ValueError(f"num_workers must be >= 0, got "
                             f"{cfg.num_workers}")
        self.cfg = cfg
        self.host = host
        self._cmd_fn = cmd_fn
        self._base_overrides = dict(overrides or {})
        # RLock so lookup helpers can guard their reads explicitly (a
        # verifiable no-cost re-entry under callers already holding it —
        # the scheduler's _take_ready_group discipline).
        self._lock = threading.RLock()
        self._workers: Dict[str, _Worker] = {}
        self._seq = 0
        self._started = False
        self._restarts_total = 0
        # Cumulative circuit trips: retirement (e.g. the shutdown
        # drain) clears a worker's OPEN state, but the final fleet/v1
        # contract must still report that supervision degraded during
        # the run — "ok" would otherwise be vacuously true at exit.
        self._circuit_tripped = 0
        # Expected capacity losses (preempt_worker / fleet.preempt):
        # counted separately from restarts because they carry no
        # circuit penalty and say nothing about worker health.
        self._preemptions = 0
        self._orphans_reaped = 0
        # Control-plane records (autoscaler target, version weights)
        # persisted alongside worker state; see set_extra_state.
        self._extras: Dict[str, Dict[str, Any]] = {}
        # Called (old_id, new_id) after a preempted worker's replacement
        # spawns, so a router can swap its routing slot in place.
        self.on_replacement: Optional[Callable[[str, str], None]] = None
        self._stop = threading.Event()
        self._persist_lock = threading.Lock()
        self._monitor: Optional[threading.Thread] = None
        # Absolute: worker paths (heartbeat, log) are handed to child
        # processes and must not depend on anyone's cwd.
        state_dir = os.path.abspath(cfg.state_dir or os.path.join(
            os.getcwd(), "fleet_state"))
        os.makedirs(state_dir, exist_ok=True)
        self.state_dir = state_dir
        self.state_path = os.path.join(state_dir, "fleet_state.json")
        # A previous supervisor life's persisted state, read BEFORE this
        # life writes anything: kill -9 recovery restores control-plane
        # extras (autoscale target, version weights) from here, and
        # start() reaps any of its workers still alive.
        self._recovered_state: Dict[str, Any] = load_persisted_state(
            self.state_path)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "WorkerSupervisor":
        """Spawn the initial fleet and the monitor. IDEMPOTENT: the
        router calls it defensively, and a caller that already started
        the supervisor must not get a second fleet."""
        with self._lock:
            spawn_initial = not self._started
            self._started = True
        if spawn_initial:
            self._reap_orphans()
            for _ in range(self.cfg.num_workers):
                self.spawn_worker(self._base_overrides)
        if self._monitor is None:
            self._stop.clear()
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="fleet-monitor", daemon=True)
            self._monitor.start()
        return self

    def stop(self, timeout_s: Optional[float] = None) -> Dict[str, Optional[int]]:
        """Drain every non-retired worker (SIGTERM -> wait -> SIGKILL
        fallback) and stop the monitor. Returns worker -> exit code."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._lock:
            ids = [w.worker_id for w in self._workers.values()
                   if w.state != "retired"]
        codes = self.drain_many(
            ids, timeout_s if timeout_s is not None
            else self.cfg.drain_timeout_s)
        self._persist_state()
        return codes

    def _reap_orphans(self) -> None:
        """Kill still-alive workers recorded by a PREVIOUS supervisor
        life in this state_dir. kill -9 of a supervisor cannot drain its
        children; each worker's parent-watcher self-drains eventually,
        but recovery must be deterministic and immediate — a restarted
        supervisor spawning a fresh fleet next to orphans would double
        capacity and fight over heartbeat files. Guarded by a /proc
        cmdline check so pid reuse cannot kill an innocent process."""
        with self._lock:
            prior = self._recovered_state
            own_pids = {w.proc.pid for w in self._workers.values()
                        if w.proc is not None}
        workers = prior.get("workers")
        if not isinstance(workers, dict):
            return
        for wid, snap in workers.items():
            if not isinstance(snap, dict):
                continue
            pid = snap.get("pid")
            if (not isinstance(pid, int) or pid <= 0 or pid in own_pids
                    or snap.get("state") == "retired"):
                continue
            if not _pid_runs_worker(pid):
                continue
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                continue
            with self._lock:
                self._orphans_reaped += 1
            _ORPHANS_REAPED.inc()
            logger.warning(
                "fleet: reaped orphaned worker %s (pid %d) left by a "
                "previous supervisor", wid, pid)

    def recovered_state(self) -> Dict[str, Any]:
        """The previous supervisor life's persisted fleet_state.json as
        read at construction ({} on a fresh state_dir): the autoscaler
        and router restore their control-plane records from here after
        a kill -9 restart."""
        with self._lock:
            return dict(self._recovered_state)

    def set_extra_state(self, key: str, value: Dict[str, Any]) -> None:
        """Merge a control-plane record (autoscaler target, version
        weights/shadow config) into ``fleet_state.json`` under ``key``,
        persisted through the same atomic write as worker state — kill
        -9 recovery reads one consistent snapshot, never half of a
        scale event or promotion."""
        if key in ("workers", "updated_ts", "restarts_total",
                   "preemptions"):
            raise ValueError(f"extra-state key {key!r} shadows a core "
                             "fleet_state field")
        with self._lock:
            self._extras[key] = dict(value)
        self._persist_state()

    def extra_state(self, key: str) -> Dict[str, Any]:
        with self._lock:
            return dict(self._extras.get(key, {}))

    def drain_many(self, worker_ids: Sequence[str],
                   timeout_s: float) -> Dict[str, Optional[int]]:
        """Drain several workers IN PARALLEL (one thread each): N x
        drain_timeout_s sequential could outlive a preemption grace
        window or a rollover client's socket budget. The one drain
        fan-out stop(), rollover success, and rollover abort share."""
        return fan_out(
            {wid: (lambda w=wid: self.drain_worker(w, timeout_s))
             for wid in worker_ids}, name="drain")

    # -- spawning ----------------------------------------------------------

    def spawn_worker(self, overrides: Optional[Dict[str, Any]] = None) -> str:
        """Create + spawn one new worker; returns its id. A failed spawn
        still registers the worker (state ``restarting``) so the monitor
        retries it with backoff instead of silently shrinking the
        fleet."""
        if self._stop.is_set():
            # A rollover (e.g. SIGHUP) racing shutdown must not spawn
            # workers AFTER stop()'s drain snapshot — they would run
            # unsupervised and undrained.
            raise RuntimeError("supervisor is stopping; refusing to "
                               "spawn new workers")
        with self._lock:
            self._seq += 1
            worker_id = f"w{self._seq}"
            port = free_port(self.host)
            w = _Worker(
                worker_id, port,
                heartbeat_path=os.path.join(
                    self.state_dir, f"heartbeat_{worker_id}.json"),
                log_path=os.path.join(self.state_dir, f"{worker_id}.log"),
                overrides={**self._base_overrides, **(overrides or {})})
            self._workers[worker_id] = w
        self._try_spawn(w, first=True)
        self._update_gauges()
        self._persist_state()
        return worker_id

    def spawn_replacements(self, n: int,
                           overrides: Optional[Dict[str, Any]] = None
                           ) -> List[str]:
        """Rollover entry: ``n`` fresh workers with override knobs (new
        checkpoint / target signature) layered over the fleet's base."""
        return [self.spawn_worker(overrides) for _ in range(n)]

    @staticmethod
    def _prune_restart_window(w: _Worker, now: float,
                              window_s: float) -> None:
        """Drop restart/spawn-attempt stamps older than the sliding
        circuit window (caller holds the lock). ONE implementation so
        the spawn-failure, respawn, and crash paths cannot drift."""
        while w.restart_times and now - w.restart_times[0] > window_s:
            w.restart_times.popleft()

    def _try_spawn(self, w: _Worker, first: bool = False) -> bool:
        """Spawn (or respawn) ``w``'s process. Popen runs OUTSIDE the
        lock (it forks); state transitions re-acquire it. EVERY
        pre-exec step runs inside the failure handling: an exception
        that escaped here would strand the worker in state "spawning",
        which nothing retries."""
        if self._stop.is_set():
            with self._lock:
                w.state = "restarting"  # shutdown drain will retire it
            return False
        try:
            if not first:
                # Fresh port per respawn: the old port may have been
                # taken while the worker sat in backoff (or the bind-0
                # race was lost), and retrying a doomed port would
                # convert a transient conflict into a circuit-open
                # worker. Everything downstream (endpoint(), probes)
                # reads w.port live.
                with self._lock:
                    w.port = free_port(self.host)
            cmd = self._cmd_fn(w.worker_id, w.port, w.heartbeat_path,
                               w.overrides)
            # The PREVIOUS incarnation's heartbeat must not outlive it:
            # a real engine worker beats only after checkpoint restore
            # + AOT warmup, and a leftover stale file would read as
            # "wedged" during that window — the wedge-killer would
            # SIGKILL every warming respawn until the circuit opened.
            try:
                os.unlink(w.heartbeat_path)
            except OSError:
                pass
            faults.maybe_raise(
                "fleet.spawn",
                lambda: OSError("injected fleet.spawn fault"))
            # Streaming child log, append-only and regenerable — the
            # integrity-sidecar regime is for state, not stdout.
            log = open(w.log_path, "ab")  # di: allow[artifact-write] streaming child-process log (append-only, regenerable)
            try:
                # cwd is INHERITED: the worker argv may carry relative
                # paths (--ckpt_name checkpoints/run1) that must resolve
                # exactly as they would for the operator's own process.
                proc = subprocess.Popen(
                    cmd, stdout=log, stderr=subprocess.STDOUT)
            finally:
                log.close()
        except Exception as exc:  # noqa: BLE001 - any pre-exec failure
            _SPAWN_FAILURES.inc(worker=w.worker_id)
            with self._lock:
                w.last_error = f"spawn failed: {exc}"
                # Failed spawn ATTEMPTS count toward the circuit like
                # successful respawns do: a persistently unspawnable
                # worker (missing binary, unopenable log path) must trip
                # the breaker, not spawn-retry forever while the fleet
                # contract reports ok.
                now = time.monotonic()
                w.restart_times.append(now)
                self._prune_restart_window(w, now,
                                           self.cfg.circuit_window_s)
                if (not first and len(w.restart_times)
                        >= self.cfg.circuit_max_restarts):
                    w.state = "circuit_open"
                    self._circuit_tripped += 1
                    logger.error(
                        "fleet: %s failed %d spawn/restart attempts "
                        "inside %.0fs — circuit OPEN (inspect %s)",
                        w.worker_id, len(w.restart_times),
                        self.cfg.circuit_window_s, w.log_path)
                    return False
                w.state = "restarting"
                w.next_restart_at = now + compute_delay(
                    w.backoff_attempt, self.cfg.restart_backoff_s,
                    self.cfg.restart_backoff_max_s)
                w.backoff_attempt += 1
            logger.error("fleet: spawning %s failed (%s); retrying with "
                         "backoff", w.worker_id, exc)
            return False
        with self._lock:
            if w.state in ("draining", "retired"):
                # A concurrent stop/rollover-abort retired this worker
                # while Popen ran outside the lock: the fresh process
                # must not outlive the decision. Kill it unsupervised-
                # never.
                try:
                    proc.kill()
                except OSError:
                    pass
                logger.warning("fleet: %s was retired mid-spawn; killed "
                               "the fresh process", w.worker_id)
                return False
            w.proc = proc
            w.state = "starting"
            w.last_error = ""
            w.spawned_at = time.monotonic()
            if not first:
                w.restarts += 1
                self._restarts_total += 1
                now = time.monotonic()
                w.restart_times.append(now)
                self._prune_restart_window(w, now,
                                           self.cfg.circuit_window_s)
        if not first:
            _RESTARTS.inc(worker=w.worker_id)
            logger.warning("fleet: restarted %s (pid %d, restart #%d)",
                           w.worker_id, proc.pid, w.restarts)
        return True

    # -- monitoring --------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - monitor must survive
                logger.exception("fleet monitor tick failed")
            self._stop.wait(self.cfg.probe_interval_s)

    def poll_once(self) -> None:
        """One supervision tick: liveness, restarts, probes. Public (and
        re-entrant-safe) so the router's rollover warm-wait and the
        tests can drive supervision deterministically instead of
        sleeping against the monitor cadence."""
        now = time.monotonic()
        # Chaos: an injected preemption notice lands at a supervision
        # tick — the newest routable worker is preempted, exactly like
        # a spot-capacity reclaim arriving out of band.
        if faults.fire("fleet.preempt"):
            victims = self.routable_workers()
            if victims:
                self.preempt_worker(victims[-1]["worker_id"])
        with self._lock:
            workers = [w for w in self._workers.values()
                       if w.state not in ("retired",)]
        changed = False
        to_probe: List[_Worker] = []
        for w in workers:
            with self._lock:
                proc, state = w.proc, w.state
            if state == "draining":
                continue
            rc = proc.poll() if proc is not None else None
            if proc is None or rc is not None:
                changed |= self._handle_down(w, rc, now)
                continue
            if state == "preempted":
                # Alive and draining itself after the preemption
                # SIGTERM: keep watching for the exit, but never
                # probe-reclassify it back to healthy/unhealthy.
                continue
            to_probe.append(w)
        # Probes run CONCURRENTLY: one black-holed worker burning its
        # full probe_timeout_s must not delay crash detection for the
        # rest of the fleet (nor serialize the rollover warm-wait,
        # which ticks this method in a tight loop).
        if len(to_probe) == 1:
            changed |= self._probe(to_probe[0])
        elif to_probe:
            results = fan_out(
                {w.worker_id: (lambda ww=w: self._probe(ww))
                 for w in to_probe},
                join_timeout_s=self.cfg.probe_timeout_s + 2.0,
                name="probe")
            changed |= any(results.values())
        if changed:
            self._persist_state()
        self._update_gauges()

    def _handle_down(self, w: _Worker, rc: Optional[int],
                     now: float) -> bool:
        """``w``'s process is gone (or never spawned). Classify, maybe
        trip the circuit, maybe respawn."""
        respawn = False
        replacement_overrides: Optional[Dict[str, Any]] = None
        with self._lock:
            if w.state == "preempted":
                # EXPECTED capacity loss: retire without a circuit
                # penalty (no restart_times entry, no backoff) and
                # replace immediately — preemption says nothing about
                # worker health, and the capacity is wanted back now.
                w.last_exit_code = rc
                w.state = "retired"
                w.last_error = "preempted (expected capacity loss)"
                self._preemptions += 1
                replacement_overrides = dict(w.overrides)
                self._gc_retired_locked()
        if replacement_overrides is not None:
            _PREEMPTIONS.inc()
            logger.warning(
                "fleet: preempted worker %s exited (rc=%s) — spawning "
                "replacement immediately", w.worker_id, rc)
            if not self._stop.is_set():
                try:
                    new_id = self.spawn_worker(replacement_overrides)
                except RuntimeError:
                    pass  # stop() raced the respawn; drain owns cleanup
                else:
                    if self.on_replacement is not None:
                        try:
                            self.on_replacement(w.worker_id, new_id)
                        except Exception:  # noqa: BLE001 - observer hook
                            logger.exception(
                                "fleet: on_replacement hook failed")
            return True
        with self._lock:
            if w.state in ("circuit_open", "spawning", "draining",
                           "retired"):
                # draining/retired re-checked UNDER the lock: poll_once
                # snapshots states before its per-worker work, and a
                # drain landing in between must not be re-read as an
                # unexpected death (which would respawn a worker someone
                # just retired).
                return False
            if w.state not in ("dead", "restarting"):
                w.last_exit_code = rc
                w.state = "dead"
                w.last_error = f"process exited rc={rc}"
                logger.error("fleet: worker %s died (rc=%s)",
                             w.worker_id, rc)
                # Prune at CHECK time, not only at respawn time: a
                # worker that flapped hours ago and then served
                # healthily must not trip the circuit on its next
                # ordinary crash — the window is a sliding one.
                self._prune_restart_window(w, now,
                                           self.cfg.circuit_window_s)
                if len(w.restart_times) >= self.cfg.circuit_max_restarts:
                    w.state = "circuit_open"
                    self._circuit_tripped += 1
                    logger.error(
                        "fleet: %s restarted %d times inside %.0fs — "
                        "circuit OPEN, no further restarts (inspect %s)",
                        w.worker_id, len(w.restart_times),
                        self.cfg.circuit_window_s, w.log_path)
                    return True
                w.next_restart_at = now + compute_delay(
                    w.backoff_attempt, self.cfg.restart_backoff_s,
                    self.cfg.restart_backoff_max_s)
                w.backoff_attempt += 1
                w.state = "restarting"
                return True
            if w.state == "restarting" and now >= w.next_restart_at:
                # Claim the respawn while holding the lock: poll_once
                # runs on the monitor thread AND from a rollover's
                # warm-wait, and a doubly-spawned worker would leak a
                # process nothing supervises.
                w.state = "spawning"
                respawn = True
        if respawn:
            self._try_spawn(w)
            return True
        return False

    def _probe(self, w: _Worker) -> bool:
        """Health-probe a live worker: /healthz + heartbeat freshness.
        Network I/O runs outside the lock."""
        hb: Optional[HeartbeatStatus] = None
        if w.heartbeat_path and self.cfg.heartbeat_max_age_s > 0:
            hb = read_heartbeat(w.heartbeat_path,
                                self.cfg.heartbeat_max_age_s)
        try:
            health = probe_healthz(self.host, w.port,
                                   timeout_s=self.cfg.probe_timeout_s)
            probe_error = ""
        except Exception as exc:  # noqa: BLE001 - classified below
            health = None
            probe_error = str(exc)
            _PROBE_FAILURES.inc(worker=w.worker_id)
        wedged = (hb is not None and hb.status == "stale"
                  and hb.age_s is not None
                  and hb.age_s > self.cfg.heartbeat_max_age_s
                  * self.cfg.wedge_kill_factor)
        with self._lock:
            spawned_at, state_now = w.spawned_at, w.state
        beating = hb is not None and hb.status == "fresh"
        if (not wedged and not beating and self.cfg.start_grace_s > 0
                and state_now in ("starting", "unhealthy")
                and health is None and spawned_at > 0
                and time.monotonic() - spawned_at
                > self.cfg.start_grace_s):
            # "not beating": a fresh heartbeat proves the process is
            # alive and making progress (a slow warmup legitimately
            # exceeds any fixed grace — engine workers beat BEFORE
            # restore starts); the grace kill is for workers that hung
            # before they could even start the beat thread.
            # Never-came-up wedge: alive past the whole start grace but
            # still unprobeable AND (possibly) never wrote a heartbeat
            # — the stale-beat detector can't see a worker that hung
            # before its first beat, so the grace bound catches it.
            wedged = True
            logger.error(
                "fleet: %s still not healthy %.0fs after spawn "
                "(unprobeable) — SIGKILL for restart", w.worker_id,
                time.monotonic() - spawned_at)
        changed = False
        with self._lock:
            if w.state in ("draining", "retired", "preempted"):
                # A drain (or preemption notice) won the race against
                # this probe's network I/O: a stale success must not
                # resurrect a retired worker (the next tick would
                # respawn it with the OLD weights).
                return False
            prev = w.state
            w.heartbeat = hb.status if hb is not None else "disabled"
            if health is not None:
                w.health = health
                stale = hb is not None and hb.status == "stale"
                routable = health.get("status") in ("ok", "overloaded")
                w.state = ("healthy" if routable and not stale
                           else "unhealthy" if stale else "starting"
                           if health.get("status") == "warming"
                           else "unhealthy")
                if w.state == "healthy":
                    w.backoff_attempt = 0
                    w.last_error = ""
                elif stale:
                    w.last_error = (f"heartbeat stale "
                                    f"({hb.age_s:.1f}s old)")
            else:
                w.last_error = f"probe failed: {probe_error}"
                if w.state == "healthy":
                    w.state = "unhealthy"
            changed = w.state != prev
        if wedged:
            _WEDGE_KILLS.inc(worker=w.worker_id)
            logger.error(
                "fleet: %s is live but wedged (heartbeat %s) — SIGKILL "
                "for restart", w.worker_id,
                f"{hb.age_s:.1f}s stale"
                if hb is not None and hb.age_s is not None
                else "never written")
            self._signal(w, signal.SIGKILL)
            changed = True
        return changed

    # -- stopping / retiring ----------------------------------------------

    def _signal(self, w: _Worker, sig: int) -> bool:
        """Deliver ``sig`` to ``w``'s process. ``fleet.kill`` is the
        chaos hook for a failed delivery (e.g. a PID namespace surprise)
        — callers must keep a fallback path."""
        with self._lock:
            proc = w.proc
        if proc is None or proc.poll() is not None:
            return False
        try:
            faults.maybe_raise(
                "fleet.kill", lambda: OSError("injected fleet.kill fault"))
            proc.send_signal(sig)
            return True
        except OSError as exc:
            with self._lock:
                w.last_error = f"signal {sig} failed: {exc}"
            logger.error("fleet: signalling %s with %s failed: %s",
                         w.worker_id, sig, exc)
            return False

    def drain_worker(self, worker_id: str,
                     timeout_s: float = 30.0) -> Optional[int]:
        """SIGTERM-drain a worker (its own PR-1 drain path finishes
        in-flight work and exits 0), SIGKILL past the grace, retire it
        either way. Returns the exit code (None if it never ran)."""
        w = self._get(worker_id)
        with self._lock:
            w.state = "draining"
            proc = w.proc
        self._persist_state()
        rc: Optional[int] = None
        if proc is not None:
            terminated = self._signal(w, signal.SIGTERM)
            try:
                rc = proc.wait(timeout=timeout_s if terminated else 0.5)
            except subprocess.TimeoutExpired:
                logger.error("fleet: %s ignored SIGTERM for %.0fs — "
                             "SIGKILL", worker_id, timeout_s)
                try:
                    proc.kill()
                except OSError:
                    pass
                try:
                    rc = proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    rc = None
            if rc is None and terminated is False:
                # SIGTERM delivery itself failed (fleet.kill chaos):
                # fall back to SIGKILL so retire is unconditional.
                try:
                    proc.kill()
                    rc = proc.wait(timeout=5.0)
                except (OSError, subprocess.TimeoutExpired):
                    rc = None
        with self._lock:
            w.last_exit_code = rc
            w.state = "retired"
            self._gc_retired_locked()
        self._update_gauges()
        self._persist_state()
        return rc

    def _gc_retired_locked(self) -> None:
        """Drop the oldest retired records beyond RETIRED_RETENTION
        (registration order approximates retirement order well enough
        for a debugging window), INCLUDING their per-worker metric
        series — without this, daily rollovers would grow the scrape
        with dead worker labels forever."""
        with self._lock:  # re-entrant: callers already hold it
            retired = [w.worker_id for w in self._workers.values()
                       if w.state == "retired"]
            dropped = retired[:max(0, len(retired) - RETIRED_RETENTION)]
            for worker_id in dropped:
                del self._workers[worker_id]
        for worker_id in dropped:
            for family in (_UP, _CIRCUIT, _RESTARTS, _SPAWN_FAILURES,
                           _PROBE_FAILURES, _WEDGE_KILLS):
                family.remove(worker=worker_id)

    def preempt_worker(self, worker_id: str) -> bool:
        """Deliver a preemption notice: mark the worker ``preempted``
        (immediately unroutable — ``routable_workers`` only returns
        ``healthy``) and SIGTERM it so its own drain path finishes
        in-flight work. When the process exits, :meth:`_handle_down`
        retires it with NO circuit penalty and spawns a replacement
        immediately. Returns False when the worker is already on its
        way out (draining/retired/preempted/circuit_open)."""
        w = self._get(worker_id)
        with self._lock:
            if w.state in ("retired", "draining", "preempted",
                           "circuit_open"):
                return False
            w.state = "preempted"
            w.last_error = "preemption notice"
        logger.warning("fleet: %s preempted — SIGTERM sent, replacement "
                       "spawns on exit", worker_id)
        self._persist_state()
        self._update_gauges()
        if not self._signal(w, signal.SIGTERM):
            # Delivery failed (fleet.kill chaos / pid surprise): SIGKILL
            # so the preempted worker cannot linger half-forgotten — the
            # replacement path only triggers on its exit.
            with self._lock:
                proc = w.proc
            if proc is not None and proc.poll() is None:
                try:
                    proc.kill()
                except OSError:
                    pass
        return True

    def kill_worker(self, worker_id: str) -> None:
        """SIGKILL (chaos / operator hammer); the monitor's normal
        crash-restart path picks up the corpse."""
        self._signal(self._get(worker_id), signal.SIGKILL)

    # -- queries -----------------------------------------------------------

    def _get(self, worker_id: str) -> _Worker:
        with self._lock:
            return self._get_locked(worker_id)

    def _get_locked(self, worker_id: str) -> _Worker:
        with self._lock:  # re-entrant: callers already hold it
            try:
                return self._workers[worker_id]
            except KeyError:
                raise KeyError(f"unknown worker {worker_id!r}") from None

    def worker_info(self, worker_id: str) -> Dict[str, Any]:
        with self._lock:
            return self._get_locked(worker_id).snapshot()

    def worker_infos(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [w.snapshot() for w in self._workers.values()]

    def routable_workers(self) -> List[Dict[str, Any]]:
        """Snapshot of workers a router may send requests to right now."""
        with self._lock:
            return [w.snapshot() for w in self._workers.values()
                    if w.state == "healthy"]

    def endpoint(self, worker_id: str) -> Sequence:
        w = self._get(worker_id)
        return self.host, w.port

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            states: Dict[str, int] = {}
            for w in self._workers.values():
                states[w.state] = states.get(w.state, 0) + 1
            return {
                "workers": {w.worker_id: w.snapshot()
                            for w in self._workers.values()},
                "states": states,
                "restarts_total": self._restarts_total,
                "circuit_open": states.get("circuit_open", 0),
                "circuit_tripped_total": self._circuit_tripped,
                "preemptions": self._preemptions,
                "orphans_reaped": self._orphans_reaped,
                "state_path": self.state_path,
            }

    # -- persistence / gauges ---------------------------------------------

    def _persist_state(self) -> None:
        with self._lock:
            state = {
                "updated_ts": time.time(),
                "restarts_total": self._restarts_total,
                "preemptions": self._preemptions,
                "workers": {w.worker_id: w.snapshot()
                            for w in self._workers.values()},
            }
            state.update({key: dict(value)
                          for key, value in self._extras.items()})
        # Serialized: atomic_write's tmp name is pid-based, so two
        # threads persisting concurrently (monitor tick + a drain
        # thread) would collide on the same tmp file.
        with self._persist_lock:
            try:
                artifacts.atomic_write(self.state_path,
                                       json.dumps(state, sort_keys=True),
                                       fsync=False)
            except OSError as exc:
                # A full disk must not take down supervision itself.
                logger.error("fleet: persisting %s failed: %s",
                             self.state_path, exc)

    def _update_gauges(self) -> None:
        with self._lock:
            states = [(w.worker_id, w.state)
                      for w in self._workers.values()]
        healthy = 0
        active = 0
        for worker_id, state in states:
            _UP.set(1.0 if state == "healthy" else 0.0, worker=worker_id)
            _CIRCUIT.set(1.0 if state == "circuit_open" else 0.0,
                         worker=worker_id)
            healthy += state == "healthy"
            active += state not in ("retired",)
        _WORKERS_TOTAL.set(float(active))
        _WORKERS_HEALTHY.set(float(healthy))
