"""Null-engine fleet worker: the serving wire contract without a model.

The fleet layer (``serving/fleet.py`` + ``serving/router.py``) is
deliberately model-agnostic — it supervises *processes* that speak the
worker protocol: ``GET /healthz`` (with ``weights_signature`` +
``warm_buckets``), ``GET /stats``, ``GET /metrics``, ``POST /predict``,
a periodic ``obs/heartbeat.py`` liveness file, and SIGTERM
drain-then-exit-0. This module is that protocol with the engine swapped
for a configurable ``time.sleep`` — a worker that starts in ~a second
instead of paying checkpoint restore + AOT compiles, so

* the chaos suite (tests/test_fleet.py) can kill -9 / flap / roll over
  a real multi-process fleet inside the fast tier, and
* the bench ``rollover`` section can measure the FLEET LAYER's latency
  disruption during a live rollover (routing swap, drain, failover
  retries) isolated from model-execution noise — the quantity the
  zero-downtime contract is actually about.

Production workers are ``cli/serve.py`` processes (the supervisor builds
their command line); this stub is the rehearsal double, kept in the
package because bench and operator game-days use it, not only tests.
Everything is stdlib + the obs/robustness layers — no jax import.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Dict, List, Optional

from deepinteract_tpu.obs import expfmt
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs.heartbeat import Heartbeat

logger = logging.getLogger(__name__)

# The same request-count series the real server records, so the router's
# per-worker relabeled aggregation has the familiar families to carry.
_REQUESTS = obs_metrics.counter(
    "di_serving_requests_total", "HTTP requests answered",
    labelnames=("endpoint", "status"))


class StubWorker:
    """One fake engine worker. ``warm_after_s`` simulates the AOT warmup
    window (healthz reports ``status: "warming"`` and an empty
    ``warm_buckets`` until it passes); ``delay_ms`` is the simulated
    device latency per predict; ``crash_after_s`` hard-exits the process
    (os._exit(3)) for supervisor-restart chaos."""

    def __init__(self, worker_id: str, weights_signature: str,
                 warm_buckets: List[str], delay_ms: float,
                 warm_after_s: float, host: str = "127.0.0.1",
                 port: int = 0, probs_value: float = 0.5,
                 mesh_shape: str = "1x1"):
        self.worker_id = worker_id
        self.weights_signature = weights_signature
        # Advertised topology label ("DxP"): a stub never owns devices,
        # but the router's topology-aware placement and rollover warm
        # proofs key on /healthz mesh_shape — this makes them
        # stub-fleet-testable without jax.
        self.mesh_shape = str(mesh_shape or "1x1")
        self.configured_buckets = list(warm_buckets)
        self.delay_s = max(0.0, float(delay_ms)) / 1e3
        # The single fake prediction value: two stubs with different
        # probs_value disagree deterministically — the shadow-traffic
        # agreement ledger's test knob.
        self.probs_value = float(probs_value)
        self._warm_at = time.monotonic() + max(0.0, float(warm_after_s))
        self._started = time.time()
        self._draining = threading.Event()
        self._inflight = 0
        self._served = 0
        self._lock = threading.Lock()
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
                logger.debug("stub http: " + fmt, *args)

            def _send_json(self, code: int, payload: Dict) -> None:
                from deepinteract_tpu.serving.fleet import endpoint_label

                body = json.dumps(payload).encode()
                _REQUESTS.inc(endpoint=endpoint_label(
                    self.path, ("/predict", "/screen", "/assembly",
                                "/healthz", "/stats", "/metrics")),
                    status=str(code))
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - stdlib name
                route = self.path.partition("?")[0]
                if route == "/healthz":
                    self._send_json(200, worker.healthz())
                elif route == "/stats":
                    self._send_json(200, worker.stats())
                elif route == "/metrics":
                    body = expfmt.render().encode()
                    _REQUESTS.inc(endpoint="/metrics", status="200")
                    self.send_response(200)
                    self.send_header("Content-Type", expfmt.CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send_json(404, {"error": f"no route {route}"})

            def do_POST(self):  # noqa: N802 - stdlib name
                route = self.path.partition("?")[0]
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if route not in ("/predict", "/screen", "/assembly"):
                    self._send_json(404, {"error": f"no route {route}"})
                    return
                # Claim the in-flight slot BEFORE the draining check:
                # checked-then-claimed would let drain() observe
                # inflight == 0 in the gap and tear this response.
                with worker._lock:
                    worker._inflight += 1
                if worker._draining.is_set():
                    # The 503 write ALSO stays inside the in-flight
                    # window (same invariant as the 200 path below):
                    # drain() must not shut the listener down while
                    # this response is mid-write.
                    try:
                        self._send_json(503,
                                        {"error": "server is draining"})
                    finally:
                        with worker._lock:
                            worker._inflight -= 1
                    return
                try:
                    # The RESPONSE WRITE stays inside the in-flight
                    # window: drain() waits for inflight == 0 before
                    # stopping the listener, and a request only stops
                    # being in flight once its bytes are on the wire —
                    # otherwise a drain racing the send tears the
                    # connection and the clean-drain contract breaks.
                    time.sleep(worker.delay_s)
                    if route == "/screen" and b'"index_path"' in body:
                        code, out = worker.indexed_screen(body)
                        self._send_json(code, out)
                        return
                    if route == "/assembly":
                        code, out = worker.assembly(body)
                        self._send_json(code, out)
                        return
                    self._send_json(200, {
                        "complex_name": "stub",
                        "n1": 1, "n2": 1, "bucket": [64, 64],
                        "cached": False, "coalesced": 1,
                        "latency_ms": worker.delay_s * 1e3,
                        "contact_probs": [[worker.probs_value]],
                        "worker_id": worker.worker_id,
                        "weights_signature": worker.weights_signature,
                    })
                finally:
                    with worker._lock:
                        worker._inflight -= 1
                        worker._served += 1

        from deepinteract_tpu.serving.fleet import QuietHTTPServer

        self.httpd = QuietHTTPServer((host, port), Handler)

    # -- protocol ----------------------------------------------------------

    @property
    def warm(self) -> bool:
        return time.monotonic() >= self._warm_at

    def indexed_screen(self, body: bytes):
        """Deterministic fake of the real server's indexed ``/screen``
        (ranked partners from a proteome index): reads ONLY the index
        manifest's partition table — no numpy, no shard bytes — and
        scores each chain as ``crc32(chain_id) % 10^4 / 10^4``. Two
        stubs given the same partitions answer identically, so the
        router's scatter/gather merge and SIGKILL failover are testable
        against real fleet processes in the fast tier."""
        import zlib

        try:
            payload = json.loads(body.decode())
            manifest_file = os.path.join(
                str(payload["index_path"]), "index_manifest.json")
            with open(manifest_file) as fh:
                manifest = json.load(fh)
        except (KeyError, ValueError, OSError) as exc:
            return 400, {"error": f"stub indexed screen: {exc}"}
        wanted = payload.get("partitions")
        query = str(payload.get("query", "stub-query"))
        ranked = []
        served = []
        for part in manifest.get("partitions", []):
            pid = part.get("partition_id")
            if wanted is not None and pid not in wanted:
                continue
            served.append(pid)
            for cid in part.get("chains", []):
                if cid == query:
                    continue
                score = (zlib.crc32(str(cid).encode()) % 10_000) / 10_000
                ranked.append({
                    "pair_id": f"{query}|{cid}",
                    "chain1": query, "chain2": cid,
                    "query": query, "partner": cid,
                    "score": score, "max_prob": score,
                    "prefilter_score": score,
                    "partition_id": pid, "top_k": 0,
                    "top_contacts": [],
                })
        ranked.sort(key=lambda r: (-r["score"], r["pair_id"]))
        top_m = int(payload.get("top_m", 0))
        survivors = ranked[:top_m] if top_m > 0 else ranked
        return 200, {
            "indexed": True,
            "query": query,
            "partitions_served": sorted(served),
            "candidates": len(ranked),
            "survivors": len(survivors),
            "pairs_decoded": len(survivors),
            "partial": False,
            "ranked": survivors,
            "worker_id": self.worker_id,
            "weights_signature": self.weights_signature,
        }

    def assembly(self, body: bytes):
        """Deterministic fake of the real server's ``POST /assembly``
        (k-chain complex scoring): takes the request's ``chains`` list
        verbatim (no file IO, no numpy), scores each i<j pair as
        ``crc32(pair_id) % 10^4 / 10^4``, and answers with the real
        route's shape — ranked records, interface graph, encode-once
        accounting (unique_encodes == k) — so the router's proxying of
        /assembly is testable against real fleet processes in the fast
        tier. Two stubs answer identically for the same chains."""
        import zlib

        try:
            payload = json.loads(body.decode())
        except ValueError as exc:
            return 400, {"error": f"stub assembly: {exc}"}
        ids = payload.get("chains") or ["stubA", "stubB"]
        if not isinstance(ids, list) or len(ids) < 2:
            return 400, {"error": "stub assembly: 'chains' must list "
                                  ">= 2 chain ids"}
        ids = [str(c) for c in ids]
        threshold = float(payload.get("edge_threshold", 0.5))
        ranked, edges = [], []
        for i in range(len(ids)):
            for j in range(i + 1, len(ids)):
                c1, c2 = sorted((ids[i], ids[j]))
                pid = f"{c1}|{c2}"
                score = (zlib.crc32(pid.encode()) % 10_000) / 10_000
                ranked.append({"pair_id": pid, "chain1": c1, "chain2": c2,
                               "score": score, "max_prob": score,
                               "top_k": 0, "top_contacts": []})
                if score >= threshold:
                    edges.append({"chain1": c1, "chain2": c2,
                                  "pair_id": pid, "score": score})
        ranked.sort(key=lambda r: (-r["score"], r["pair_id"]))
        return 200, {
            "ranked": ranked,
            "interface": {"nodes": ids, "edges": edges},
            "chains": len(ids),
            "pairs_total": len(ranked),
            "pairs_scored": len(ranked),
            "unique_encodes": len(ids),
            "encode_cache_hits": 0,
            "decode_batches": 1,
            "interface_edges": len(edges),
            "interactability": (sum(r["score"] for r in ranked)
                                / max(1, len(ranked))),
            "control_score": None,
            "calibrated": False,
            "calibration": None,
            "worker_id": self.worker_id,
            "weights_signature": self.weights_signature,
        }

    def healthz(self) -> Dict:
        warm = self.warm
        with self._lock:
            inflight = self._inflight
        return {
            "status": ("draining" if self._draining.is_set()
                       else "ok" if warm else "warming"),
            "draining": self._draining.is_set(),
            "degraded": False,
            "weights_signature": self.weights_signature,
            "mesh_shape": self.mesh_shape,
            "warm_buckets": list(self.configured_buckets) if warm else [],
            "worker_id": self.worker_id,
            # Queue-depth signal: the supervisor's probes cache this in
            # the worker snapshot, where the autoscaler reads it.
            "inflight": inflight,
        }

    def stats(self) -> Dict:
        with self._lock:
            inflight, served = self._inflight, self._served
        return {
            "worker_id": self.worker_id,
            "uptime_seconds": time.time() - self._started,
            "inflight": inflight,
            "served": served,
            "stub": True,
        }

    def drain(self) -> None:
        """SIGTERM path: refuse new predicts, let in-flight handler
        threads finish their sleep+response, stop the listener."""
        if self._draining.is_set():
            return
        self._draining.set()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.02)
        self.httpd.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--worker_id", default="stub")
    parser.add_argument("--weights_signature", default="stub-v1")
    parser.add_argument("--warm_buckets", default="64x64/b1",
                        help="comma list of compile-inventory labels "
                             "healthz reports once warm")
    parser.add_argument("--delay_ms", type=float, default=10.0)
    parser.add_argument("--mesh_shape", default="1x1",
                        help="advertised mesh topology label 'DxP' "
                             "(fake: rehearses topology-aware routing)")
    parser.add_argument("--probs_value", type=float, default=0.5,
                        help="the stub's constant contact probability — "
                             "distinct values make two versions disagree "
                             "deterministically (shadow-traffic tests)")
    parser.add_argument("--warm_after_s", type=float, default=0.0)
    parser.add_argument("--crash_after_s", type=float, default=0.0,
                        help="> 0: hard-exit (os._exit 3) after this many "
                             "seconds — the supervisor-restart chaos knob")
    parser.add_argument("--heartbeat_file", default="")
    parser.add_argument("--heartbeat_interval_s", type=float, default=0.5)
    parser.add_argument("--parent_pid", type=int, default=0,
                        help="drain and exit when this stops being our "
                             "parent (orphaned-worker protection; 0 "
                             "disables)")
    args = parser.parse_args(argv)

    worker = StubWorker(
        args.worker_id, args.weights_signature,
        [b for b in args.warm_buckets.split(",") if b.strip()],
        args.delay_ms, args.warm_after_s, host=args.host, port=args.port,
        probs_value=args.probs_value, mesh_shape=args.mesh_shape)
    hb = None
    if args.heartbeat_file:
        hb = Heartbeat(args.heartbeat_file,
                       interval_s=args.heartbeat_interval_s)
        hb.progress(worker_id=args.worker_id, role="stub-worker",
                    port=worker.httpd.server_address[1],
                    weights_signature=args.weights_signature)
        hb.start()

    signal.signal(signal.SIGTERM, lambda *_: threading.Thread(
        target=worker.drain, daemon=True).start())
    from deepinteract_tpu.serving.fleet import watch_parent

    watch_parent(args.parent_pid, worker.drain, interval_s=0.5)
    if args.crash_after_s > 0:
        def _crash():
            time.sleep(args.crash_after_s)
            os._exit(3)

        threading.Thread(target=_crash, daemon=True).start()

    logger.info("stub worker %s on %s:%d", args.worker_id,
                *worker.httpd.server_address[:2])
    try:
        worker.httpd.serve_forever(poll_interval=0.05)
    finally:
        worker.httpd.server_close()
        if hb is not None:
            hb.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
