"""Fleet HTTP front: health-checked routing, failover, warm rollover.

The router is the one address clients know. Behind it, a
:class:`~deepinteract_tpu.serving.fleet.WorkerSupervisor` keeps N
single-engine workers alive; the router:

* **routes** — ``POST /predict`` / ``POST /screen`` / ``POST
  /assembly`` are proxied to a healthy worker. Same-bucket requests stick to the same worker while
  the fleet is stable (an ``X-DI-Bucket`` hint is hashed onto the active
  list, so a bucket's compile cache and micro-batch coalescing stay
  warm on ONE worker) and fall back to round-robin without a hint. The
  answering worker is echoed in the ``X-DI-Worker`` response header.
* **fails over** — ``predict``/``screen`` are pure functions of the
  request, so when a worker dies mid-flight (connection refused/reset,
  torn response) or answers 503-draining, the SAME request is retried on
  a sibling — bounded by the PR-11 request deadline
  (``X-Request-Deadline-Ms`` forwarded with the REMAINING budget) and by
  one attempt per distinct healthy worker. Worker application errors
  (400/500 with an intact response) pass through untouched: the worker
  answered; re-asking a sibling would just re-execute a bad request.
* **aggregates** — ``GET /stats`` merges the supervisor's fleet view
  with every worker's own ``/stats``; ``GET /metrics`` renders the
  router's registry plus every live worker's exposition with a
  ``worker="wN"`` label injected into the ``di_*`` families (one merged
  family block per metric, so the scrape stays valid Prometheus text);
  ``GET /healthz`` is the fleet's liveness page.
* **rolls over** — ``POST /admin/rollover`` (or SIGHUP) performs a
  zero-downtime weights/config update: spawn replacement workers (with
  e.g. a new ``ckpt_name``), wait until each reports **warm** on
  ``/healthz`` (``status: ok``, ``warm_buckets`` covering the configured
  prefixes, ``weights_signature`` matching the target when one is
  given), atomically swap the routing table, then SIGTERM-drain the old
  workers through their own PR-1/PR-11 drain path. In-flight requests
  finish on the old workers; requests racing the swap fail over to the
  new ones; nothing is dropped and no client ever hits a cold compile.
  A replacement that never warms ABORTS the rollover (replacements are
  killed, the old fleet keeps serving) — rollover is all-or-nothing.
* **serves versions** — rollover's ``weights_signature`` plumbing
  generalizes from "replace the fleet" to "run several checkpoint
  versions concurrently". A request pins a version with the
  ``X-DI-Version`` header (or a ``version`` field in a JSON body) and
  is then routed — including every failover retry — ONLY within that
  version's workers; a pinned version with zero healthy workers answers
  503 + ``Retry-After``, never a silent cross-version fallback.
  Unpinned traffic is split by smooth weighted round-robin over the
  canary weights configured via ``POST /admin/versions``, which also
  arms **shadow traffic**: a sampled fraction of ``/predict`` requests
  is mirrored (off the critical path) to the candidate version, the
  outputs are compared, and every comparison is appended to a JSONL
  agreement ledger written atomically through
  ``robustness/artifacts.py``. ``POST /admin/promote`` shifts routing
  weight to the candidate ONLY when the measured agreement clears the
  configured bar (min samples + min agreement rate) and refuses — fleet
  untouched — otherwise. Version weights, shadow config, and promotion
  count persist through the supervisor's ``fleet_state.json`` so a
  kill -9 of the whole control plane drops no version pins.

The rollover response and the router's final stdout line (printed by
``cli/serve.py``) share the machine-readable ``fleet/v1`` contract
(``tools/check_cli_contract.py`` kind ``fleet``); ``/admin/versions``
answers the ``versions/v1`` contract.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import logging
import os
import re
import signal
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, List, Optional, Tuple

from deepinteract_tpu.obs import expfmt
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.robustness import artifacts
from deepinteract_tpu.robustness.preemption import PreemptionGuard
from deepinteract_tpu.serving.admission import Deadline
from deepinteract_tpu.serving.fleet import (
    QuietHTTPServer,
    WorkerSupervisor,
    endpoint_label,
    fan_out,
    parse_mesh_shape,
    request_json,
)

logger = logging.getLogger(__name__)


def _bucket_hint_dims(bucket_hint: Optional[str]) -> Optional[Tuple[int, int]]:
    """Parse an ``X-DI-Bucket`` hint ("N1xN2") into its bucket dims;
    None for absent/malformed hints — placement is best-effort, a bad
    header must never fail routing."""
    if not bucket_hint:
        return None
    parts = str(bucket_hint).lower().split("x")
    if len(parts) != 2:
        return None
    try:
        return int(parts[0]), int(parts[1])
    except ValueError:
        return None


def _advertises_pair_axis(health: Optional[Dict[str, Any]]) -> bool:
    """True when a worker's /healthz payload advertises a mesh with a
    pair axis (mesh_shape "DxP", P > 1) — the workers huge-complex
    requests prefer. Tolerant of pre-mesh workers (no field -> 1x1)."""
    try:
        return parse_mesh_shape((health or {}).get("mesh_shape"))[1] > 1
    except ValueError:
        return False

_ROUTED = obs_metrics.counter(
    "di_fleet_routed_total", "Requests answered through the router",
    labelnames=("endpoint", "status"))
_FAILOVERS = obs_metrics.counter(
    "di_fleet_failovers_total",
    "Requests retried on a sibling after a worker failed mid-flight",
    labelnames=("reason",))
_ROLLOVERS = obs_metrics.counter(
    "di_fleet_rollovers_total", "Warm rollovers", labelnames=("outcome",))
_VERSION_PICKS = obs_metrics.counter(
    "di_fleet_version_picks_total",
    "Requests assigned to a checkpoint version (pinned or canary split)",
    labelnames=("version", "mode"))
_SHADOW = obs_metrics.counter(
    "di_fleet_shadow_total",
    "Shadow-mirrored requests by comparison outcome",
    labelnames=("outcome",))
_INDEXED_FANOUTS = obs_metrics.counter(
    "di_fleet_indexed_screens_total",
    "Indexed /screen queries scatter/gathered across partition groups")
_PROMOTIONS = obs_metrics.counter(
    "di_fleet_promotions_total", "Version promotion attempts",
    labelnames=("outcome",))
_REQ_LATENCY = obs_metrics.histogram(
    "di_router_request_seconds",
    "Router-side end-to-end proxy latency, failovers included — the "
    "autoscaler's p99 signal")


class RolloverFailed(RuntimeError):
    """A rollover aborted (replacements never warmed / already rolling).
    The OLD fleet keeps serving — failure is never downtime."""


class RolloverBusy(RolloverFailed):
    """A rollover is already in progress (HTTP 409 — retry later). A
    TYPE, not a message substring, so rewording can't break the status
    mapping."""


class VersionError(ValueError):
    """Malformed ``/admin/versions`` / ``/admin/promote`` request
    (HTTP 400); the routing state is untouched."""


class PromotionRefused(RuntimeError):
    """A promotion did not clear the measured-agreement bar (HTTP 409).
    The fleet's routing weights are UNTOUCHED — a candidate earns
    traffic by evidence, not by asking twice."""

    def __init__(self, msg: str, stats: Optional[Dict[str, Any]] = None):
        super().__init__(msg)
        self.stats = dict(stats or {})


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Routing + rollover policy (CLI surface: ``cli/serve.py``)."""

    # Per-attempt proxy bound when the request carries no deadline.
    proxy_timeout_s: float = 120.0
    # Deadline applied when the client sends none (0 = none; then
    # proxy_timeout_s is the only bound) — mirrors the worker flag.
    default_deadline_ms: float = 0.0
    # Compile-inventory label prefixes a replacement must report in
    # /healthz warm_buckets before a rollover may switch to it
    # (e.g. ("128x128/",) from --warmup_buckets). Empty = status ok
    # (+ signature match) is warm enough.
    required_warm_buckets: Tuple[str, ...] = ()
    # Mesh topology label ("DxP") a replacement must advertise in
    # /healthz before a rollover may switch to it, and the fleet
    # contract's topology record. None = any topology (single-device
    # fleets, mixed rehearsals). With it set, warm_buckets prefixes are
    # already topology-prefixed (serving/fleet.mesh_label_prefix), so
    # the rollover warm proof is per-topology end to end.
    required_mesh_shape: Optional[str] = None
    # Bucket pad at/above which a request's X-DI-Bucket hint prefers
    # workers advertising a pair-axis mesh (mesh_shape "Dx P" with
    # P > 1): huge-complex requests route to pair-sharded workers
    # first, with the rest of the fleet as the failover tail. 0 = off.
    pair_bucket_threshold: int = 0
    # Bound on the replacement warm-up wait before a rollover aborts.
    warm_timeout_s: float = 300.0
    # SIGTERM-drain grace for the old workers after the routing swap.
    drain_timeout_s: float = 60.0
    # Short transport bound for /stats//metrics aggregation fetches.
    aggregate_timeout_s: float = 3.0


class FleetRouter:
    """Supervisor-backed HTTP front (module docstring)."""

    def __init__(self, supervisor: WorkerSupervisor,
                 host: str = "127.0.0.1", port: int = 0,
                 cfg: RouterConfig = RouterConfig()):
        self.sup = supervisor
        self.cfg = cfg
        self._draining = threading.Event()
        self._lock = threading.Lock()
        # Worker ids eligible for routing; swapped atomically by
        # rollover. Retired/unknown ids are filtered at pick time
        # against the supervisor's live states.
        self._active: List[str] = []
        self._rr = 0
        self._routed = 0
        self._failovers = 0
        self._rollovers = 0
        # One rollover at a time; a second request answers 409. The
        # separate _rollover_active flag (under _lock) is what /healthz
        # reports — probing the mutex itself from health() could make a
        # real rollover spuriously 409.
        self._rollover_lock = threading.Lock()
        self._rollover_active = False
        # Multi-version routing state (all under _lock). Empty weights =
        # legacy single-pool behaviour: every active worker is one pool.
        self._version_weights: Dict[str, float] = {}
        self._version_rr: Dict[str, float] = {}
        self._shadow: Optional[Dict[str, Any]] = None
        self._shadow_counter = 0
        self._shadow_samples = 0
        self._shadow_agree = 0
        self._shadow_ledger: List[Dict[str, Any]] = []
        self._promotions = 0
        # Preemption replacements carry a NEW worker id; the supervisor
        # tells us so the routing table swaps old->new in place.
        supervisor.on_replacement = self._on_replacement
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
                logger.debug("router http: " + fmt, *args)

            def _send_body(self, code: int, body: bytes, ctype: str,
                           extra: Optional[Dict[str, str]] = None) -> None:
                _ROUTED.inc(endpoint=endpoint_label(
                    self.path, ("/predict", "/screen", "/assembly",
                                "/healthz", "/stats", "/metrics",
                                "/admin/rollover", "/admin/versions",
                                "/admin/promote")),
                    status=str(code))
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for name, value in (extra or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, payload: Dict,
                           extra: Optional[Dict[str, str]] = None) -> None:
                self._send_body(code, json.dumps(payload).encode(),
                                "application/json", extra=extra)

            def do_GET(self):  # noqa: N802 - stdlib name
                route = self.path.partition("?")[0]
                if route == "/healthz":
                    self._send_json(200, router.health())
                elif route == "/stats":
                    self._send_json(200, router.stats())
                elif route == "/admin/versions":
                    self._send_json(200, router.versions_record())
                elif route == "/metrics":
                    self._send_body(200, router.metrics_text().encode(),
                                    expfmt.CONTENT_TYPE)
                else:
                    self._send_json(404, {"error": f"no route {route}"})

            def do_POST(self):  # noqa: N802 - stdlib name
                route = self.path.partition("?")[0]
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if route == "/admin/rollover":
                    self._do_rollover(body)
                    return
                if route == "/admin/versions":
                    self._do_versions(body)
                    return
                if route == "/admin/promote":
                    self._do_promote(body)
                    return
                if route not in ("/predict", "/screen", "/assembly"):
                    self._send_json(404, {"error": f"no route {route}"})
                    return
                if router._draining.is_set():
                    self._send_json(503, {"error": "router is draining"})
                    return
                try:
                    deadline = self._deadline()
                except ValueError as exc:
                    self._send_json(400, {"error": str(exc)})
                    return
                if (route == "/screen" and body
                        and b'"index_path"' in body
                        and b'"partitions"' not in body):
                    # Indexed screen: scatter partition groups across
                    # the fleet, gather + merge the rankings. A body
                    # that already scopes "partitions" is a sub-request
                    # (or a client wanting one worker) and proxies
                    # normally — no recursive fan-out.
                    status, out, headers = router.indexed_screen(
                        body, deadline=deadline,
                        version=self._version_pin(body))
                else:
                    status, out, headers = router.proxy(
                        "POST", self.path, body,
                        content_type=self.headers.get(
                            "Content-Type",
                            "application/octet-stream"),
                        bucket_hint=self.headers.get("X-DI-Bucket"),
                        deadline=deadline,
                        version=self._version_pin(body))
                self._send_body(status, out,
                                headers.pop("Content-Type",
                                            "application/json"),
                                extra=headers)

            def _version_pin(self, body: bytes) -> Optional[str]:
                """The request's pinned version: ``X-DI-Version`` header,
                else a ``version`` field in a JSON body. The body parse
                only runs when the raw bytes can contain the key, so
                unpinned hot-path requests never pay a JSON decode."""
                pin = self.headers.get("X-DI-Version")
                if pin is not None:
                    return pin
                if body and b'"version"' in body:
                    try:
                        payload = json.loads(body.decode())
                    except (ValueError, UnicodeDecodeError):
                        return None  # the worker answers 400 for itself
                    if isinstance(payload, dict) and \
                            payload.get("version") is not None:
                        return str(payload["version"])
                return None

            def _do_versions(self, body: bytes) -> None:
                try:
                    spec = json.loads(body.decode()) if body else {}
                    if not isinstance(spec, dict):
                        raise VersionError(
                            "versions body must be a JSON object")
                    record = router.set_versions(spec)
                except (VersionError, ValueError) as exc:
                    self._send_json(400, {"error": str(exc), "ok": False})
                    return
                self._send_json(200, record)

            def _do_promote(self, body: bytes) -> None:
                try:
                    spec = json.loads(body.decode()) if body else {}
                    if not isinstance(spec, dict):
                        raise VersionError(
                            "promote body must be a JSON object")
                    record = router.promote(spec)
                except PromotionRefused as exc:
                    self._send_json(409, {
                        **router.versions_record(), "ok": False,
                        "error": str(exc), "refused": exc.stats})
                    return
                except (VersionError, ValueError) as exc:
                    self._send_json(400, {"error": str(exc), "ok": False})
                    return
                self._send_json(200, record)

            def _deadline(self) -> Optional[Deadline]:
                hdr = self.headers.get("X-Request-Deadline-Ms")
                if hdr is not None:
                    ms = float(hdr)
                    if not ms > 0:
                        raise ValueError(
                            f"X-Request-Deadline-Ms must be > 0, got "
                            f"{hdr!r}")
                    return Deadline.after(ms / 1e3)
                if router.cfg.default_deadline_ms > 0:
                    return Deadline.after(
                        router.cfg.default_deadline_ms / 1e3)
                return None

            def _do_rollover(self, body: bytes) -> None:
                try:
                    overrides = json.loads(body.decode()) if body else {}
                    if not isinstance(overrides, dict):
                        raise ValueError(
                            "rollover body must be a JSON object")
                except ValueError as exc:
                    self._send_json(400, {"error": str(exc)})
                    return
                try:
                    record = router.rollover(overrides)
                except RolloverFailed as exc:
                    self._send_json(
                        409 if isinstance(exc, RolloverBusy) else 500,
                        {**router.final_contract(),
                         "error": str(exc), "ok": False})
                    return
                self._send_json(200, {**router.final_contract(),
                                      "rollover": record})

        self.httpd = QuietHTTPServer((host, port), Handler)
        self._serve_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self.httpd.server_address[:2]
        return str(host), int(port)

    def start(self) -> "FleetRouter":
        """Spawn the fleet (if not already started) and start accepting
        connections. The routing table adopts every current worker;
        routability is still gated per request on live health."""
        self.sup.start()
        with self._lock:
            if not self._active:
                self._active = [w["worker_id"]
                                for w in self.sup.worker_infos()
                                if w["state"] != "retired"]
        self._restore_versions()
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="fleet-router",
            daemon=True)
        self._serve_thread.start()
        return self

    def drain(self) -> None:
        """Stop accepting, stop the listener, drain every worker."""
        if self._draining.is_set():
            return
        self._draining.set()
        self.httpd.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10.0)
        self.httpd.server_close()
        self.sup.stop()

    def run(self, guard: Optional[PreemptionGuard] = None,
            poll_seconds: float = 0.25) -> int:
        """Blocking serve loop with the PR-1 preemption discipline, plus
        SIGHUP = warm rollover (the classic reload signal)."""
        own_guard = guard is None
        guard = guard or PreemptionGuard(log=logger.warning)
        if own_guard:
            guard.__enter__()
        self._install_sighup()
        try:
            host, port = self.address
            logger.info(
                "fleet router on http://%s:%d (POST /predict, POST "
                "/screen, POST /assembly, POST /admin/rollover, GET "
                "/healthz, GET /stats, GET /metrics; SIGHUP = rollover)",
                host, port)
            while not guard.requested:
                time.sleep(poll_seconds)
            logger.warning("drain requested (%s): stopping router and "
                           "draining %d worker(s)", guard.reason,
                           len(self.sup.worker_infos()))
        finally:
            self.drain()
            if own_guard:
                guard.__exit__(None, None, None)
        return 0

    def _install_sighup(self) -> None:
        def _on_hup(*_):
            def _roll():
                try:
                    self.rollover({})
                except RolloverFailed as exc:
                    logger.error("SIGHUP rollover failed: %s", exc)

            threading.Thread(target=_roll, name="sighup-rollover",
                             daemon=True).start()

        try:
            signal.signal(signal.SIGHUP, _on_hup)
        except (ValueError, AttributeError, OSError):
            # Not the main thread (tests) or no SIGHUP (platform):
            # /admin/rollover is the portable path.
            logger.debug("SIGHUP rollover handler not installed")

    # -- routing -----------------------------------------------------------

    def _pick_sequence(self, bucket_hint: Optional[str],
                       version: Optional[str] = None) -> List[str]:
        """Failover-ordered candidate workers: every routable worker at
        most once, starting from the bucket-affine (or round-robin)
        choice. A pinned ``version`` restricts candidates — and every
        failover retry — to that version's workers; zero healthy pinned
        workers yields an EMPTY sequence (the caller answers 503 +
        Retry-After), never a cross-version fallback. Unpinned requests
        under configured canary weights choose a version by smooth
        weighted round-robin and order its workers first; other
        versions' workers stay as the failover tail, so an unpinned
        request is never dropped while ANY version is healthy."""
        health_of = {w["worker_id"]: (w.get("health") or {})
                     for w in self.sup.routable_workers()}
        sig_of = {wid: str(health.get("weights_signature"))
                  for wid, health in health_of.items()}
        chosen: Optional[str] = None
        with self._lock:
            candidates = [wid for wid in self._active if wid in sig_of]
            if version is not None:
                candidates = [wid for wid in candidates
                              if sig_of[wid] == version]
            if not candidates:
                return []
            if bucket_hint:
                start = zlib.crc32(bucket_hint.encode()) % len(candidates)
            else:
                start = self._rr % len(candidates)
                self._rr += 1
            sequence = candidates[start:] + candidates[:start]
            if version is None and self._version_weights:
                chosen = self._choose_version_locked(
                    {sig_of[wid] for wid in candidates})
                if chosen is not None:
                    sequence = (
                        [w for w in sequence if sig_of[w] == chosen]
                        + [w for w in sequence if sig_of[w] != chosen])
            if self._wants_pair_worker(bucket_hint):
                # Topology-aware placement LAST (it outranks the version
                # ordering): a p512+ hint goes to pair-sharded workers
                # first — a data-parallel worker would decode the huge
                # map on one chip (models/tiled.py) at a latency the
                # pair path exists to beat. Stable within each group;
                # non-pair workers remain as the failover tail, so the
                # request still completes on a degraded fleet.
                pair_first = [w for w in sequence
                              if _advertises_pair_axis(health_of.get(w))]
                if pair_first:
                    sequence = pair_first + [w for w in sequence
                                             if w not in set(pair_first)]
        picked = version if version is not None else chosen
        if picked is not None:
            _VERSION_PICKS.inc(version=picked,
                               mode="pinned" if version else "weighted")
        return sequence

    def _wants_pair_worker(self, bucket_hint: Optional[str]) -> bool:
        """Placement trigger: the bucket hint's longer side reaches the
        configured pair threshold — the same over-threshold rule the
        engine's placement policy applies (serving/fleet.mesh_placement),
        read from the request side."""
        if self.cfg.pair_bucket_threshold <= 0:
            return False
        dims = _bucket_hint_dims(bucket_hint)
        return (dims is not None
                and max(dims) >= self.cfg.pair_bucket_threshold)

    def _choose_version_locked(self, available: set) -> Optional[str]:
        """Smooth weighted round-robin (the nginx algorithm) over the
        configured weights, restricted to versions that have a routable
        worker RIGHT NOW — a weighted-but-down version never swallows
        picks. Caller holds ``_lock``."""
        weights = {v: w for v, w in self._version_weights.items()  # di: allow[lock-discipline] caller holds _lock
                   if v in available and w > 0}
        if not weights:
            return None
        total = sum(weights.values())
        for v, w in weights.items():
            self._version_rr[v] = self._version_rr.get(v, 0.0) + w  # di: allow[lock-discipline] caller holds _lock
        best = max(sorted(weights), key=lambda v: self._version_rr[v])  # di: allow[lock-discipline] caller holds _lock
        self._version_rr[best] -= total  # di: allow[lock-discipline] caller holds _lock
        return best

    def proxy(self, method: str, path: str, body: bytes,
              content_type: str = "application/json",
              bucket_hint: Optional[str] = None,
              deadline: Optional[Deadline] = None,
              version: Optional[str] = None,
              ) -> Tuple[int, bytes, Dict[str, str]]:
        """Forward one idempotent request, failing over across siblings
        (within the pinned ``version``'s workers when one is given).
        Returns (status, body, response headers); observes the router
        latency histogram (the autoscaler's p99 signal) and mirrors a
        sampled fraction of successful unpinned ``/predict`` requests to
        the shadow candidate off the critical path."""
        t0 = time.monotonic()
        status, out, headers = self._route(
            method, path, body, content_type, bucket_hint, deadline,
            version)
        _REQ_LATENCY.observe(time.monotonic() - t0)
        if version is not None:
            headers.setdefault("X-DI-Version", version)
        elif status == 200:
            self._maybe_shadow(method, path, body, content_type, out)
        return status, out, headers

    def indexed_screen(self, body: bytes,
                       deadline: Optional[Deadline] = None,
                       version: Optional[str] = None,
                       ) -> Tuple[int, bytes, Dict[str, str]]:
        """Partition-affine scatter/gather for an indexed ``/screen``.

        The router reads the index manifest (partition table only — it
        never touches shard bytes), assigns every partition to a worker
        slot by ``crc32(partition_id) % n_workers`` — the SAME affinity
        hash ``_pick_sequence`` applies to the sub-request's
        ``bucket_hint``, so each worker owns a stable partition slice
        and its shard cache stays warm — and fans the sub-requests (the
        client body + a ``partitions`` scope) through :meth:`_route`,
        inheriting failover and version-pinning unchanged: a worker
        SIGKILL'd mid-query just moves its groups to siblings. Gather
        merges the per-group rankings by ``(-score, pair_id)``; groups
        that failed every retry mark the merged answer ``partial``
        rather than voiding the survivors that did come back."""
        try:
            payload = json.loads(body.decode())
            if not isinstance(payload, dict):
                raise ValueError("screen body must be a JSON object")
        except (ValueError, UnicodeDecodeError) as exc:
            return self._count(400, json.dumps(
                {"error": f"indexed screen body: {exc}"}).encode(), {})
        from deepinteract_tpu.index.format import read_manifest
        try:
            manifest = read_manifest(str(payload.get("index_path")))
        except (artifacts.ArtifactError, OSError, TypeError) as exc:
            return self._count(400, json.dumps(
                {"error": f"index: {exc}"}).encode(), {})
        pids = sorted(p["partition_id"] for p in manifest["partitions"])
        if not pids:
            return self._count(400, json.dumps(
                {"error": "index has no partitions"}).encode(), {})
        sequence = self._pick_sequence(None, version)
        if not sequence:
            return self._count(503, json.dumps({
                "error": "no healthy worker available for indexed "
                         "screen" + (f" (version {version!r})"
                                     if version else ""),
                "retry_after_s": 1.0,
            }).encode(), {"Retry-After": "1"})
        n = len(sequence)
        groups: Dict[int, List[str]] = {}
        for pid in pids:
            groups.setdefault(zlib.crc32(pid.encode()) % n,
                              []).append(pid)
        join_s = (deadline.remaining_s() + 1.0 if deadline is not None
                  else self.cfg.proxy_timeout_s + 1.0)
        tasks = {}
        for g in sorted(groups):
            sub = json.dumps({**payload,
                              "partitions": groups[g]}).encode()
            tasks[g] = (lambda b=sub, hint=groups[g][0]: self._route(
                "POST", "/screen", b, "application/json", hint,
                deadline, version))
        _INDEXED_FANOUTS.inc()
        results = fan_out(tasks, join_timeout_s=join_s,
                          name="indexed-screen")
        merged: List[Dict] = []
        served: List[str] = []
        failed: List[Dict] = []
        statuses: List[int] = []
        partial = False
        totals = {"candidates": 0, "survivors": 0, "pairs_decoded": 0}
        for g in sorted(groups):
            res = results.get(g)
            if res is None:
                failed.append({"partitions": groups[g],
                               "error": "fan-out timed out"})
                continue
            status, out, _ = res
            if status != 200:
                try:
                    err = json.loads(out.decode()).get("error", "")
                except (ValueError, UnicodeDecodeError):
                    err = out[:200].decode(errors="replace")
                failed.append({"partitions": groups[g],
                               "status": status, "error": err})
                statuses.append(status)
                continue
            try:
                sub_out = json.loads(out.decode())
            except (ValueError, UnicodeDecodeError):
                failed.append({"partitions": groups[g],
                               "error": "torn worker response"})
                continue
            merged.extend(sub_out.get("ranked", []))
            served.extend(sub_out.get("partitions_served", groups[g]))
            partial = partial or bool(sub_out.get("partial"))
            for key in totals:
                totals[key] += int(sub_out.get(key, 0))
        if failed and not merged and len(failed) == len(groups):
            status = (statuses[0] if statuses
                      and all(s == statuses[0] for s in statuses)
                      else 503)
            return self._count(status, json.dumps({
                "error": "indexed screen failed on every partition "
                         "group",
                "failed_groups": len(failed),
                "failed_detail": failed}).encode(), {})
        merged.sort(key=lambda r: (-float(r.get("score", 0.0)),
                                   str(r.get("pair_id", ""))))
        answer = {
            "indexed": True,
            "index_path": payload.get("index_path"),
            "query": payload.get("query"),
            "chains": int(manifest["num_chains"]),
            "partitions": len(pids),
            "partitions_served": sorted(served),
            "fanout_groups": len(groups),
            "failed_groups": len(failed),
            "failed_detail": failed,
            "partial": partial or bool(failed),
            "ranked": merged,
            **totals,
        }
        headers = {"X-DI-Fanout": str(len(groups))}
        if version is not None:
            headers["X-DI-Version"] = version
        return self._count(200, json.dumps(answer).encode(), headers)

    def _route(self, method: str, path: str, body: bytes,
               content_type: str, bucket_hint: Optional[str],
               deadline: Optional[Deadline], version: Optional[str],
               ) -> Tuple[int, bytes, Dict[str, str]]:
        """The failover loop behind :meth:`proxy`. After exhausting the
        candidate list, ONE re-pick: a request that raced a rollover's
        routing swap may have frozen the OLD (now-draining) workers as
        its candidates while warm replacements exist — the second pick
        reads the post-swap table, keeping the zero-dropped contract.
        When every candidate answered a worker-side 500 (a transient
        batch failure — 'safe to retry' per the PR-11 contract), the
        LAST such response is returned rather than a misleading
        no-healthy-worker 503."""
        attempts: List[str] = []
        last_500: List[Tuple[int, bytes, Dict[str, str]]] = []
        sequence = self._pick_sequence(bucket_hint, version)
        for round_no in (1, 2):
            if round_no == 2:
                refreshed = self._pick_sequence(bucket_hint, version)
                sequence = [wid for wid in refreshed
                            if wid not in attempts]
                if not sequence:
                    break
            status_out = self._proxy_round(
                sequence, attempts, method, path, body, content_type,
                deadline, last_500)
            if status_out is not None:
                return status_out
        if last_500:
            return self._count(*last_500[-1])
        retry_after = 1.0
        pool = ("no healthy worker available" if version is None
                else f"no healthy worker for version {version!r} "
                     "(pinned requests never fall back to another "
                     "version)")
        return self._count(503, json.dumps({
            "error": pool
                     + (f" (attempted {attempts})" if attempts else ""),
            "retry_after_s": retry_after,
        }).encode(), {"Retry-After": str(int(retry_after))})

    def _proxy_round(self, sequence: List[str], attempts: List[str],
                     method: str, path: str, body: bytes,
                     content_type: str, deadline: Optional[Deadline],
                     last_500: List) -> Optional[Tuple]:
        """One pass over ``sequence``; returns an answer tuple or None
        when every candidate failed over (worker-500 responses are
        stashed in ``last_500`` for the caller's fallback)."""
        for worker_id in sequence:
            if deadline is not None and deadline.expired:
                return self._count(504, json.dumps({
                    "error": "deadline expired while failing over",
                    "attempted_workers": attempts}).encode(), {})
            try:
                host, port = self.sup.endpoint(worker_id)
            except KeyError:
                continue
            timeout = self.cfg.proxy_timeout_s
            if deadline is not None:
                timeout = min(timeout, deadline.remaining_s() + 0.25)
            attempts.append(worker_id)
            try:
                status, out, headers = self._attempt(
                    host, port, method, path, body, content_type,
                    deadline, timeout)
            except Exception as exc:  # noqa: BLE001 - transport failover
                self._note_failover(worker_id, f"transport: {exc}",
                                    reason="transport")
                continue
            if status == 503:
                # Draining/shutting-down sibling: the work was refused,
                # not executed — the retry contract says "another
                # replica", and the router IS the other replica's door.
                self._note_failover(worker_id, "worker answered 503",
                                    reason="worker_draining")
                continue
            if status == 500:
                # A worker 500 is a transient batch failure
                # (BatchExecutionError — "safe to retry" in the PR-11
                # client contract) and predict/screen are pure: retry
                # on a sibling, keeping the response in case every
                # sibling fails the same way.
                headers["X-DI-Worker"] = worker_id
                last_500.append((status, out, headers))
                self._note_failover(worker_id, "worker answered 500",
                                    reason="worker_error")
                continue
            headers["X-DI-Worker"] = worker_id
            if len(attempts) > 1:
                headers["X-DI-Failovers"] = str(len(attempts) - 1)
            return self._count(status, out, headers)
        return None

    def _count(self, status: int, body: bytes,
               headers: Dict[str, str]) -> Tuple[int, bytes, Dict[str, str]]:
        with self._lock:
            self._routed += 1
        return status, body, headers

    def _note_failover(self, worker_id: str, detail: str,
                       reason: str) -> None:
        with self._lock:
            self._failovers += 1
        _FAILOVERS.inc(reason=reason)
        logger.warning("fleet: failing over off %s (%s)", worker_id,
                       detail)

    def _attempt(self, host: str, port: int, method: str, path: str,
                 body: bytes, content_type: str,
                 deadline: Optional[Deadline],
                 timeout: float) -> Tuple[int, bytes, Dict[str, str]]:
        conn = http.client.HTTPConnection(host, port,
                                          timeout=max(0.05, timeout))
        try:
            headers = {"Content-Type": content_type,
                       "Content-Length": str(len(body))}
            if deadline is not None:
                headers["X-Request-Deadline-Ms"] = str(
                    max(1.0, deadline.remaining_s() * 1e3))
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            out = resp.read()
            passthrough = {}
            for name in ("Retry-After", "Content-Type"):
                value = resp.getheader(name)
                if value is not None:
                    passthrough[name] = value
            return resp.status, out, passthrough
        finally:
            conn.close()

    # -- rollover ----------------------------------------------------------

    def rollover(self, overrides: Optional[Dict[str, Any]] = None) -> Dict:
        """Zero-downtime worker replacement (module docstring). Raises
        :class:`RolloverFailed` when replacements never warm (they are
        killed; the old fleet keeps serving) or when another rollover is
        already in progress."""
        overrides = dict(overrides or {})
        if not self._rollover_lock.acquire(blocking=False):
            raise RolloverBusy("a rollover is already in progress")
        with self._lock:
            self._rollover_active = True
        t0 = time.monotonic()
        try:
            target_sig = overrides.get("weights_signature")
            with self._lock:
                old = list(self._active)
            n = len(old) or max(1, self.sup.cfg.num_workers)
            new_ids: List[str] = []
            try:
                new_ids = self.sup.spawn_replacements(n, overrides)
                logger.info("rollover: spawned replacement(s) %s "
                            "(target signature: %s)", new_ids,
                            target_sig or "<any>")
                pending = set(new_ids)
                warm_deadline = (time.monotonic()
                                 + self.cfg.warm_timeout_s)
                # Warm-wait cadence: bounded below the monitor's own
                # interval but never a tight loop — real replacements
                # spend minutes compiling, and hammering /healthz 20x/s
                # fleet-wide would be pure overhead against workers
                # that are busy warming.
                wait_s = min(max(self.sup.cfg.probe_interval_s, 0.05),
                             0.25)
                while pending and time.monotonic() < warm_deadline:
                    self.sup.poll_once()
                    for wid in list(pending):
                        if self._is_warm(wid, target_sig):
                            pending.discard(wid)
                    if pending:
                        time.sleep(wait_s)
                if pending:
                    raise RolloverFailed(
                        f"replacement(s) {sorted(pending)} not warm "
                        f"after {self.cfg.warm_timeout_s:.0f}s — "
                        "rollover aborted, old fleet keeps serving")
            except BaseException as exc:
                # ANY failure before the swap aborts all-or-nothing:
                # already-spawned replacements must not linger under
                # supervision (each retried rollover would strand
                # another batch of new-weights workers).
                if new_ids:
                    self.sup.drain_many(new_ids, timeout_s=5.0)
                _ROLLOVERS.inc(outcome="failed")
                if isinstance(exc, RolloverFailed):
                    raise
                if not isinstance(exc, Exception):
                    # KeyboardInterrupt/SystemExit keep their type —
                    # cleanup done, but exit signals must not be
                    # laundered into an ordinary failed rollover.
                    raise
                raise RolloverFailed(
                    f"rollover failed before the routing swap: {exc!r} "
                    "— replacements cleaned up, old fleet keeps "
                    "serving") from exc
            # The atomic moment: new picks go to the replacements; old
            # workers only see requests already past _pick_sequence (and
            # those either finish during the drain below or fail over).
            with self._lock:
                self._active = list(new_ids)
                self._rollovers += 1
            _ROLLOVERS.inc(outcome="ok")
            # Parallel drains: N x drain_timeout_s sequential could
            # outlive the rollover client's socket timeout on a wide
            # fleet (supervisor drain_many is the shared fan-out).
            exit_codes = self.sup.drain_many(
                old, timeout_s=self.cfg.drain_timeout_s)
            record = {
                "ok": True,
                "old_workers": old,
                "new_workers": new_ids,
                "drain_exit_codes": exit_codes,
                "target_weights_signature": target_sig,
                "elapsed_s": round(time.monotonic() - t0, 3),
            }
            logger.info("rollover complete: %s", record)
            return record
        finally:
            with self._lock:
                self._rollover_active = False
            self._rollover_lock.release()

    def _is_warm(self, worker_id: str,
                 target_sig: Optional[str]) -> bool:
        try:
            info = self.sup.worker_info(worker_id)
        except KeyError:
            return False
        health = info.get("health") or {}
        if info["state"] != "healthy" or health.get("status") != "ok":
            return False
        if target_sig and health.get("weights_signature") != target_sig:
            return False
        if (self.cfg.required_mesh_shape
                and str(health.get("mesh_shape") or "1x1")
                != self.cfg.required_mesh_shape):
            # Wrong topology can never be warm: its compile inventory
            # belongs to a different device layout even if the label
            # prefixes happened to match.
            return False
        warm = health.get("warm_buckets") or []
        return all(any(str(label).startswith(req) for label in warm)
                   for req in self.cfg.required_warm_buckets)

    # -- multi-version serving ---------------------------------------------

    def adopt_worker(self, worker_id: str) -> None:
        """Add a (warm) worker to the routing table — the autoscaler's
        scale-up entry after its replacement finished warming."""
        with self._lock:
            if worker_id not in self._active:
                self._active.append(worker_id)

    def release_worker(self, worker_id: str) -> None:
        """Remove a worker from the routing table BEFORE draining it —
        new picks stop immediately; in-flight requests finish or fail
        over."""
        with self._lock:
            if worker_id in self._active:
                self._active.remove(worker_id)

    def _on_replacement(self, old_id: str, new_id: str) -> None:
        """Supervisor callback: a preempted worker's replacement swaps
        into the old worker's routing slot (same overrides, same
        version) — capacity recovers without operator action."""
        with self._lock:
            if old_id in self._active:
                self._active[self._active.index(old_id)] = new_id

    def request_p99_ms(self) -> float:
        """Router-side p99 latency in ms (0.0 before any request) — one
        of the autoscaler's inputs."""
        return _REQ_LATENCY.percentile(99) * 1e3

    def set_versions(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        """Apply a ``POST /admin/versions`` spec: ``weights`` (canary
        split, ``{signature: weight}``) and/or ``shadow`` (mirror
        config: ``candidate``, ``fraction``, optional ``tolerance`` /
        ``min_agreement`` / ``min_samples`` / ``ledger_path``; null
        disarms). Validates fully BEFORE touching state, persists
        through the supervisor's fleet_state.json, and returns the
        ``versions/v1`` record."""
        weights = None
        if spec.get("weights") is not None:
            weights = self._parse_weights(spec["weights"])
        shadow = None
        if spec.get("shadow") is not None:
            shadow = self._parse_shadow(spec["shadow"])
        with self._lock:
            if weights is not None:
                self._version_weights = weights
                self._version_rr = {}
            if "shadow" in spec:
                old_candidate = (self._shadow or {}).get("candidate")
                self._shadow = shadow
                if shadow is None or \
                        shadow["candidate"] != old_candidate:
                    # A new (or cleared) candidate starts its agreement
                    # evidence from zero — stale ledgers don't promote.
                    self._shadow_counter = 0
                    self._shadow_samples = 0
                    self._shadow_agree = 0
                    self._shadow_ledger = []
        self._persist_versions()
        logger.info("versions: weights=%s shadow=%s",
                    weights if weights is not None else "<unchanged>",
                    shadow if "shadow" in spec else "<unchanged>")
        return self.versions_record()

    @staticmethod
    def _parse_weights(raw: Any) -> Dict[str, float]:
        if not isinstance(raw, dict):
            raise VersionError("weights must be an object "
                               "{signature: weight}")
        weights: Dict[str, float] = {}
        for sig, value in raw.items():
            try:
                w = float(value)
            except (TypeError, ValueError):
                raise VersionError(
                    f"weight for {sig!r} must be a number, got "
                    f"{value!r}")
            if w < 0:
                raise VersionError(f"weight for {sig!r} must be >= 0")
            if w > 0:
                weights[str(sig)] = w
        if raw and not weights:
            raise VersionError("at least one weight must be > 0")
        return weights

    def _parse_shadow(self, raw: Any) -> Dict[str, Any]:
        if not isinstance(raw, dict) or not raw.get("candidate"):
            raise VersionError(
                "shadow must be an object with a 'candidate' signature")
        candidate = str(raw["candidate"])
        try:
            fraction = float(raw.get("fraction", 1.0))
        except (TypeError, ValueError):
            raise VersionError("shadow fraction must be a number")
        if not 0 < fraction <= 1:
            raise VersionError("shadow fraction must be in (0, 1]")
        default_ledger = os.path.join(
            os.path.dirname(self.sup.state_path),
            f"agreement_{candidate}.jsonl")
        return {
            "candidate": candidate,
            "fraction": fraction,
            "tolerance": float(raw.get("tolerance", 1e-6)),
            "min_agreement": float(raw.get("min_agreement", 0.98)),
            "min_samples": int(raw.get("min_samples", 10)),
            "ledger_path": str(raw.get("ledger_path", default_ledger)),
        }

    def promote(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        """``POST /admin/promote``: shift routing weight to the shadow
        candidate ONLY on measured agreement. Raises
        :class:`PromotionRefused` (fleet untouched) when the evidence
        does not clear the bar, :class:`VersionError` when there is no
        candidate to judge."""
        with self._lock:
            shadow = dict(self._shadow) if self._shadow else {}
            samples, agree = self._shadow_samples, self._shadow_agree
        candidate = spec.get("candidate") or shadow.get("candidate")
        if not candidate:
            raise VersionError("no promotion candidate: pass "
                               "'candidate' or arm shadow traffic first")
        min_agreement = float(
            spec.get("min_agreement",
                     shadow.get("min_agreement", 0.98)))
        min_samples = int(
            spec.get("min_samples", shadow.get("min_samples", 10)))
        rate = (agree / samples) if samples else 0.0
        stats = {"candidate": candidate, "samples": samples,
                 "agreements": agree,
                 "agreement_rate": round(rate, 6),
                 "min_agreement": min_agreement,
                 "min_samples": min_samples}
        if samples < min_samples or rate < min_agreement:
            _PROMOTIONS.inc(outcome="refused")
            raise PromotionRefused(
                f"promotion refused: {samples} sample(s) at "
                f"{rate:.4f} agreement vs bar of >= {min_samples} "
                f"samples and >= {min_agreement:.4f} — routing weights "
                "untouched", stats=stats)
        weights = self._parse_weights(
            spec.get("weights") or {candidate: 1.0})
        with self._lock:
            self._version_weights = weights
            self._version_rr = {}
            self._shadow = None
            self._promotions += 1
        _PROMOTIONS.inc(outcome="ok")
        self._persist_versions()
        logger.info("promotion: %s -> weights %s (%s)", candidate,
                    weights, stats)
        return {**self.versions_record(), "promoted": candidate,
                "evidence": stats}

    def versions_record(self) -> Dict[str, Any]:
        """The ``versions/v1`` machine-readable record (the
        ``/admin/versions`` response and ``cli/serve.py --versions``
        final line)."""
        by_version: Dict[str, int] = {}
        for w in self.sup.routable_workers():
            sig = str((w.get("health") or {}).get("weights_signature"))
            by_version[sig] = by_version.get(sig, 0) + 1
        with self._lock:
            weights = dict(self._version_weights)
            shadow = dict(self._shadow) if self._shadow else None
            samples, agree = self._shadow_samples, self._shadow_agree
            promotions = self._promotions
        return {
            "schema": "versions/v1",
            "metric": "fleet_active_versions",
            "value": float(len(by_version)),
            "unit": "versions",
            "ok": True,
            "weights": weights,
            "workers_by_version": by_version,
            "shadow": shadow,
            "shadow_samples": samples,
            "shadow_agreement": (round(agree / samples, 6)
                                 if samples else None),
            "promotions": promotions,
        }

    def _persist_versions(self) -> None:
        with self._lock:
            record = {
                "weights": dict(self._version_weights),
                "shadow": dict(self._shadow) if self._shadow else None,
                "promotions": self._promotions,
            }
        try:
            self.sup.set_extra_state("versions", record)
        except (OSError, ValueError) as exc:
            logger.warning("versions: persist failed: %s", exc)

    def _restore_versions(self) -> None:
        """Recover version weights / shadow config / promotion count
        from a dead supervisor's fleet_state.json — kill -9 of the
        control plane drops no version pins."""
        record = self.sup.recovered_state().get("versions")
        if not isinstance(record, dict):
            return
        weights = record.get("weights")
        shadow = record.get("shadow")
        with self._lock:
            if isinstance(weights, dict):
                restored: Dict[str, float] = {}
                for sig, value in weights.items():
                    if isinstance(value, (int, float)) and value > 0:
                        restored[str(sig)] = float(value)
                self._version_weights = restored
                self._version_rr = {}
            if isinstance(shadow, dict) and shadow.get("candidate"):
                self._shadow = shadow
            promotions = record.get("promotions")
            if isinstance(promotions, int):
                self._promotions = promotions
        logger.info("versions: restored from fleet_state.json: %s",
                    record)
        self._persist_versions()

    def _maybe_shadow(self, method: str, path: str, body: bytes,
                      content_type: str, primary_out: bytes) -> None:
        """Counter-based deterministic sampling: request n is mirrored
        iff floor(n*f) advanced — exactly fraction f of requests, no
        RNG. The mirror runs on its own daemon thread; the client's
        response already left."""
        if path.partition("?")[0] != "/predict":
            return
        with self._lock:
            shadow = self._shadow
            if not shadow:
                return
            self._shadow_counter += 1
            n, f = self._shadow_counter, shadow["fraction"]
            if int(n * f) == int((n - 1) * f):
                return
            shadow = dict(shadow)
        threading.Thread(
            target=self._shadow_one,
            args=(shadow, method, path, body, content_type, primary_out),
            name="shadow-mirror", daemon=True).start()

    def _shadow_one(self, shadow: Dict[str, Any], method: str, path: str,
                    body: bytes, content_type: str,
                    primary_out: bytes) -> None:
        candidate = shadow["candidate"]
        entry: Dict[str, Any] = {"ts": round(time.time(), 3),
                                 "path": path, "candidate": candidate}
        try:
            sequence = self._pick_sequence(None, version=candidate)
            if not sequence:
                entry["outcome"] = "no_worker"
                _SHADOW.inc(outcome="no_worker")
            else:
                worker_id = sequence[0]
                host, port = self.sup.endpoint(worker_id)
                status, out, _ = self._attempt(
                    host, port, method, path, body, content_type, None,
                    self.cfg.proxy_timeout_s)
                entry["shadow_worker"] = worker_id
                if status != 200:
                    entry.update(outcome="error", status=status)
                    _SHADOW.inc(outcome="error")
                else:
                    agreed, diff = _prediction_agreement(
                        primary_out, out, shadow["tolerance"])
                    entry["outcome"] = "agree" if agreed else "disagree"
                    if diff is not None:
                        entry["max_abs_diff"] = diff
                    _SHADOW.inc(outcome=entry["outcome"])
                    with self._lock:
                        self._shadow_samples += 1
                        self._shadow_agree += int(agreed)
        except Exception as exc:  # noqa: BLE001 - shadow is best-effort
            entry.update(outcome="error", error=str(exc))
            _SHADOW.inc(outcome="error")
        self._append_ledger(shadow["ledger_path"], entry)

    def _append_ledger(self, path: str, entry: Dict[str, Any]) -> None:
        """Append to the in-memory ledger and rewrite the WHOLE JSONL
        atomically (artifact + integrity sidecar): a reader — fsck, the
        promotion rule, an operator's tail — sees a complete, verifiable
        ledger or the previous one, never a torn line."""
        with self._lock:
            self._shadow_ledger.append(entry)
            data = "".join(json.dumps(e, sort_keys=True) + "\n"
                           for e in self._shadow_ledger)
            entries = len(self._shadow_ledger)
        try:
            artifacts.atomic_write_artifact(
                path, data, "agreement_ledger",
                extra={"entries": entries})
        except OSError as exc:
            logger.warning("shadow: ledger write failed: %s", exc)

    # -- observability -----------------------------------------------------

    def health(self) -> Dict[str, Any]:
        infos = self.sup.worker_infos()
        active = [w for w in infos if w["state"] != "retired"]
        healthy = [w for w in active if w["state"] == "healthy"]
        draining = self._draining.is_set()
        status = ("draining" if draining
                  else "down" if not healthy
                  else "ok" if len(healthy) == len(active) else "degraded")
        with self._lock:
            rollover_busy = self._rollover_active
            version_weights = dict(self._version_weights)
            shadow_candidate = (self._shadow or {}).get("candidate")
        return {
            "status": status,
            "role": "fleet-router",
            "draining": draining,
            "workers": len(active),
            "healthy": len(healthy),
            "rollover_in_progress": rollover_busy,
            "weights_signatures": sorted(
                {str(w["health"].get("weights_signature"))
                 for w in healthy if w.get("health")}),
            "version_weights": version_weights,
            "shadow_candidate": shadow_candidate,
        }

    def stats(self) -> Dict[str, Any]:
        worker_stats = self._fetch_workers("/stats")
        with self._lock:
            router = {
                "routed": self._routed,
                "failovers": self._failovers,
                "rollovers": self._rollovers,
                "active_workers": list(self._active),
                "draining": self._draining.is_set(),
                "version_weights": dict(self._version_weights),
                "shadow_samples": self._shadow_samples,
                "promotions": self._promotions,
            }
        return {"router": router, "fleet": self.sup.stats(),
                "workers": worker_stats}

    def _fetch_workers(self, path: str) -> Dict[str, Any]:
        """Fetch ``path`` from every non-retired worker CONCURRENTLY:
        sequential fetches would stall a /stats or /metrics scrape by
        aggregate_timeout_s per hung worker — blinding the operator
        exactly when the fleet is degraded."""
        infos = [info for info in self.sup.worker_infos()
                 if info["state"] != "retired"]
        results = fan_out(
            {info["worker_id"]: (
                lambda i=info: self._fetch_worker(i, path))
             for info in infos},
            join_timeout_s=self.cfg.aggregate_timeout_s + 1.0,
            name="fetch")
        for info in infos:
            results.setdefault(info["worker_id"],
                               {"error": "aggregation fetch timed out"})
        return results

    def _fetch_worker(self, info: Dict[str, Any], path: str):
        if info["state"] != "healthy":
            return {"error": f"worker is {info['state']}"}
        try:
            _, payload = request_json(
                self.sup.host, info["port"], "GET", path,
                timeout_s=self.cfg.aggregate_timeout_s)
            return payload
        except Exception as exc:  # noqa: BLE001 - aggregation best-effort
            return {"error": str(exc)}

    def metrics_text(self) -> str:
        """The router's registry plus every healthy worker's exposition
        with ``worker=`` labels injected into the ``di_*`` families —
        merged per family so the combined scrape stays valid."""
        families = _parse_exposition(expfmt.render())
        for worker_id, text in self._fetch_workers("/metrics").items():
            if not isinstance(text, str):
                continue
            for name, fam in _parse_exposition(
                    text, relabel=worker_id).items():
                mine = families.setdefault(
                    name, {"help": fam["help"], "type": fam["type"],
                           "samples": []})
                mine["samples"].extend(fam["samples"])
        out: List[str] = []
        for name, fam in families.items():
            if fam["help"] is not None:
                out.append(f"# HELP {name} {fam['help']}")
            if fam["type"] is not None:
                out.append(f"# TYPE {name} {fam['type']}")
            out.extend(fam["samples"])
        return "\n".join(out) + "\n"

    def final_contract(self) -> Dict[str, Any]:
        """The ``fleet/v1`` machine-readable record: the router's final
        stdout line (``cli/serve.py``) and the base of every
        ``/admin/rollover`` response."""
        sup = self.sup.stats()
        states = sup["states"]
        active = sum(n for state, n in states.items() if state != "retired")
        versions = len({
            str((w.get("health") or {}).get("weights_signature"))
            for w in sup["workers"].values()
            if w["state"] == "healthy"})
        with self._lock:
            routed, failovers, rollovers = (
                self._routed, self._failovers, self._rollovers)
        return {
            "schema": "fleet/v1",
            "metric": "fleet_unplanned_worker_restarts",
            "value": float(sup["restarts_total"]),
            "unit": "restarts",
            # Cumulative trips, not just currently-open: the shutdown
            # drain retires open-circuit workers right before the final
            # line prints, and a degraded run must not exit "ok".
            "ok": (sup["circuit_open"] == 0
                   and sup["circuit_tripped_total"] == 0),
            "circuit_tripped": sup["circuit_tripped_total"],
            "workers": active,
            "healthy": states.get("healthy", 0),
            "restarts": sup["restarts_total"],
            "circuit_open": sup["circuit_open"],
            "rollovers": rollovers,
            "failovers": failovers,
            "routed": routed,
            "preemptions": sup["preemptions"],
            "versions": versions,
            "mesh_shape": self.cfg.required_mesh_shape or "1x1",
            "state_path": sup["state_path"],
        }


# ---------------------------------------------------------------------------
# Shadow-output comparison
# ---------------------------------------------------------------------------


def _flatten(value: Any) -> Optional[List[float]]:
    """Nested number lists -> flat float list; None when the structure
    holds anything that is not a number or a list."""
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        return [float(value)]
    if isinstance(value, list):
        out: List[float] = []
        for item in value:
            flat = _flatten(item)
            if flat is None:
                return None
            out.extend(flat)
        return out
    return None


def _prediction_agreement(primary: bytes, shadow: bytes,
                          tolerance: float,
                          ) -> Tuple[bool, Optional[float]]:
    """Compare two /predict response bodies on ``contact_probs``:
    (agreed, max abs elementwise diff). Structural mismatch (missing
    key, different shape, non-JSON) is a DISAGREEMENT with diff None —
    a candidate that changes the response shape must not promote."""
    try:
        a = json.loads(primary.decode())
        b = json.loads(shadow.decode())
    except (ValueError, UnicodeDecodeError):
        return False, None
    if not isinstance(a, dict) or not isinstance(b, dict):
        return False, None
    flat_a = _flatten(a.get("contact_probs"))
    flat_b = _flatten(b.get("contact_probs"))
    if flat_a is None or flat_b is None or len(flat_a) != len(flat_b):
        return False, None
    diff = max((abs(x - y) for x, y in zip(flat_a, flat_b)),
               default=0.0)
    return diff <= tolerance, diff


# ---------------------------------------------------------------------------
# Prometheus text merging (per-worker relabeled aggregation)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(.+)$")


def _inject_label(line: str, worker_id: str) -> str:
    """``name{a="b"} 1`` -> ``name{worker="wN",a="b"} 1`` (and the
    label-less form grows the braces). Non-matching lines pass
    through untouched."""
    m = _SAMPLE_RE.match(line)
    if m is None:
        return line
    name, _, inner, value = m.groups()
    label = f'worker="{worker_id}"'
    inner = f"{label},{inner}" if inner else label
    return f"{name}{{{inner}}} {value}"


def _family_of(sample_name: str) -> str:
    """Histogram series (_bucket/_sum/_count) group under their base
    family for HELP/TYPE purposes."""
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            return sample_name[: -len(suffix)]
    return sample_name


def _parse_exposition(text: str,
                      relabel: Optional[str] = None) -> Dict[str, Dict]:
    """Exposition text -> ordered {family: {help, type, samples}}.
    With ``relabel``, a ``worker`` label is injected into every sample
    of a ``di_*`` family (the repo's own namespace; foreign families
    pass through unlabeled)."""
    families: Dict[str, Dict] = {}

    def fam(name: str) -> Dict:
        return families.setdefault(
            name, {"help": None, "type": None, "samples": []})

    for line in text.splitlines():
        if line.startswith("# HELP "):
            name, _, help_text = line[len("# HELP "):].partition(" ")
            fam(name)["help"] = help_text
        elif line.startswith("# TYPE "):
            name, _, type_text = line[len("# TYPE "):].partition(" ")
            fam(name)["type"] = type_text
        elif line.strip() and not line.startswith("#"):
            m = _SAMPLE_RE.match(line)
            name = _family_of(m.group(1)) if m else line.split()[0]
            if relabel is not None and name.startswith("di_"):
                line = _inject_label(line, relabel)
            fam(name)["samples"].append(line)
    return families
