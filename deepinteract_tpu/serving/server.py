"""Stdlib HTTP JSON API over the :class:`InferenceEngine`.

No web framework — ``http.server.ThreadingHTTPServer`` is enough for a
JSON control plane whose heavy lifting (batching, compile reuse) lives in
the engine: handler threads just parse the upload, enqueue, and block on
the future while the scheduler thread owns device dispatch.

Endpoints:

* ``POST /predict`` — body is either a complex ``.npz`` upload
  (``data/io.py`` schema, ``Content-Type: application/octet-stream``) or
  a JSON object with ``{"npz_path": ...}`` / ``{"left_pdb": ...,
  "right_pdb": ...}`` featurized server-side via ``pipeline/pair.py``.
  Response: ``{"complex_name", "n1", "n2", "bucket", "cached",
  "coalesced", "latency_ms", "contact_probs": [[...]]}``.
* ``POST /screen`` — small SYNCHRONOUS bulk screen (docking funnel):
  JSON ``{"npz_paths": [...complex npz...], "top_k": 10, "include_self":
  false, "max_pairs": 0, "query": ["name:g1", ...]}``. The listed
  complexes are split into chains, every pair is scored through the
  split-phase path (N encoder passes + N^2 micro-batched decodes over
  the server's shared embedding cache — ``deepinteract_tpu.screening``),
  and the ranked records come back in the response. Screens above
  ``screen_max_pairs`` are refused with 400 — the offline
  ``cli/screen.py`` (manifest + preemption resume) is the tool for
  those.
* ``GET /healthz`` — liveness + draining flag.
* ``GET /stats`` — queue depth, per-bucket compile inventory, result-cache
  hit rate, request-latency percentiles, and a ``screening`` block
  (``/screen`` request count + shared embedding-cache hit rate).

Request-scoped tracing: every ``POST /predict`` / ``POST /screen`` mints
a ``trace_id`` (:mod:`deepinteract_tpu.obs.reqtrace`) that is carried
through the scheduler queue and the engine's flush and echoed in the
response. Appending ``?trace=1`` to either route additionally returns
the full latency decomposition (queue-wait / batch-assembly / compile /
device for predicts; encode / decode for screens) — the same numbers
recorded as ``di_request_*`` histograms in ``/metrics`` and, when a span
sink is configured, as ``request_*`` events in ``events.jsonl`` under
that ``trace_id``.
* ``GET /metrics`` — the process-wide telemetry registry in Prometheus
  text format (``obs/expfmt.py``). Latency percentiles in ``/stats`` are
  derived from the same registry histogram the exposition serves, so the
  two endpoints agree by construction.

Overload discipline (``serving/admission.py``): the engine's bounded
queues reject excess submits with a typed ``Overloaded`` -> **429 +
``Retry-After``**; per-request deadlines (``X-Request-Deadline-Ms``
header or ``deadline_s`` JSON field, default ``--default_deadline_ms``)
are enforced end to end -> **504** when the budget expires; and an
adaptive :class:`LoadShedder` flips the server into degraded mode under
sustained pressure — POST routes answer 429 before any parse work,
``/healthz`` reports ``{"status": "overloaded"}``, and ``/stats`` /
``/metrics`` stay live — with hysteresis so it recovers cleanly.

Shutdown: ``run()`` installs the PR-1 :class:`PreemptionGuard`; on
SIGTERM/SIGINT the server stops accepting (``503`` on new predicts),
drains in-flight requests through the scheduler, answers their responses,
and returns 0 — the same cooperative-drain discipline training's
preemption path uses.
"""

from __future__ import annotations

import io
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepinteract_tpu.data.io import GRAPH_KEYS
from deepinteract_tpu.obs import expfmt
from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs.reqtrace import RequestTrace
from deepinteract_tpu.robustness.preemption import PreemptionGuard
from deepinteract_tpu.serving.admission import (
    Deadline,
    DeadlineExceeded,
    LoadShedder,
    Overloaded,
    ShedderConfig,
    ShuttingDown,
)
from deepinteract_tpu.serving.engine import InferenceEngine
from deepinteract_tpu.serving.scheduler import SchedulerClosed

logger = logging.getLogger(__name__)

# Every answered request, labeled by route and HTTP status. The 200-count
# on /predict equals the latency histogram's count (both recorded on the
# same success path) — the /metrics-vs-/stats agreement tests pin that.
_REQUESTS = obs_metrics.counter(
    "di_serving_requests_total", "HTTP requests answered",
    labelnames=("endpoint", "status"))


def raw_from_npz_bytes(body: bytes) -> Dict:
    """An uploaded ``.npz`` complex (the exact ``save_complex_npz``
    schema) -> raw dict, without touching the filesystem. Schema
    construction is delegated to ``data/io.py:load_complex_npz`` (the one
    reader) — only the clearer missing-key message lives here."""
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        missing = [k for p in ("g1", "g2")
                   for k in (f"{p}_{key}" for key in GRAPH_KEYS)
                   if k not in z] + [k for k in ("examples",) if k not in z]
        if missing:
            raise ValueError(f"npz upload missing keys: {missing}")
    from deepinteract_tpu.data.io import load_complex_npz

    return load_complex_npz(io.BytesIO(body))


def raw_from_json(payload: Dict) -> Dict:
    """JSON request body -> raw complex dict (path-based variants)."""
    if "npz_path" in payload:
        from deepinteract_tpu.data.io import load_complex_npz

        return load_complex_npz(payload["npz_path"])
    if "left_pdb" in payload and "right_pdb" in payload:
        from deepinteract_tpu.pipeline.pair import convert_pdb_pair_to_complex

        return convert_pdb_pair_to_complex(
            payload["left_pdb"], payload["right_pdb"], with_labels=False)
    raise ValueError(
        "JSON body must contain 'npz_path' or both 'left_pdb' and "
        "'right_pdb' (or upload npz bytes as application/octet-stream)")


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """stdlib's handle_error prints a traceback banner to stderr for any
    handler-thread exception — including routine client disconnects and
    keep-alive sockets torn down by a drain. Route it to debug logging;
    real request failures are already answered as 4xx/5xx JSON by the
    handler itself."""

    def handle_error(self, request, client_address):  # noqa: N802
        logger.debug("connection error from %s", client_address,
                     exc_info=True)


class _LatencyTracker:
    """Request-latency percentiles for /stats, backed by the process-wide
    registry histogram (the same series ``/metrics`` exposes).

    Replaces the old rolling-sample window, which re-sorted a 2048-entry
    Python list under the handler lock on EVERY /stats call; histogram
    percentile estimation is O(buckets), recording is O(buckets) worst
    case, and /stats can no longer disagree with the exposition. The
    output keys are unchanged (count/p50_ms/p90_ms/p99_ms/max_ms)."""

    def __init__(self):
        self._hist = obs_metrics.histogram(
            "di_serving_request_latency_seconds",
            "End-to-end /predict latency (parse to response)")

    def record(self, seconds: float) -> None:
        self._hist.observe(seconds)

    def stats(self) -> Dict[str, Any]:
        count = self._hist.count()
        if count == 0:
            return {"count": 0}
        return {
            "count": count,
            "p50_ms": self._hist.percentile(50) * 1e3,
            "p90_ms": self._hist.percentile(90) * 1e3,
            "p99_ms": self._hist.percentile(99) * 1e3,
            "max_ms": self._hist.max_value() * 1e3,
        }


class ServingServer:
    """Engine + ThreadingHTTPServer + cooperative drain."""

    def __init__(self, engine: InferenceEngine, host: str = "127.0.0.1",
                 port: int = 8008, request_timeout_s: float = 120.0,
                 screen_max_pairs: int = 512,
                 default_deadline_ms: float = 0.0,
                 shedder_cfg: Optional[ShedderConfig] = None,
                 index_path: Optional[str] = None,
                 calibration_path: Optional[str] = None):
        self.engine = engine
        self.latency = _LatencyTracker()
        self._draining = threading.Event()
        self.request_timeout_s = request_timeout_s
        self.screen_max_pairs = int(screen_max_pairs)
        # Requests without their own X-Request-Deadline-Ms / deadline_s
        # get this budget; <= 0 keeps the legacy no-deadline behavior
        # (request_timeout_s is then the only bound).
        self.default_deadline_ms = float(default_deadline_ms)
        # Degraded-mode switch over the same signals /metrics serves:
        # admission utilization + queue depth, request p99, compile
        # in-flight (serving/admission.py). Evaluated per POST and per
        # /healthz — no background thread.
        self.shedder = LoadShedder(shedder_cfg or ShedderConfig(),
                                   self._shed_signals)
        # Screens share one embedding cache across requests (a library
        # chain re-screened later skips its encoder pass) and serialize
        # on one lock: each screen is many device dispatches, and two
        # interleaved screens would just thrash the device queue.
        self._screen_cache = None
        self._screen_lock = threading.Lock()
        # Proteome indexes (deepinteract_tpu.index): opened handles are
        # cached per path (shards verify once, stay resident). A
        # --index_path preload happens HERE so a worker with a bad or
        # stale index fails at startup, not on its first query.
        self.index_path = index_path
        self._indices: Dict[str, Any] = {}
        self._index_lock = threading.Lock()
        if index_path:
            self._get_index(index_path)
        # Fitted probability calibration (deepinteract_tpu.calibration),
        # verified at startup against the served weights — a worker with
        # a stale or corrupt map fails HERE, not by silently rescaling
        # its first response. Applied to /screen and /assembly rankings
        # (raw scores always preserved alongside).
        self.calibration_path = calibration_path
        self.calibrator = None
        if calibration_path:
            from deepinteract_tpu.calibration import load_calibration

            self.calibrator = load_calibration(
                calibration_path,
                expect_signature=engine.weights_signature())
        server = self

        class Handler(BaseHTTPRequestHandler):
            # Handler threads must not outlive a drain by minutes on a
            # stuck client; keep stdlib defaults otherwise.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: N802 - stdlib name
                logger.debug("http: " + fmt, *args)

            def _route(self) -> str:
                """Path sans query string (``/predict?trace=1`` is the
                /predict route, for dispatch AND the metrics label)."""
                return self.path.partition("?")[0]

            def _trace_requested(self) -> bool:
                from urllib.parse import parse_qs

                query = self.path.partition("?")[2]
                return parse_qs(query).get("trace", ["0"])[-1] in (
                    "1", "true", "yes")

            def _request_deadline(self, payload: Optional[Dict] = None):
                """Per-request deadline: the ``X-Request-Deadline-Ms``
                header wins, then a JSON body's ``deadline_s``, then the
                server-wide default; None = no deadline (legacy
                behavior, request_timeout_s is the only bound). Raises
                ValueError on a non-positive or non-numeric budget."""
                hdr = self.headers.get("X-Request-Deadline-Ms")
                if hdr is not None:
                    ms = float(hdr)
                    if not ms > 0:
                        raise ValueError(
                            f"X-Request-Deadline-Ms must be > 0, got {hdr!r}")
                    return Deadline.after(ms / 1e3)
                if payload is not None and "deadline_s" in payload:
                    sec = float(payload["deadline_s"])
                    if not sec > 0:
                        raise ValueError(
                            f"deadline_s must be > 0, got {sec!r}")
                    return Deadline.after(sec)
                if server.default_deadline_ms > 0:
                    return Deadline.after(server.default_deadline_ms / 1e3)
                return None

            def _send_overloaded(self, retry_after_s: float,
                                 error: str) -> None:
                """429 + Retry-After: the client retry contract for both
                admission rejections and shedder-degraded mode."""
                import math

                retry = max(1, int(math.ceil(retry_after_s)))
                self._send_json(
                    429,
                    {"error": error,
                     "retry_after_s": round(float(retry_after_s), 3)},
                    extra_headers={"Retry-After": str(retry)})

            def _send_body(self, code: int, body: bytes,
                           content_type: str,
                           extra_headers: Optional[Dict] = None) -> None:
                # Counted BEFORE the body write: a client that disconnects
                # mid-response must not make the request vanish from the
                # counter while the latency histogram already saw it (the
                # /stats-vs-/metrics agreement depends on it). Route label
                # is the matched route ("other" for 404s), not the raw
                # path — unknown client paths must not mint unbounded
                # label values in the registry.
                endpoint = self._route() if self._route() in (
                    "/predict", "/screen", "/assembly", "/healthz",
                    "/stats", "/metrics") else "other"
                _REQUESTS.inc(endpoint=endpoint, status=str(code))
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for name, value in (extra_headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, payload: Dict,
                           extra_headers: Optional[Dict] = None) -> None:
                self._send_body(code, json.dumps(payload).encode(),
                                "application/json",
                                extra_headers=extra_headers)

            def do_GET(self):  # noqa: N802 - stdlib name
                route = self._route()
                if route == "/healthz":
                    # Degraded (overloaded) is a liveness-page state, not
                    # an error: the process is healthy, it is REFUSING
                    # work on purpose. /stats and /metrics stay live
                    # throughout — observability during the incident is
                    # the point.
                    degraded = server.shedder.evaluate()
                    draining = server._draining.is_set()
                    status = ("draining" if draining
                              else "overloaded" if degraded else "ok")
                    # Warm-replica fields (fleet routing/rollover): the
                    # served weights' identity and the AOT compile-cache
                    # inventory, so a router (serving/router.py) can
                    # verify a replica is warm on the right weights
                    # BEFORE switching traffic to it — the same labels
                    # /stats reports as compiled_buckets, via a cheap
                    # accessor (this route is probed every supervisor
                    # tick).
                    self._send_json(200, {
                        "status": status,
                        "draining": draining,
                        "degraded": degraded,
                        "weights_signature":
                            server.engine.weights_signature(),
                        "mesh_shape":
                            server.engine.mesh_shape_label(),
                        "warm_buckets":
                            server.engine.warm_bucket_labels(),
                    })
                elif route == "/stats":
                    self._send_json(200, server.stats())
                elif route == "/metrics":
                    self._send_body(200, server.metrics_text().encode(),
                                    expfmt.CONTENT_TYPE)
                else:
                    self._send_json(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802 - stdlib name
                route = self._route()
                if route not in ("/predict", "/screen", "/assembly"):
                    self._send_json(404, {"error": f"no route {self.path}"})
                    return
                if server._draining.is_set():
                    self._send_json(503, {"error": "server is draining"})
                    return
                if server.shedder.evaluate():
                    # Degraded mode: drain the body (keep-alive framing
                    # must stay intact) but skip ALL parse/featurize work.
                    self.rfile.read(int(self.headers.get(
                        "Content-Length", 0)))
                    server.shedder.count_rejection()
                    self._send_overloaded(
                        server.engine.admission.retry_after_s(),
                        "server overloaded (load shedding active); "
                        "retry after the indicated delay")
                    return
                if route == "/screen":
                    self._do_screen()
                    return
                if route == "/assembly":
                    self._do_assembly()
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    body = self.rfile.read(length)
                    ctype = self.headers.get("Content-Type", "")
                    if ctype.startswith("application/json"):
                        payload = json.loads(body.decode())
                        deadline = self._request_deadline(payload)
                        raw = raw_from_json(payload)
                    else:
                        deadline = self._request_deadline()
                        raw = raw_from_npz_bytes(body)
                except Exception as exc:  # noqa: BLE001 - client error
                    self._send_json(400, {"error": str(exc)})
                    return
                # Minted AFTER parse: the trace covers the request's trip
                # through the scheduler/engine, the thing an operator
                # debugs with it; upload decode time is in latency_ms.
                reqtrace = RequestTrace("/predict")
                t0 = time.monotonic()
                try:
                    result = server.engine.predict(
                        raw, timeout=server.request_timeout_s,
                        reqtrace=reqtrace, deadline=deadline)
                except Overloaded as exc:
                    self._send_overloaded(exc.retry_after_s, str(exc))
                    return
                except DeadlineExceeded as exc:
                    response = {"error": str(exc),
                                "trace_id": reqtrace.trace_id}
                    if self._trace_requested() and exc.trace is not None:
                        response["trace"] = exc.trace
                    self._send_json(504, response)
                    return
                except (SchedulerClosed, ShuttingDown):
                    self._send_json(503, {"error": "server is draining"})
                    return
                except Exception as exc:  # noqa: BLE001 - surfaced to client
                    logger.exception("predict failed")
                    self._send_json(500, {"error": str(exc)})
                    return
                latency = time.monotonic() - t0
                server.latency.record(latency)
                response = {
                    "complex_name": raw.get("complex_name", ""),
                    "trace_id": reqtrace.trace_id,
                    "n1": result["n1"],
                    "n2": result["n2"],
                    "bucket": list(result["bucket"]),
                    "cached": result["cached"],
                    "coalesced": result.get("coalesced", 1),
                    "latency_ms": latency * 1e3,
                    "contact_probs": np.asarray(
                        result["probs"], dtype=np.float64).tolist(),
                }
                if self._trace_requested() and "trace" in result:
                    response["trace"] = result["trace"]
                self._send_json(200, response)

            def _do_screen(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length).decode())
                    if not isinstance(payload, dict):
                        raise ValueError("screen body must be a JSON object")
                    deadline = self._request_deadline(payload)
                except Exception as exc:  # noqa: BLE001 - client error
                    self._send_json(400, {"error": str(exc)})
                    return
                reqtrace = RequestTrace("/screen")
                t0 = time.monotonic()
                try:
                    out = server.run_screen(payload,
                                            trace_id=reqtrace.trace_id,
                                            deadline=deadline)
                except DeadlineExceeded as exc:
                    self._send_json(504, {"error": str(exc),
                                          "trace_id": reqtrace.trace_id})
                    return
                except (ValueError, KeyError, FileNotFoundError,
                        OSError) as exc:
                    self._send_json(400, {"error": str(exc)})
                    return
                except Exception as exc:  # noqa: BLE001 - surfaced
                    logger.exception("screen failed")
                    self._send_json(500, {"error": str(exc)})
                    return
                out["latency_ms"] = (time.monotonic() - t0) * 1e3
                out["trace_id"] = reqtrace.trace_id
                # A screen's device phases are its encode+decode wall
                # (dispatches go straight to the device, no queue).
                encode_s = out.get("encode_seconds", 0.0)
                decode_s = out.get("decode_seconds", 0.0)
                reqtrace.set_phase("device", encode_s + decode_s)
                trace = reqtrace.finish(encode=encode_s, decode=decode_s)
                if self._trace_requested():
                    out["trace"] = trace
                self._send_json(200, out)

            def _do_assembly(self):
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length).decode())
                    if not isinstance(payload, dict):
                        raise ValueError(
                            "assembly body must be a JSON object")
                    deadline = self._request_deadline(payload)
                except Exception as exc:  # noqa: BLE001 - client error
                    self._send_json(400, {"error": str(exc)})
                    return
                reqtrace = RequestTrace("/assembly")
                t0 = time.monotonic()
                try:
                    out = server.run_assembly(payload,
                                              trace_id=reqtrace.trace_id,
                                              deadline=deadline)
                except DeadlineExceeded as exc:
                    self._send_json(504, {"error": str(exc),
                                          "trace_id": reqtrace.trace_id})
                    return
                except (ValueError, KeyError, FileNotFoundError,
                        OSError) as exc:
                    self._send_json(400, {"error": str(exc)})
                    return
                except Exception as exc:  # noqa: BLE001 - surfaced
                    logger.exception("assembly failed")
                    self._send_json(500, {"error": str(exc)})
                    return
                out["latency_ms"] = (time.monotonic() - t0) * 1e3
                out["trace_id"] = reqtrace.trace_id
                encode_s = out.get("encode_seconds", 0.0)
                decode_s = out.get("decode_seconds", 0.0)
                reqtrace.set_phase("device", encode_s + decode_s)
                trace = reqtrace.finish(encode=encode_s, decode=decode_s)
                if self._trace_requested():
                    out["trace"] = trace
                self._send_json(200, out)

        self.httpd = _QuietThreadingHTTPServer((host, port), Handler)
        self._serve_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    @property
    def address(self) -> Tuple[str, int]:
        host, port = self.httpd.server_address[:2]
        return str(host), int(port)

    def serve_background(self) -> None:
        """Start accepting connections on a daemon thread (used by run()
        and by tests; production entry is run())."""
        self._serve_thread = threading.Thread(
            target=self.httpd.serve_forever, name="http-serve", daemon=True)
        self._serve_thread.start()

    def drain(self) -> None:
        """Stop accepting new predicts, finish in-flight ones, stop the
        listener. Idempotent."""
        if self._draining.is_set():
            return
        self._draining.set()
        # Flush everything still queued; handler threads blocked on their
        # futures get their responses before the listener goes away.
        self.engine.close()
        self.httpd.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=10.0)
        self.httpd.server_close()

    def run(self, guard: Optional[PreemptionGuard] = None,
            poll_seconds: float = 0.25) -> int:
        """Blocking serve loop with the PR-1 preemption discipline:
        SIGTERM/SIGINT -> drain in-flight requests -> exit 0. ``guard`` is
        injectable for tests (flag-only mode outside the main thread)."""
        own_guard = guard is None
        guard = guard or PreemptionGuard(log=logger.warning)
        if own_guard:
            guard.__enter__()
        try:
            self.serve_background()
            host, port = self.address
            logger.info("serving on http://%s:%d (POST /predict, "
                        "POST /screen, POST /assembly, GET /healthz, "
                        "GET /stats, GET /metrics)", host, port)
            while not guard.requested:
                time.sleep(poll_seconds)
            logger.warning("drain requested (%s): refusing new requests, "
                           "flushing %d queued",
                           guard.reason,
                           self.engine.scheduler.stats()["queue_depth"])
        finally:
            self.drain()
            if own_guard:
                guard.__exit__(None, None, None)
        return 0

    # -- screening ---------------------------------------------------------

    def run_screen(self, payload: Dict, trace_id: str = "",
                   deadline: Optional[Deadline] = None) -> Dict:
        """Synchronous small screen for ``POST /screen`` (see module
        docstring). Raises ValueError/KeyError/OSError for client
        mistakes (mapped to 400 by the handler). ``trace_id`` labels the
        screen's ``screen_encode``/``screen_decode`` span events.
        ``deadline`` is enforced at encode/decode batch boundaries
        (DeadlineExceeded -> 504)."""
        from deepinteract_tpu.screening import (
            ChainLibrary,
            EmbeddingCache,
            ScreenConfig,
            ScreenRunner,
            enumerate_pairs,
        )

        if payload.get("index_path") or (
                self.index_path and payload.get("indexed")):
            return self._run_indexed_screen(payload, deadline=deadline)
        npz_paths = payload.get("npz_paths")
        if not npz_paths or not isinstance(npz_paths, list):
            raise ValueError("screen body needs 'npz_paths': a non-empty "
                             "list of complex .npz paths")
        library = ChainLibrary.from_complex_files(
            [str(p) for p in npz_paths])
        pairs = enumerate_pairs(
            library,
            queries=payload.get("query"),
            include_self=bool(payload.get("include_self", False)),
            max_pairs=int(payload.get("max_pairs", 0)))
        if len(pairs) > self.screen_max_pairs:
            raise ValueError(
                f"screen of {len(pairs)} pairs exceeds the synchronous "
                f"limit ({self.screen_max_pairs}); run cli/screen.py for "
                "large libraries (manifest + preemption resume)")
        with self._screen_lock:
            if self._screen_cache is None:
                self._screen_cache = EmbeddingCache()
            runner = ScreenRunner(
                self.engine, cache=self._screen_cache,
                cfg=ScreenConfig(
                    top_k=int(payload.get("top_k", 10)),
                    decode_batch=self.engine.cfg.max_batch,
                    encode_batch=self.engine.cfg.max_batch))
            result = runner.screen(library, pairs, trace_id=trace_id,
                                   deadline=deadline)
        out = {
            "chains": result.chains,
            "pairs": result.pairs_total,
            "ranked": result.records,
            **result.summary(),
        }
        if self.calibrator is not None:
            from deepinteract_tpu.calibration.calibrator import (
                annotate_records,
            )

            annotate_records(out["ranked"], self.calibrator)
            out["calibration"] = self.calibration_path
        return out

    def run_assembly(self, payload: Dict, trace_id: str = "",
                     deadline: Optional[Deadline] = None) -> Dict:
        """Synchronous k-chain assembly for ``POST /assembly``
        (deepinteract_tpu.assembly). Rides the same admission as
        /screen: C(k,2) pairs count against ``screen_max_pairs``, the
        shared embedding cache + screen lock serialize device work, and
        the request deadline is enforced at batch boundaries. Raises
        ValueError/KeyError/OSError for client mistakes (-> 400),
        DeadlineExceeded -> 504."""
        from deepinteract_tpu.assembly import AssemblyConfig, AssemblyRunner
        from deepinteract_tpu.screening import ChainLibrary, EmbeddingCache

        npz_paths = payload.get("npz_paths")
        if not npz_paths or not isinstance(npz_paths, list):
            raise ValueError("assembly body needs 'npz_paths': a "
                             "non-empty list of complex .npz paths")
        library = ChainLibrary.from_complex_files(
            [str(p) for p in npz_paths])
        chain_ids = payload.get("chains")
        if chain_ids is not None and not isinstance(chain_ids, list):
            raise ValueError("'chains' must be a list of chain ids")
        k = len(chain_ids) if chain_ids else len(library.ids())
        pairs = k * (k - 1) // 2
        if pairs > self.screen_max_pairs:
            raise ValueError(
                f"assembly of {k} chains is {pairs} pairs, over the "
                f"synchronous limit ({self.screen_max_pairs}); run "
                "cli/assemble.py for large assemblies")
        keep_maps = bool(payload.get("maps", False))
        with self._screen_lock:
            if self._screen_cache is None:
                self._screen_cache = EmbeddingCache()
            runner = AssemblyRunner(
                self.engine, cache=self._screen_cache,
                cfg=AssemblyConfig(
                    top_k=int(payload.get("top_k", 10)),
                    decode_batch=self.engine.cfg.max_batch,
                    encode_batch=self.engine.cfg.max_batch,
                    edge_threshold=float(
                        payload.get("edge_threshold", 0.5)),
                    control=bool(payload.get("control", True)),
                    keep_maps=keep_maps),
                calibrator=self.calibrator)
            result = runner.assemble(library, chain_ids=chain_ids,
                                     trace_id=trace_id,
                                     deadline=deadline)
        out = {
            "ranked": result.records,
            "interface": result.interface,
            "weights_signature": self.engine.weights_signature(),
            "calibration": self.calibration_path,
            **result.summary(),
        }
        if keep_maps:
            out["maps"] = {pid: np.asarray(m, dtype=np.float64).tolist()
                           for pid, m in result.maps.items()}
        return out

    def _get_index(self, path: str):
        """Open-or-cached ChainIndex handle; manifest problems surface
        as ValueError (-> 400), never as a silent empty index."""
        from deepinteract_tpu.index import ChainIndex
        from deepinteract_tpu.robustness import artifacts

        key = os.path.abspath(str(path))
        with self._index_lock:
            hit = self._indices.get(key)
            if hit is not None:
                return hit
        try:
            index = ChainIndex.open(key)
        except artifacts.ArtifactError as exc:
            raise ValueError(f"index at {path}: {exc}")
        with self._index_lock:
            return self._indices.setdefault(key, index)

    def _run_indexed_screen(self, payload: Dict,
                            deadline: Optional[Deadline] = None) -> Dict:
        """Ranked-partner query against a prebuilt proteome index.

        EXEMPT from ``screen_max_pairs``: the pre-filter bounds decoder
        work to top-M survivors regardless of library size, and the
        decode loop streams micro-batches under the request deadline —
        expiry mid-decode FLUSHES the partners ranked so far with
        ``partial: true`` instead of burning the whole budget into a
        504 (an indexed library is exactly the case where a prefix of
        the ranking is still useful)."""
        from deepinteract_tpu.index import IndexedQueryRunner, QueryConfig
        from deepinteract_tpu.screening import ChainLibrary, EmbeddingCache

        index = self._get_index(payload.get("index_path")
                                or self.index_path)
        query = payload.get("query")
        if isinstance(query, list):
            if len(query) != 1:
                raise ValueError("indexed screen needs exactly one "
                                 "'query' chain id")
            query = query[0]
        if not query:
            raise ValueError("indexed screen needs 'query': the chain id "
                             "to rank partners for")
        query = str(query)
        partitions = payload.get("partitions")
        if partitions is not None and not isinstance(partitions, list):
            raise ValueError("'partitions' must be a list of partition "
                             "ids")
        with self._screen_lock:
            if self._screen_cache is None:
                self._screen_cache = EmbeddingCache()
            runner = IndexedQueryRunner(
                self.engine, index,
                cfg=QueryConfig(
                    top_m=int(payload.get("top_m", 32)),
                    top_k=int(payload.get("top_k", 10)),
                    decode_batch=self.engine.cfg.max_batch),
                cache=self._screen_cache,
                allow_stale=bool(payload.get("allow_stale", False)))
            npz_paths = payload.get("npz_paths")
            if npz_paths:
                library = ChainLibrary.from_complex_files(
                    [str(p) for p in npz_paths])
                entry = library[query]
                result = runner.query_from_raw(
                    entry.chain_id, entry.raw, partitions=partitions,
                    deadline=deadline, on_deadline="partial")
            else:
                result = runner.query_from_index(
                    query, partitions=partitions, deadline=deadline,
                    on_deadline="partial")
        out = {
            "indexed": True,
            "index_path": index.index_dir,
            "query": result.query,
            "chains": index.num_chains,
            "partitions_served": (sorted(partitions)
                                  if partitions is not None
                                  else index.partition_ids()),
            "weights_signature": self.engine.weights_signature(),
            "ranked": result.records,
            **result.summary(),
        }
        if self.calibrator is not None:
            from deepinteract_tpu.calibration.calibrator import (
                annotate_records,
            )

            annotate_records(out["ranked"], self.calibrator)
            out["calibration"] = self.calibration_path
        return out

    # -- observability -----------------------------------------------------

    def _shed_signals(self) -> Dict[str, float]:
        """The load shedder's inputs, read from the SAME sources /metrics
        serves: admission occupancy (leading indicator), the request-
        latency histogram's p99, and the compile-in-flight gauge."""
        adm = self.engine.admission.stats()
        return {
            "utilization": adm["inflight"] / max(1, adm["max_inflight"]),
            "queue_depth": float(adm["queued"]),
            "p99_ms": float(self.latency.stats().get("p99_ms", 0.0)),
            "compile_inflight": obs_metrics.gauge(
                "di_serving_compile_inflight").value(),
        }

    def stats(self) -> Dict[str, Any]:
        # /stats stays live in degraded mode BY DESIGN (the shedder only
        # gates POST routes): an overloaded server that also goes blind
        # is an unoperable one.
        return {
            "engine": self.engine.stats(),
            "latency": self.latency.stats(),
            "screening": self.screening_stats(),
            "shedding": self.shedder.stats(),
            "draining": self._draining.is_set(),
        }

    def screening_stats(self) -> Dict[str, Any]:
        """Operator view of the ``/screen`` route (invisible pre-PR-7):
        answered-request counts read from the SAME registry counter the
        exposition serves (agreement by construction), plus the shared
        embedding cache's hit rate and occupancy."""
        # NO _screen_lock here: run_screen holds it for an entire screen
        # and /stats//metrics must not block behind in-flight device
        # work. A bare attribute read is atomic, and EmbeddingCache.
        # stats() takes the cache's own (short-held) lock.
        cache = self._screen_cache  # di: allow[lock-discipline] deliberate lock-free read, see comment above
        cache_stats = cache.stats() if cache is not None else {}
        return {
            "requests": _REQUESTS.value(endpoint="/screen", status="200"),
            "requests_rejected": _REQUESTS.value(endpoint="/screen",
                                                 status="400"),
            "emb_cache_entries": int(cache_stats.get("size", 0)),
            "emb_cache_hit_rate": float(cache_stats.get("hit_rate", 0.0)),
        }

    def metrics_text(self) -> str:
        """Prometheus text for ``GET /metrics``: point-in-time gauges
        (queue depth, compile inventory, cache hit rate) are refreshed
        from the engine at scrape time, then the whole process registry —
        including training/data/robustness families when co-resident —
        is rendered."""
        eng = self.engine.stats()
        g = obs_metrics.gauge
        g("di_serving_queue_depth",
          "Requests pending in the micro-batch scheduler").set(
            eng["scheduler"]["queue_depth"])
        g("di_serving_compiled_executables",
          "Entries in the shape-bucketed compile cache").set(
            eng["num_compiled_executables"])
        g("di_serving_result_cache_size",
          "Entries in the LRU result cache").set(eng["result_cache"]["size"])
        g("di_serving_result_cache_hit_rate",
          "Result-cache hit rate since startup").set(
            eng["result_cache"]["hit_rate"])
        g("di_serving_uptime_seconds",
          "Engine uptime").set(eng["uptime_seconds"])
        g("di_serving_draining",
          "1 while the server refuses new work").set(
            float(self._draining.is_set()))
        # Refresh the shedder at scrape time: di_shed_degraded must show
        # the CURRENT mode even when no request has polled it recently.
        self.shedder.evaluate()
        adm = eng["admission"]
        g("di_serving_inflight",
          "Admitted requests not yet answered").set(adm["inflight"])
        g("di_serving_retry_after_seconds",
          "Current backlog-drain estimate handed to rejected clients").set(
            adm["retry_after_s"])
        screening = self.screening_stats()
        g("di_serving_screen_emb_cache_entries",
          "Embeddings resident in the shared /screen cache").set(
            screening["emb_cache_entries"])
        g("di_serving_screen_emb_cache_hit_rate",
          "Shared /screen embedding-cache hit rate since startup").set(
            screening["emb_cache_hit_rate"])
        return expfmt.render()
