"""DeepInteract-TPU: a TPU-native (JAX/XLA/Pallas) framework for protein
interface contact prediction with the capabilities of DeepInteract
(Geometric Transformers for Protein Interface Contact Prediction, ICLR'22).

This is a ground-up TPU-first redesign, not a port:

* Residue graphs are statically-shaped, fixed-degree (kNN) dense tensors
  laid out as ``[N, K]`` neighbor slots instead of dynamic sparse graphs,
  so every graph op maps onto dense MXU-friendly einsums and masked
  softmaxes (no scatter/gather message passing UDFs).
* Parallelism is expressed with ``jax.sharding.Mesh`` + ``shard_map``
  (data-parallel axis over complexes, context-parallel axis over the
  L1 x L2 pair map) with XLA collectives over ICI — replacing the
  reference's Lightning DDP / NCCL stack.
* The edge-softmax/aggregation hot loop is a dense fused op
  (see ``deepinteract_tpu.ops``).

Reference layout citations in docstrings point into the upstream repo
(``/root/reference``) for parity checking.
"""

__version__ = "0.1.0"

from deepinteract_tpu import constants  # noqa: F401
