"""Consumer side: resolve a tuned config from the store and apply it.

Used by ``cli/train.py`` (model-side knobs must apply BEFORE the model is
constructed), :class:`deepinteract_tpu.training.loop.Trainer` (loop-side
scan_k at startup), the serving engine (per-bucket warmup), and bench's
tuned-vs-default A/B — one resolution path, so every consumer agrees on
what "the tuned config for this bucket" means.

Lookup order:

1. exact key ``(device_kind, jax version, model signature, bucket)``;
2. any-bucket fallback for the same device + model: model-side knobs
   (remat, scan_chunks, Pallas blocks) transfer across buckets far better
   than scan_k does, so the fallback adoption DROPS scan_k (keeps the
   caller's default) and says so in the adoption summary.

Multi-host reads go through the store's replicated path — every host
adopts identical knobs by construction.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deepinteract_tpu.tuning.space import (
    TrialConfig,
    apply_to_loop_config,
    apply_to_model_config,
    apply_to_optim_config,
    bucket_key,
    model_signature,
)
from deepinteract_tpu.tuning.store import TuningStore, runtime_key


@dataclasses.dataclass(frozen=True)
class Adopted:
    """One resolved adoption: the config, where it came from, and whether
    scan_k is trustworthy for the caller's bucket."""

    config: TrialConfig
    key: str
    source: str  # 'exact' | 'bucket_fallback'
    partial: bool = False

    @property
    def scan_k_applies(self) -> bool:
        return self.source == "exact"

    def summary(self) -> str:
        """The log line consumers print — the acceptance-criterion tuple."""
        c = self.config
        return (
            f"remat={'off' if not c.remat else c.remat_policy}, "
            f"scan_k={c.scan_k if self.scan_k_applies else 'kept-default'}, "
            f"microbatch={c.microbatch}, "
            f"scan_chunks={c.scan_chunks}, "
            f"pallas_blocks=({c.pallas_fwd_blocks}, {c.pallas_bwd_blocks}), "
            f"diagonal_buckets={c.diagonal_buckets}, "
            f"stem={c.interaction_stem or 'kept-config'}, "
            f"dtype={c.compute_dtype or 'kept-config'}, "
            f"mesh_placement={c.mesh_placement or 'policy'} "
            f"[{self.source}{', partial search' if self.partial else ''}]"
        )


def lookup(store: Optional[TuningStore], model_cfg, batch: int, pad: int,
           mesh_shape=None) -> Optional[Adopted]:
    """Resolve the tuned config for ``(model_cfg, b{batch}_p{pad})`` on
    this process's device, with the any-bucket fallback. None = nothing
    usable in the store.

    ``mesh_shape`` (the serving worker's (data, pair) topology) tries
    the topology-suffixed bucket key FIRST, then falls back to the plain
    single-device key — mesh knobs that transfer (stem, scan_chunks)
    still adopt on a mesh worker whose topology was never tuned, while a
    topology-specific entry (e.g. a pinned ``mesh_placement``) wins when
    one exists."""
    if store is None:
        return None
    sig = model_signature(model_cfg)
    buckets = [bucket_key(batch, pad, mesh_shape=mesh_shape)]
    plain = bucket_key(batch, pad)
    if plain != buckets[0]:
        buckets.append(plain)
    for bucket in buckets:
        key = runtime_key(sig, bucket)
        entry = store.get(key)
        if entry is not None and "config" in entry:
            return Adopted(config=TrialConfig.from_dict(entry["config"]),
                           key=key, source="exact",
                           partial=bool(entry.get("partial")))
    entry = store.best_entry_any_bucket(sig)
    if entry is not None and "config" in entry:
        return Adopted(config=TrialConfig.from_dict(entry["config"]),
                       key=runtime_key(sig, buckets[0]),
                       source="bucket_fallback",
                       partial=bool(entry.get("partial")))
    return None


def lookup_path(store_path: Optional[str], model_cfg, batch: int, pad: int,
                mesh_shape=None) -> Optional[Adopted]:
    """:func:`lookup` from a path, via the replicated (multi-host-safe)
    read. A missing store returns None; a schema-mismatched store raises
    (StoreSchemaError) — silently training on stale knobs is the failure
    mode the version field exists to prevent."""
    if not store_path:
        return None
    store = TuningStore.load_replicated(store_path)
    return lookup(store, model_cfg, batch, pad, mesh_shape=mesh_shape)


def restrict_pallas_blocks(adopted: Optional[Adopted], pads,
                           knn: int = 20):
    """Strip the tuned Pallas grid unless it is legal at EVERY padded
    chain length in ``pads``.

    The grid is a model-wide setting but the entry was tuned at one
    symmetric bucket; the kernel runs at each chain's OWN pad, so a
    multi-bucket training plan (or an asymmetric serving bucket) can
    reach pads the tuned block count does not divide — which is a trace-
    time ValueError, not a slow path. Callers pass every distinct pad
    their plan can compile (both chain dims). Returns ``(adopted, note)``
    where ``note`` is non-empty when the grid was dropped."""
    if adopted is None:
        return adopted, ""
    c = adopted.config
    if c.pallas_fwd_blocks is None and c.pallas_bwd_blocks is None:
        return adopted, ""
    from deepinteract_tpu.ops.pallas_attention import edge_block_options

    legal = all(
        (c.pallas_fwd_blocks is None
         or c.pallas_fwd_blocks in edge_block_options(p, knn))
        and (c.pallas_bwd_blocks is None
             or c.pallas_bwd_blocks in edge_block_options(p, knn,
                                                          backward=True))
        for p in pads)
    if legal:
        return adopted, ""
    stripped = dataclasses.replace(
        adopted,
        config=dataclasses.replace(c, pallas_fwd_blocks=None,
                                   pallas_bwd_blocks=None))
    return stripped, (" (tuned Pallas grid NOT applied: illegal for at "
                      "least one bucket pad in the plan)")


def respect_explicit(adopted: Optional[Adopted], *, stem: bool = False,
                     dtype: bool = False):
    """Strip the stem/precision knobs from an adoption when the operator
    set them EXPLICITLY on the CLI (cli/args.py ``pinned_knobs``): a
    stored trial then keeps its perf knobs but cannot silently override a
    typed --interaction_stem / --compute_dtype (dtype is additionally an
    accuracy-affecting knob). None fields already mean "keep the caller's
    config" (tuning/space.py)."""
    if adopted is None or not (stem or dtype):
        return adopted
    updates = {}
    if stem and adopted.config.interaction_stem is not None:
        updates["interaction_stem"] = None
    if dtype and adopted.config.compute_dtype is not None:
        updates["compute_dtype"] = None
    if not updates:
        return adopted
    return dataclasses.replace(
        adopted, config=dataclasses.replace(adopted.config, **updates))


def adopt_model_config(model_cfg, adopted: Optional[Adopted]):
    """Apply the model-side tuned knobs (remat, remat_policy, scan_chunks,
    Pallas blocks). Returns ``model_cfg`` unchanged when nothing was
    adopted."""
    if adopted is None:
        return model_cfg
    return apply_to_model_config(model_cfg, adopted.config)


def adopt_loop_config(loop_cfg, adopted: Optional[Adopted]):
    """Apply the loop-side tuned knobs (scan_k -> steps_per_dispatch).
    Fallback adoptions keep the caller's scan_k (see module doc)."""
    if adopted is None or not adopted.scan_k_applies:
        return loop_cfg
    return apply_to_loop_config(loop_cfg, adopted.config)


def adopt_optim_config(optim_cfg, adopted: Optional[Adopted]):
    """Apply the optimizer-side tuned knob (microbatch ->
    accumulate_steps). The tuner measured the objective WITH this setting,
    so a consumer that skipped it would run a config nobody measured."""
    if adopted is None:
        return optim_cfg
    return apply_to_optim_config(optim_cfg, adopted.config)
