"""Declarative search space over the knobs the codebase already exposes.

Every axis here names a real configuration surface that existed before the
tuner — nothing is invented for tuning's sake:

* ``remat`` / ``remat_policy`` — decoder rematerialization
  (``models/decoder.py:DecoderConfig``; the b8 p128 HBM lever).
* ``scan_k`` — train steps scanned per device dispatch
  (``training/loop.py:LoopConfig.steps_per_dispatch``; the single biggest
  single-chip throughput lever through a remote-dispatch transport).
* ``microbatch`` — gradient-accumulation microbatches
  (``training/optim.py:OptimConfig.accumulate_steps``).
* ``scan_chunks`` — decoder chunk scan vs unroll
  (``DecoderConfig.scan_chunks``; ~5-8x compile-time difference).
* ``pallas_fwd_blocks`` / ``pallas_bwd_blocks`` — edge-block grid sizes of
  the fused attention kernel (``ops/pallas_attention.py``; None = the
  kernel's built-in heuristic).
* ``diagonal_buckets`` — loader bucket diagonalization
  (``data/loader.py``; compile count vs pad FLOPs trade).
* ``interaction_stem`` — factorized vs materialized first decoder layer
  (``models/stem.py``; the pair-tensor HBM lever — same params, same
  numerics up to float association; searched as concrete values, None =
  keep the caller's config — the pinning sentinel only).
* ``compute_dtype`` — the end-to-end activation dtype policy
  (``models/policy.py``): a DECLARED axis (TrialConfig + adoption honor
  it) that is not auto-searched — a latency-only objective would always
  pick bf16 and silently flip an accuracy-affecting knob; see
  ``axes_for_bucket``.

The space is bucket- and device-aware: axes that cannot apply to a given
``(batch, pad)`` bucket (a Pallas grid the kernel rejects, a scan_k of 1
"searched" twice) are pruned at enumeration time, so the search loop never
wastes a trial on a config that cannot run.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from deepinteract_tpu.models.stem import validate_stem


@dataclasses.dataclass(frozen=True)
class TrialConfig:
    """One point in the search space. ``None`` on the Pallas axes means
    "use the kernel's built-in block heuristic"."""

    remat: bool = False
    remat_policy: str = "full"
    scan_k: int = 8
    microbatch: int = 1
    scan_chunks: bool = True
    pallas_fwd_blocks: Optional[int] = None
    pallas_bwd_blocks: Optional[int] = None
    diagonal_buckets: bool = False
    # None on the stem/dtype axes means "keep the caller's configured
    # value" — adoption must never silently override an explicit
    # --interaction_stem / --compute_dtype with a searched default.
    interaction_stem: Optional[str] = None
    compute_dtype: Optional[str] = None
    # Serving-mesh placement for the bucket: a DECLARED axis like
    # compute_dtype (TrialConfig + the engine's adoption honor it, and
    # the store key can carry the mesh topology — see ``bucket_key``)
    # that is not auto-searched: the single-process tuner has no mesh to
    # measure under. None = the engine's placement policy
    # (serving/fleet.mesh_placement); "data"/"pair" pin the bucket.
    mesh_placement: Optional[str] = None

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict) -> "TrialConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def label(self) -> str:
        parts = [
            f"remat={'off' if not self.remat else self.remat_policy}",
            f"scan_k={self.scan_k}",
        ]
        if self.microbatch > 1:
            parts.append(f"micro={self.microbatch}")
        if not self.scan_chunks:
            parts.append("unrolled")
        if self.pallas_fwd_blocks is not None:
            parts.append(f"pfwd={self.pallas_fwd_blocks}")
        if self.pallas_bwd_blocks is not None:
            parts.append(f"pbwd={self.pallas_bwd_blocks}")
        if self.diagonal_buckets:
            parts.append("diag")
        if self.interaction_stem is not None:
            parts.append(f"stem-{self.interaction_stem}")
        if self.compute_dtype is not None:
            parts.append(self.compute_dtype)
        if self.mesh_placement is not None:
            parts.append(f"mesh-{self.mesh_placement}")
        return ",".join(parts)


@dataclasses.dataclass(frozen=True)
class Axis:
    """One tunable dimension: a name (TrialConfig field) and its candidate
    values for the bucket under search."""

    name: str
    values: Tuple
    description: str = ""


def default_trial() -> TrialConfig:
    """The configuration every entry point hardcodes today — the A/B
    baseline the tuner must beat (and bench's 'default' row)."""
    return TrialConfig()


def axes_for_bucket(batch: int, pad: int, device_kind: str = "cpu",
                    knn: int = 20, tune_pallas: Optional[bool] = None,
                    include_loader_axis: bool = True,
                    base_stem: str = "factorized") -> List[Axis]:
    """The applicable axes for one ``(batch, pad)`` bucket.

    ``tune_pallas`` defaults to "is this a TPU" — off-TPU the kernel runs
    in interpret mode only and block timings are meaningless. p256 remat
    is forced ON (the scanned decoder backward OOMs a 16G chip without
    it, bench.py bucket table), so the remat axis collapses there.
    ``include_loader_axis=False`` drops ``diagonal_buckets`` — the
    single-bucket synthetic measurement cannot see its effect (it changes
    corpus-level compile counts and run lengths, not one step's time), so
    only a corpus-aware caller should search it. ``base_stem`` is the
    caller's CONFIGURED interaction stem: the stem axis searches the two
    CONCRETE stems, base first — stored trials must name the stem they
    measured, because the store key (``model_signature``) deliberately
    excludes the stem and a later consumer may be configured with the
    OTHER one; a relative None would then silently resolve to a stem the
    trial never ran. None stays reserved for "keep the caller's config"
    (the pinning sentinel, ``consume.respect_explicit``).
    """
    if tune_pallas is None:
        tune_pallas = "TPU" in device_kind or "tpu" in device_kind
    axes: List[Axis] = []
    if pad >= 256:
        axes.append(Axis("remat", (True,),
                         "forced: p256 backward OOMs without remat"))
    else:
        axes.append(Axis("remat", (False, True), "decoder rematerialization"))
    axes.append(Axis("remat_policy", ("full", "convs"),
                     "what remat saves vs recomputes (ignored remat=off)"))
    axes.append(Axis("scan_k", (1, 4, 8, 16),
                     "train steps per device dispatch"))
    # NOT searched: the microbatch (grad-accumulation) axis. It is part
    # of the declared space (TrialConfig field + apply_to_optim_config)
    # but the ms-per-scanned-step objective cannot judge it fairly — an
    # accumulation step is only a FRACTION of an optimizer update, so
    # microbatch=2 measures faster per step while halving updates per
    # epoch. Searching it needs an updates-aware (or loss-per-wall)
    # objective; until then consumers only ever see microbatch=1.
    axes.append(Axis("scan_chunks", (True, False),
                     "decoder chunk scan vs unroll"))
    if tune_pallas:
        from deepinteract_tpu.ops.pallas_attention import edge_block_options

        fwd = edge_block_options(pad, knn, backward=False)
        bwd = edge_block_options(pad, knn, backward=True)
        if len(fwd) > 1:
            axes.append(Axis("pallas_fwd_blocks", (None,) + fwd,
                             "forward edge-block grid size (None = heuristic)"))
        if len(bwd) > 1:
            axes.append(Axis("pallas_bwd_blocks", (None,) + bwd,
                             "backward edge-block grid size (None = heuristic)"))
    if include_loader_axis:
        axes.append(Axis("diagonal_buckets", (False, True),
                         "loader bucket diagonalization"))
    # NOT searched: the compute_dtype (precision-policy) axis. Like the
    # microbatch axis above it is part of the declared space (TrialConfig
    # field + apply_to_model_config honor it), but the ms-per-step
    # objective cannot judge it fairly — bf16 nearly always wins pure
    # step time while changing the numerics, so a latency-only search
    # would silently flip an accuracy-affecting knob. bench's
    # precision_ab section is the evidence surface; an operator (or a
    # future accuracy-aware objective) can still store entries with it
    # set. interaction_stem IS searched: the two stems are numerics-
    # equivalent (tests/test_stem.py parity), so a speed objective judges
    # them fairly — and always as concrete values, so the persisted
    # winner is base-config-independent (see the docstring).
    other_stem = ("materialized" if validate_stem(base_stem) == "factorized"
                  else "factorized")
    axes.append(Axis("interaction_stem", (base_stem, other_stem),
                     f"first decoder layer: the configured {base_stem} stem "
                     f"vs {other_stem} (models/stem.py)"))
    return axes


def enumerate_trials(axes: Sequence[Axis], max_trials: int = 64,
                     ) -> List[TrialConfig]:
    """Deduplicated grid over ``axes``, default-first, capped.

    Degenerate combinations collapse (``remat=False`` makes every
    ``remat_policy`` identical), so the dedup happens on the CANONICAL
    form — the same physical config never runs twice. The full grid is
    ordered default-config-first (successive halving then always measures
    the baseline in rung 0) and truncated to ``max_trials`` by cycling
    axis-distance from the default: near-default configs first, so a tight
    budget explores one-knob deviations before exotic corners.
    """
    names = [a.name for a in axes]
    seen = set()
    trials: List[TrialConfig] = []
    for combo in itertools.product(*[a.values for a in axes]):
        trial = TrialConfig(**dict(zip(names, combo)))
        trial = canonicalize(trial)
        if trial in seen:
            continue
        seen.add(trial)
        trials.append(trial)
    base = canonicalize(default_trial())

    def distance(t: TrialConfig) -> Tuple[int, str]:
        d = sum(
            1 for f in dataclasses.fields(TrialConfig)
            if getattr(t, f.name) != getattr(base, f.name)
        )
        return (d, t.label())

    trials.sort(key=distance)
    if base in seen and trials[0] != base:
        trials.remove(base)
        trials.insert(0, base)
    return trials[:max_trials]


def canonicalize(trial: TrialConfig) -> TrialConfig:
    """Collapse don't-care fields so physically identical configs compare
    equal (remat off => policy irrelevant). The stem axis needs no
    collapsing here: ``axes_for_bucket`` searches the two concrete stems,
    so no value aliases another (None appears only in pinned/hand-written
    configs, never in a search grid)."""
    if not trial.remat:
        return dataclasses.replace(trial, remat_policy="full")
    return trial


# ---------------------------------------------------------------------------
# Applying a trial to the real config objects
# ---------------------------------------------------------------------------


def apply_to_model_config(model_cfg, trial: TrialConfig):
    """A new ``ModelConfig`` with the trial's model-side knobs applied
    (decoder remat/policy/scan_chunks, Pallas block grid, interaction
    stem, compute-dtype policy)."""
    decoder = dataclasses.replace(
        model_cfg.decoder,
        remat=trial.remat,
        remat_policy=trial.remat_policy,
        scan_chunks=trial.scan_chunks,
    )
    gnn = dataclasses.replace(
        model_cfg.gnn,
        pallas_fwd_blocks=trial.pallas_fwd_blocks,
        pallas_bwd_blocks=trial.pallas_bwd_blocks,
    )
    out = dataclasses.replace(model_cfg, decoder=decoder, gnn=gnn)
    # None = keep the caller's configured stem/precision: an explicit
    # --interaction_stem/--compute_dtype must never be silently
    # overridden by a searched default.
    if trial.interaction_stem is not None:
        out = dataclasses.replace(out, interaction_stem=trial.interaction_stem)
    if trial.compute_dtype is not None:
        # The model-level policy pushes the dtype into every sub-config
        # (ModelConfig.__post_init__).
        out = dataclasses.replace(out, compute_dtype=trial.compute_dtype)
    return out


def apply_to_loop_config(loop_cfg, trial: TrialConfig):
    """A new ``LoopConfig`` with the trial's loop-side knobs applied."""
    return dataclasses.replace(loop_cfg, steps_per_dispatch=trial.scan_k)


def apply_to_optim_config(optim_cfg, trial: TrialConfig):
    return dataclasses.replace(optim_cfg, accumulate_steps=trial.microbatch)


def model_signature(model_cfg) -> str:
    """Stable signature of the ARCHITECTURE a tuning entry applies to.

    Deliberately excludes the tunable axes themselves (remat, scan_chunks,
    Pallas blocks, interaction stem, compute dtype — the last two became
    searched axes with the factorized-stem/bf16-policy work, so tuned and
    default builds of one model share one store entry) and includes
    everything else that changes the compiled graphs' math: layer counts,
    widths, heads, decoder chunks/channels, attention mode, module type."""
    g, d = model_cfg.gnn, model_cfg.decoder
    return (
        f"{model_cfg.gnn_layer_type}-{model_cfg.interact_module_type}"
        f"-gl{g.num_layers}h{g.hidden}a{g.num_heads}-{g.attention_mode}"
        f"-il{d.num_chunks}c{d.num_channels}"
        + ("-tiled" if model_cfg.tile_pair_map else "")
    )


def bucket_key(batch: int, pad: int, mesh_shape=None) -> str:
    """Store-key bucket token. ``mesh_shape`` (a ``(data, pair)`` tuple;
    None/(1, 1) = single-device) suffixes the key so entries tuned under
    different serving topologies never alias — a placement/grid measured
    on a 2x4 mesh says nothing about the 1-chip build of the same
    bucket. Single-device keys are unchanged, so every existing store
    resolves exactly as before."""
    key = f"b{batch}_p{pad}"
    if mesh_shape is not None and tuple(mesh_shape) != (1, 1):
        key += f"_m{int(mesh_shape[0])}x{int(mesh_shape[1])}"
    return key
