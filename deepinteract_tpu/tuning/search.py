"""Budget-aware successive-halving search with incremental persistence.

The search loop is deliberately boring and interruption-obsessed, because
the measurement environment is not: compiles take 48-247 s, the transport
drops responses, and the driver enforces wall-clock kills. Rules:

* **Successive halving** (Jamieson & Talwalkar): rung 0 measures every
  candidate at a small fidelity (few timed iterations), keeps the best
  ``1/eta`` fraction, and re-measures survivors at ``eta``x the fidelity —
  cheap configs die cheaply, the winner is measured most carefully.
* **Hard per-trial deadline.** Each measurement runs under a SIGALRM
  timer (main thread; no-op elsewhere): an over-budget trial becomes a
  recorded ``timeout``, not a dead tuning run. Honesty note: CPython only
  runs the handler between bytecodes, so the alarm preempts Python-level
  work and interruptible syscalls — a compile wedged inside native XLA
  code is NOT preemptible in-process (run the whole tune under an outer
  ``timeout(1)`` for that; the store is kill-safe by construction, and a
  second SIGTERM/SIGINT escalates to an immediate abort).
* **Incremental persistence.** The store is rewritten (atomically) after
  EVERY trial — a SIGTERM, deadline kill, or crash keeps everything
  measured so far, marked ``partial`` (the BENCH_r03/r04 rc=124 lesson).
* **Observable.** Every trial emits an ``obs`` span
  (``tuning_trial``) and ``di_tuning_*`` counters, so a live tuning run
  reports progress through the same telemetry as training and serving.

The measure function is injected (``measure(trial, fidelity) -> (value,
detail)``), which is what makes the loop testable with a fake timer and
lets ``cli.tune --dry_run`` exercise the whole pipeline on CPU in
milliseconds.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import signal
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deepinteract_tpu.obs import metrics as obs_metrics
from deepinteract_tpu.obs import spans as obs_spans
from deepinteract_tpu.tuning.space import TrialConfig, canonicalize, default_trial
from deepinteract_tpu.tuning.store import TuningStore

_TRIALS = obs_metrics.counter(
    "di_tuning_trials_total", "Tuning trials by outcome",
    labelnames=("status",))
_TRIAL_SECONDS = obs_metrics.histogram(
    "di_tuning_trial_seconds", "Wall time of each tuning trial")
_RUNGS = obs_metrics.counter(
    "di_tuning_rungs_total", "Completed successive-halving rungs")
_STORE_WRITES = obs_metrics.counter(
    "di_tuning_store_writes_total", "Incremental tuning-store persists")

MeasureFn = Callable[[TrialConfig, int], Tuple[float, Dict]]


class TrialTimeout(Exception):
    """A trial hit its hard wall-clock deadline."""


class SearchStopped(Exception):
    """SIGTERM/SIGINT requested a stop; everything measured is persisted."""


@contextlib.contextmanager
def _hard_deadline(seconds: Optional[float]):
    """SIGALRM-based per-trial deadline. Engages only on the main thread
    of a Unix process (signal handlers cannot be installed elsewhere);
    otherwise the deadline is advisory via the caller's budget check. The
    timer is always cancelled on exit, so a fast trial cannot be killed
    by a stale alarm. Scope: the raise lands at the next bytecode — it
    interrupts Python-level work and interruptible syscalls, not a
    compile wedged inside native code (see module docstring)."""
    if (not seconds or seconds <= 0
            or threading.current_thread() is not threading.main_thread()
            or not hasattr(signal, "SIGALRM")):
        yield
        return

    def _raise(signum, frame):
        raise TrialTimeout(f"trial exceeded {seconds:.0f}s deadline")

    old = signal.signal(signal.SIGALRM, _raise)
    signal.setitimer(signal.ITIMER_REAL, float(seconds))
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, old)


@dataclasses.dataclass
class TrialResult:
    config: TrialConfig
    status: str  # 'ok' | 'timeout' | 'error' | 'skipped'
    value: Optional[float] = None  # objective, lower is better
    rung: int = 0
    fidelity: int = 0
    seconds: float = 0.0
    detail: Optional[Dict] = None
    error: Optional[str] = None

    def to_dict(self) -> Dict:
        d = {
            "config": self.config.to_dict(),
            "status": self.status,
            "rung": self.rung,
            "fidelity": self.fidelity,
            "seconds": round(self.seconds, 3),
        }
        if self.value is not None:
            d["value"] = self.value
        if self.error:
            d["error"] = self.error
        return d


@dataclasses.dataclass
class SearchResult:
    best: Optional[TrialConfig]
    best_value: Optional[float]
    default_value: Optional[float]
    results: List[TrialResult]
    partial: bool
    stopped_reason: Optional[str] = None

    @property
    def completed(self) -> int:
        return sum(1 for r in self.results if r.status == "ok")


class SuccessiveHalvingSearch:
    """Drives one bucket's search and persists into ``store`` under
    ``store_key`` after every trial."""

    def __init__(
        self,
        measure: MeasureFn,
        *,
        store: Optional[TuningStore] = None,
        store_key: Optional[str] = None,
        objective: str = "train_scan_ms_per_step",
        eta: int = 3,
        base_fidelity: int = 3,
        max_rungs: int = 3,
        trial_deadline_s: Optional[float] = None,
        total_budget_s: Optional[float] = None,
        install_signal_handlers: bool = True,
        log: Callable[[str], None] = lambda _m: None,
        baseline: Optional[TrialConfig] = None,
    ):
        self.measure = measure
        self.store = store
        self.store_key = store_key
        self.objective = objective
        self.eta = max(2, int(eta))
        self.base_fidelity = max(1, int(base_fidelity))
        self.max_rungs = max(1, int(max_rungs))
        self.trial_deadline_s = trial_deadline_s
        self.total_budget_s = total_budget_s
        self.install_signal_handlers = install_signal_handlers
        self.log = log
        # The trial whose measurement is "the default" for speedup
        # reporting. Callers whose grid names config-dependent knobs
        # concretely (the stem axis, tuning/space.py) must pass the
        # concretized baseline — default_trial() alone would match no
        # trial there.
        self.baseline = canonicalize(baseline or default_trial())
        self._stop = threading.Event()
        self._stop_reason: Optional[str] = None
        self._t0 = time.monotonic()

    # -- interruption ------------------------------------------------------

    def request_stop(self, reason: str) -> None:
        """Cooperative stop: honored between trials; the in-flight trial
        still finishes (or hits its own deadline). Everything measured is
        already on disk by then."""
        if not self._stop.is_set():
            self._stop_reason = reason
            self._stop.set()

    @contextlib.contextmanager
    def _signals(self):
        if (not self.install_signal_handlers
                or threading.current_thread() is not threading.main_thread()):
            yield
            return
        old = {}

        def handler(signum, frame):
            name = signal.Signals(signum).name
            if self._stop.is_set():
                # Second signal: the operator means NOW. Everything
                # measured is already persisted, so an immediate abort
                # loses nothing — and a trial wedged in native code
                # would never reach the cooperative stop point.
                raise KeyboardInterrupt(
                    f"second {name}: aborting immediately "
                    "(store holds every completed trial)")
            self.request_stop(f"signal {name}")

        for sig in (signal.SIGTERM, signal.SIGINT):
            old[sig] = signal.signal(sig, handler)
        try:
            yield
        finally:
            for sig, prev in old.items():
                signal.signal(sig, prev)

    def _remaining_s(self) -> float:
        if self.total_budget_s is None:
            return math.inf
        return self.total_budget_s - (time.monotonic() - self._t0)

    # -- persistence -------------------------------------------------------

    def _persist(self, results: List[TrialResult], trials_total: int,
                 partial: bool) -> None:
        if self.store is None or self.store_key is None:
            return
        ok = [r for r in results if r.status == "ok" and r.value is not None]
        entry: Dict = {
            "objective": self.objective,
            "trials_completed": len(ok),
            "trials_total": trials_total,
            "partial": partial,
            "measured_at": time.time(),
            "trial_log": [r.to_dict() for r in results],
        }
        if ok:
            # Highest-rung first, then lowest objective: a rung-2 value is
            # measured at eta^2 the fidelity of a rung-0 one and wins ties.
            best = min(ok, key=lambda r: (-r.rung, r.value))
            entry["config"] = best.config.to_dict()
            entry["value"] = best.value
            defaults = [r for r in ok
                        if canonicalize(r.config) == self.baseline]
            if defaults:
                entry["default_value"] = min(
                    defaults, key=lambda r: (-r.rung, r.value)).value
        else:
            existing = self.store.get(self.store_key)
            if existing is not None and "config" in existing:
                # A refresh run that has measured NOTHING yet must not
                # destroy a previously measured winner: keep the old
                # entry and attach this search's (so-far-empty) record.
                entry = dict(existing, last_failed_search=entry)
        self.store.put(self.store_key, entry)
        self.store.save()
        _STORE_WRITES.inc()

    # -- the loop ----------------------------------------------------------

    def run(self, trials: Sequence[TrialConfig]) -> SearchResult:
        results: List[TrialResult] = []
        trials_total = len(trials)
        survivors = list(trials)
        partial = False
        with self._signals():
            for rung in range(self.max_rungs):
                fidelity = self.base_fidelity * (self.eta ** rung)
                rung_results: List[TrialResult] = []
                for trial in survivors:
                    if self._stop.is_set():
                        partial = True
                        break
                    if self._remaining_s() <= 0:
                        self.request_stop("total budget exhausted")
                        partial = True
                        break
                    res = self._run_trial(trial, rung, fidelity)
                    results.append(res)
                    if res.status == "ok":
                        rung_results.append(res)
                    # Incremental persistence: the store is valid after
                    # every trial, kill-safe by construction.
                    self._persist(results, trials_total, partial=True)
                else:
                    _RUNGS.inc()
                    survivors = self._select(rung_results)
                    if not survivors:
                        break
                    # A lone survivor still gets its remaining rungs: the
                    # winner's published value comes from the HIGHEST
                    # fidelity measured (max_rungs bounds the cost).
                    continue
                break  # inner break (stop/budget) propagates out
        ok = [r for r in results if r.status == "ok" and r.value is not None]
        best = min(ok, key=lambda r: (-r.rung, r.value)) if ok else None
        defaults = [r for r in ok if canonicalize(r.config) == self.baseline]
        default_value = (min(defaults, key=lambda r: (-r.rung, r.value)).value
                         if defaults else None)
        partial = partial or self._stop.is_set()
        self._persist(results, trials_total, partial=partial)
        return SearchResult(
            best=best.config if best else None,
            best_value=best.value if best else None,
            default_value=default_value,
            results=results,
            partial=partial,
            stopped_reason=self._stop_reason,
        )

    def _select(self, rung_results: List[TrialResult]) -> List[TrialConfig]:
        if not rung_results:
            return []
        keep = max(1, len(rung_results) // self.eta)
        ranked = sorted(rung_results,
                        key=lambda r: (r.value, r.config.label()))
        return [r.config for r in ranked[:keep]]

    def _run_trial(self, trial: TrialConfig, rung: int,
                   fidelity: int) -> TrialResult:
        t0 = time.perf_counter()
        status, value, detail, err = "ok", None, None, None
        with obs_spans.span("tuning_trial", config=trial.label(),
                            rung=rung, fidelity=fidelity):
            try:
                with _hard_deadline(self.trial_deadline_s):
                    value, detail = self.measure(trial, fidelity)
                value = float(value)
                if not math.isfinite(value):
                    status, err = "error", f"non-finite objective {value}"
                    value = None
            except TrialTimeout as exc:
                status, err = "timeout", str(exc)
            except SearchStopped as exc:
                status, err = "skipped", str(exc)
                self.request_stop(str(exc))
            except Exception as exc:  # a failed config is data, not fatal
                status = "error"
                err = str(exc).splitlines()[0][:300] if str(exc) else repr(exc)
        seconds = time.perf_counter() - t0
        _TRIALS.inc(status=status)
        _TRIAL_SECONDS.observe(seconds)
        self.log(
            f"trial rung={rung} fid={fidelity} [{trial.label()}]: "
            + (f"{value:.4g} ({self.objective})" if value is not None
               else f"{status}: {err}")
            + f" [{seconds:.1f}s]")
        return TrialResult(config=trial, status=status, value=value,
                           rung=rung, fidelity=fidelity, seconds=seconds,
                           detail=detail, error=err)
