"""Autotuning subsystem: persisted per-device search over the perf knobs
every entry point used to hardcode.

Layout:

* :mod:`~deepinteract_tpu.tuning.space` — declarative search space
  (remat / scan_k / microbatch / scan_chunks / Pallas blocks / bucket
  diagonalization) and the apply-to-config helpers.
* :mod:`~deepinteract_tpu.tuning.timing` — the hardened differenced
  measurement protocol, shared with ``bench.py`` so tuner and bench can
  never disagree on how time is measured.
* :mod:`~deepinteract_tpu.tuning.search` — budget-aware successive
  halving with hard per-trial deadlines and after-every-trial
  persistence.
* :mod:`~deepinteract_tpu.tuning.store` — the versioned on-disk store
  keyed by ``(device_kind, jax version, model signature, bucket)``.
* :mod:`~deepinteract_tpu.tuning.measure` — real (device) and dry-run
  (cost-model) trial measurement functions.
* :mod:`~deepinteract_tpu.tuning.consume` — the one resolution path
  train / serve / bench use to adopt a tuned config.
* :mod:`~deepinteract_tpu.tuning.compile_cache` — the shared
  ``--compile_cache_dir`` plumbing + hit/miss telemetry.

Entry point: ``python -m deepinteract_tpu.cli.tune`` (see README
"Autotuning").
"""

from deepinteract_tpu.tuning.consume import Adopted, lookup, lookup_path
from deepinteract_tpu.tuning.search import SearchResult, SuccessiveHalvingSearch
from deepinteract_tpu.tuning.space import TrialConfig, bucket_key, model_signature
from deepinteract_tpu.tuning.store import (
    SCHEMA_VERSION,
    StoreSchemaError,
    TuningStore,
    runtime_key,
)

__all__ = [
    "Adopted",
    "SCHEMA_VERSION",
    "SearchResult",
    "StoreSchemaError",
    "SuccessiveHalvingSearch",
    "TrialConfig",
    "TuningStore",
    "bucket_key",
    "lookup",
    "lookup_path",
    "model_signature",
    "runtime_key",
]
