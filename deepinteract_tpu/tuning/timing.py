"""Shared measurement core: the hardened differenced-timing protocol.

Extracted from ``bench.py`` (which now imports it) so the autotuner and the
benchmark can never disagree on how time is measured. Every lesson baked
into the protocol travels with it:

* ``materialize``: ``block_until_ready`` alone proved untrustworthy through
  the axon PJRT tunnel (r2/r3 recorded physically-impossible >1.0 MFU —
  the loop was timing dispatch, not execution). Fetching actual bytes to
  the host cannot return before the producing execution finishes.
* ``time_compiled``: per rep, time k calls then 2k calls (each run ending
  in a host fetch) and report per-call = (t_2k - t_k) / k. The subtraction
  cancels every fixed cost in the timed region — pipeline fill, the host
  fetch itself, per-dispatch client latency — so the figure is device
  execution time. ``overhead_ms`` and ``linearity`` ride along so a
  broken-timer regime is visible in the output instead of silently
  inflating throughput.
* ``compile_with_retry``: the axon tunnel's remote_compile sporadically
  drops the response mid-read; retrying costs seconds, losing a bucket
  costs a driver round.
* ``mfu_guard_violations``: analytic MFU is <= 1 by construction, so > 1
  can only mean the timing is wrong — callers fail the measurement loudly
  rather than publish an impossible number.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

# Peak matmul throughput by device kind, for MFU (bf16 peak: XLA runs f32
# convs through bf16-multipass MXU kernels, so bf16 peak is the roofline
# either way). DI_PEAK_FLOPS overrides.
PEAK_FLOPS_BY_KIND = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "TPU v6e": 918e12,
}

DEFAULT_WARMUP = 2
DEFAULT_ITERS = 12
DEFAULT_REPS = 3

# Healthy band for the per-rep linearity ratio t_2k / t_k: ~2.0 means the
# differenced subtraction is operating on an almost-pure per-call signal;
# toward 1.0 the fixed overhead dominates and the subtraction amplifies
# noise; above ~2.5 the run is super-linear (interference, thermal, or
# caching effects). BENCH_r05 shipped headline numbers at linearity
# 1.53-1.93 without comment — samples that unstable now carry an explicit
# ``timing_warning`` so consumers (bench contract line,
# tools/check_perf_regression.py) can widen their tolerance instead of
# treating the figure as decision-grade.
LINEARITY_HEALTHY_BAND = (1.55, 2.45)
# Per-rep spread (max - min linearity across reps) beyond which the
# samples disagree about the measurement regime itself.
LINEARITY_SPREAD_LIMIT = 0.35


def resolve_peak_flops(device_kind: str) -> float:
    if "DI_PEAK_FLOPS" in os.environ:
        return float(os.environ["DI_PEAK_FLOPS"])
    return PEAK_FLOPS_BY_KIND.get(device_kind, 197e12)


def is_transient_compile_error(exc: Exception) -> bool:
    """Failure signatures of the axon PJRT tunnel worth retrying (shared by
    every retry loop so a new signature only needs classifying once)."""
    msg = str(exc)
    return "remote_compile" in msg or "INTERNAL" in msg


def compile_with_retry(fn, args, attempts: int = 3,
                       log: Callable[[str], None] = lambda _m: None):
    """lower+compile with retries for transient tunnel failures."""
    for attempt in range(attempts):
        try:
            return fn.lower(*args).compile()
        except Exception as exc:
            if attempt == attempts - 1 or not is_transient_compile_error(exc):
                raise
            log(f"transient compile failure (attempt {attempt + 1}): "
                f"{str(exc).splitlines()[0][:200]}; retrying")
            time.sleep(5.0 * (attempt + 1))


def materialize(out) -> float:
    """Force HOST materialization of a value derived from ``out`` (see
    module docstring — the anti-dispatch-timing guarantee)."""
    import jax

    leaves = jax.tree_util.tree_leaves(out)
    leaf = min(leaves, key=lambda a: int(getattr(a, "size", 1 << 62)))
    return float(np.asarray(jax.device_get(leaf)).ravel()[0])


def arg_variants(args, n: int):
    """n device-resident copies of ``args``, each with one float leaf
    perturbed by a harmless epsilon — defeats any same-input caching or
    result reuse between timed calls.

    All UNPERTURBED leaves are device_put ONCE and shared between the
    variants: a flagship train state is ~3.4k leaves, and per-leaf
    transfers through the axon tunnel cost ~10-100 ms each — full copies
    spent minutes per section just shipping identical bytes."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(args)
    idx = next(
        (i for i, l in enumerate(leaves)
         if hasattr(l, "dtype") and jnp.issubdtype(np.asarray(l).dtype, jnp.floating)),
        None,
    )

    def put(leaf):
        # Leaves already resident on an accelerator (e.g. a train state
        # produced by the jitted init) are kept as-is: re-putting ~3.4k
        # state leaves costs one tunnel RPC each, minutes per section.
        if isinstance(leaf, jax.Array):
            try:
                if all(d.platform != "cpu" for d in leaf.devices()):
                    return leaf
            except Exception:
                pass
        return jax.device_put(leaf)

    shared = [put(l) for l in leaves]
    variants = []
    for j in range(n):
        ls = list(shared)
        if idx is not None and j > 0:
            ls[idx] = jax.device_put(np.asarray(leaves[idx]) + np.float32(j * 1e-6))
        variants.append(jax.tree_util.tree_unflatten(treedef, ls))
    jax.block_until_ready(variants)
    return variants


def time_compiled(fn, args, iters: int = DEFAULT_ITERS,
                  reps: int = DEFAULT_REPS, warmup: int = DEFAULT_WARMUP,
                  log: Callable[[str], None] = lambda _m: None,
                  ) -> Tuple[float, Dict, Optional[float]]:
    """(compile_s, timing dict, xla_flops) for a jitted fn under the
    differenced protocol (module docstring)."""
    import jax

    t0 = time.perf_counter()
    compiled = compile_with_retry(fn, args, log=log)
    compile_s = time.perf_counter() - t0
    flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass
    memory = None
    try:
        # Compiled memory footprint rides along in the timing dict (the
        # compiled object never leaves this function): temp bytes are the
        # activation working set — what the factorized interaction stem
        # exists to shrink (bench's per-bucket `interaction_bytes`).
        ma = compiled.memory_analysis()
        if ma is not None:
            memory = {
                "temp_size_in_bytes": int(ma.temp_size_in_bytes),
                "argument_size_in_bytes": int(ma.argument_size_in_bytes),
                "output_size_in_bytes": int(ma.output_size_in_bytes),
            }
    except Exception:
        pass

    variants = arg_variants(args, 4)

    def run(ncalls: int) -> float:
        t0 = time.perf_counter()
        out = None
        for i in range(ncalls):
            out = compiled(*variants[i % len(variants)])
        jax.block_until_ready(out)
        materialize(out)
        return time.perf_counter() - t0

    for _ in range(warmup):
        run(1)
    k = max(1, iters // reps)
    samples, overheads, linearity = [], [], []
    clamped = 0
    for _ in range(reps):
        t1 = run(k)
        t2 = run(2 * k)
        per_call = (t2 - t1) / k
        if per_call <= 1e-9:  # noisy rep: t2 <= t1 (ADVICE r4 item 4)
            clamped += 1
            per_call = 1e-9
        samples.append(per_call)
        overheads.append(t1 - k * per_call)
        linearity.append(t2 / t1 if t1 > 0 else float("inf"))
    finite_lin = [v for v in linearity if np.isfinite(v)]
    spread = (float(max(finite_lin) - min(finite_lin))
              if len(finite_lin) > 1 else 0.0)
    timing = {
        "median": float(np.median(samples)),
        "min": float(np.min(samples)),
        "mean": float(np.mean(samples)),
        "samples": len(samples),
        "calls_per_sample": k,
        "overhead_ms": float(np.median(overheads)) * 1e3,
        "linearity": float(np.median(linearity)),
        "linearity_spread": spread,
        "clamped_samples": clamped,
        "protocol": "differenced+host-fetch",
    }
    warning = timing_warning(timing)
    if warning:
        timing["timing_warning"] = warning
    if memory is not None:
        timing["memory"] = memory
    return compile_s, timing, flops


def timing_warning(timing: Dict) -> str:
    """Non-empty description when a differenced-timing dict looks
    UNSTABLE — clamped reps, median linearity outside the healthy band,
    or reps disagreeing with each other (the BENCH_r05 1.53-1.93 case).
    Consumers: bench lifts this into the section detail and contract
    line; tools/check_perf_regression.py widens its tolerance for keys
    measured under a warning."""
    lo, hi = LINEARITY_HEALTHY_BAND
    problems = []
    if timing.get("clamped_samples", 0) > 0:
        problems.append(
            f"{timing['clamped_samples']} clamped sample(s) (t_2k <= t_k)")
    lin = timing.get("linearity")
    if lin is not None and not lo <= lin <= hi:
        problems.append(
            f"median linearity {lin:.2f} outside healthy band "
            f"[{lo}, {hi}] (ideal 2.0 — differenced signal degraded)")
    spread = timing.get("linearity_spread")
    if spread is not None and spread > LINEARITY_SPREAD_LIMIT:
        problems.append(
            f"linearity spread {spread:.2f} across reps > "
            f"{LINEARITY_SPREAD_LIMIT} (reps disagree on the regime)")
    return "; ".join(problems)


def mfu_guard_violations(entry: Dict, keys, threshold: float = 1.02) -> Dict:
    """Analytic-MFU keys of ``entry`` above ``threshold`` (impossible by
    construction — the timing is wrong, not the chip fast). Empty dict =
    the measurement passes the guard."""
    return {k: entry[k] for k in keys if k in entry and entry[k] > threshold}
