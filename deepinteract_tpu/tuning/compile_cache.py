"""JAX persistent compilation cache, wired through flags and telemetry.

Compile times of 48-247 s are what pushed bench rounds past their driver
timeout (BENCH_r02-r04 rc=124); the persistent cache turns a repeat
compile of an unchanged graph into a disk read. This module is the one
place that enables it, so every entry point (train/serve/tune) shares the
same behavior and the same ``di_compile_cache_*`` counters.

Hit/miss counting rides jax's own monitoring events
(``/jax/compilation_cache/cache_hits`` etc.) when that API exists;
registration is best-effort — on a jax build without the monitoring hooks
the cache still works, only the counters stay silent (and the enable log
line says so).

NOTE: bench.py deliberately does NOT enable the cache — executable
serialization was observed to hang through the axon PJRT tunnel (forward
compile 40 s without the cache, >9 min stuck with it). That is why this is
an opt-in CLI flag rather than a process-wide default, and why
``DI_DISABLE_COMPILE_CACHE=1`` force-disables it even when a flag asks.
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from deepinteract_tpu.obs import metrics as obs_metrics

_CACHE_HITS = obs_metrics.counter(
    "di_compile_cache_hits_total",
    "Executables loaded from the persistent compilation cache")
_CACHE_MISSES = obs_metrics.counter(
    "di_compile_cache_misses_total",
    "Compilations that missed the persistent cache")
_CACHE_ERRORS = obs_metrics.counter(
    "di_compile_cache_errors_total",
    "Persistent compilation cache read/write errors")

_listener_registered = False


def _on_event(event: str, **kwargs) -> None:
    # jax emits durations on some of these; the event NAME is the signal.
    if "compilation_cache" not in event:
        return
    if "hit" in event:
        _CACHE_HITS.inc()
    elif "miss" in event:
        _CACHE_MISSES.inc()
    elif "error" in event:
        _CACHE_ERRORS.inc()


def _register_listener() -> bool:
    """Best-effort hookup of the hit/miss counters to jax.monitoring."""
    global _listener_registered
    if _listener_registered:
        return True
    try:
        from jax import monitoring

        monitoring.register_event_listener(
            lambda event, **kw: _on_event(event, **kw))
        _listener_registered = True
        return True
    except Exception:
        return False


def resolve_cache_dir(flag_value: Optional[str],
                      ckpt_dir: Optional[str]) -> Optional[str]:
    """Map the ``--compile_cache_dir`` flag onto a concrete directory.

    ``"off"``/``""`` (or DI_DISABLE_COMPILE_CACHE=1) disables; ``"auto"``
    (the flag default) uses ``<ckpt_dir>/compile_cache`` when a checkpoint
    directory exists and disables otherwise (no durable place to put it);
    anything else is used verbatim."""
    if os.environ.get("DI_DISABLE_COMPILE_CACHE"):
        return None
    if flag_value in (None, "", "off", "none"):
        return None
    if flag_value == "auto":
        return os.path.join(ckpt_dir, "compile_cache") if ckpt_dir else None
    return flag_value


def enable_compile_cache(cache_dir: Optional[str],
                         log: Callable[[str], None] = print) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Returns True when enabled. ``min_compile_time_secs`` drops to 0.5 so
    the medium compiles (eval steps, small buckets) are cached too — the
    default threshold of 1 s skips exactly the graphs a CPU test exercises.
    """
    if not cache_dir:
        return False
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        try:
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.5)
        except Exception:
            pass  # knob renamed/absent on this jax; cache still works
        counted = _register_listener()
        log(f"persistent compilation cache: {cache_dir}"
            + ("" if counted else
               " (hit/miss counters unavailable on this jax build)"))
        return True
    except Exception as exc:
        log(f"persistent compilation cache unavailable: {exc}")
        return False


def add_compile_cache_arg(parser) -> None:
    """The shared ``--compile_cache_dir`` flag (train/serve/tune)."""
    parser.add_argument(
        "--compile_cache_dir", type=str, default="auto",
        help="persistent XLA compilation cache directory; 'auto' (default) "
             "uses <ckpt_dir>/compile_cache, 'off' disables. Cache hits "
             "turn 48-247 s recompiles into disk reads; hit/miss counts "
             "are exported as di_compile_cache_* metrics")
