"""Trial measurement functions for the tuner.

Two implementations of the same ``measure(trial, fidelity) -> (value,
detail)`` contract (see :mod:`deepinteract_tpu.tuning.search`):

* :func:`make_train_measure` — the real one: builds the trial's model on
  the live backend, runs the scanned train step on a synthetic batch at
  the bucket's shapes, and times it with the SAME differenced protocol
  bench.py uses (:mod:`deepinteract_tpu.tuning.timing`). Objective is
  milliseconds per optimization step — lower is better, and it is exactly
  bench's ``train_scan_ms_per_step``.
* :func:`make_dry_run_measure` — a deterministic cost MODEL (no jax, no
  device): used by ``cli.tune --dry_run`` and the fast-tier CI test to
  exercise the whole search/store pipeline in milliseconds. The model
  encodes the measured shape of the real trade-offs (scan amortization,
  remat recompute tax, unroll compile tax) so the winning config is
  plausible, but its numbers are synthetic and marked as such in the
  store entry.
"""

from __future__ import annotations

import math
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from deepinteract_tpu.tuning import timing
from deepinteract_tpu.tuning.space import (
    TrialConfig,
    apply_to_model_config,
    apply_to_optim_config,
)


def make_train_measure(base_model_cfg, batch: int, pad: int, *,
                       knn: int = 20, geo: int = 2, seed: int = 0,
                       reps: int = 3,
                       analytic_train_flops=None,
                       peak_flops: Optional[float] = None):
    """Real device measurement of the scanned train step for one bucket.

    ``fidelity`` maps to timed iterations per rep (successive halving
    re-measures survivors with more iterations). The per-trial state/batch
    are built fresh inside the call — each trial's model differs (remat /
    scan_chunks / Pallas grid change the graph), so nothing meaningful is
    shareable across trials except the host-side featurized arrays, which
    ARE cached across calls.

    ``analytic_train_flops`` is a float, or a callable ``trial -> float``
    (the FLOP count depends on the trial: remat adds a decoder recompute).
    With it and ``peak_flops`` set, every trial runs under bench's
    impossible-MFU guard — an MFU > 1 fails the trial instead of
    persisting a broken-timer measurement as a winner."""
    from deepinteract_tpu.data.graph import stack_complexes
    from deepinteract_tpu.data.synthetic import random_complex

    rng = np.random.default_rng(seed)
    # Host featurization is trial-invariant: build once, reuse every trial.
    one = [random_complex(max(pad - 28, knn + 1), max(pad - 48, knn + 1),
                          rng=rng, n_pad1=pad, n_pad2=pad, knn=knn,
                          geo_nbrhd_size=geo)
           for _ in range(batch)]
    host_batch = stack_complexes(one)

    def measure(trial: TrialConfig, fidelity: int) -> Tuple[float, Dict]:
        import jax

        from deepinteract_tpu.models.model import DeepInteract
        from deepinteract_tpu.training.optim import OptimConfig
        from deepinteract_tpu.training.steps import (
            create_train_state,
            multi_train_step,
            stack_microbatches,
        )

        model = DeepInteract(apply_to_model_config(base_model_cfg, trial))
        optim_cfg = apply_to_optim_config(
            OptimConfig(steps_per_epoch=100, num_epochs=50), trial)
        state = create_train_state(
            model, jax.tree_util.tree_map(lambda x: x[:1], host_batch),
            optim_cfg=optim_cfg)
        scan_k = max(1, trial.scan_k)
        stacked = stack_microbatches([host_batch] * scan_k)
        step = jax.jit(lambda s, bst: multi_train_step(s, bst))
        compile_s, proto, _ = timing.time_compiled(
            step, (state, stacked),
            iters=max(3, int(fidelity)), reps=reps)
        ms_per_step = proto["median"] * 1e3 / scan_k
        detail = {
            "objective": "train_scan_ms_per_step",
            "train_scan_ms_per_step": ms_per_step,
            "train_scan_complexes_per_sec": batch * scan_k / proto["median"],
            "compile_s": compile_s,
            "timing_protocol": proto,
        }
        flops = (analytic_train_flops(trial)
                 if callable(analytic_train_flops) else analytic_train_flops)
        if flops and peak_flops:
            mfu = scan_k * flops / proto["median"] / peak_flops
            detail["analytic_train_scan_mfu"] = mfu
            bad = timing.mfu_guard_violations(detail,
                                              ("analytic_train_scan_mfu",))
            if bad:
                # Same discipline as bench: an impossible MFU means the
                # timing broke — fail the trial, never record the number.
                raise RuntimeError(
                    f"impossible analytic MFU (timing untrustworthy): {bad}")
        return ms_per_step, detail

    return measure


def make_dry_run_measure(batch: int, pad: int):
    """Deterministic synthetic cost model (``--dry_run``; no device work).

    The functional form mirrors measured behavior so the pipeline's
    selection logic is exercised realistically: per-step cost =
    device_compute * remat_tax / dtype + dispatch_overhead / scan_k
    (+ a small unroll and Pallas-grid term), perturbed by a deterministic
    per-config hash jitter standing in for measurement noise."""

    def measure(trial: TrialConfig, fidelity: int) -> Tuple[float, Dict]:
        base = 2.0 + 0.004 * pad + 0.15 * batch  # "device" ms/step
        cost = base
        if trial.remat:
            cost *= 1.25 if trial.remat_policy == "full" else 1.12
        if not trial.scan_chunks:
            cost *= 1.03
        if trial.pallas_fwd_blocks is not None:
            cost *= 1.0 + 0.01 * abs(trial.pallas_fwd_blocks - 4)
        if trial.pallas_bwd_blocks is not None:
            cost *= 1.0 + 0.01 * abs(trial.pallas_bwd_blocks - 8)
        if trial.diagonal_buckets:
            cost *= 0.98
        cost *= 1.0 + 0.05 * (trial.microbatch - 1)
        cost += 25.0 / max(1, trial.scan_k)  # dispatch amortization
        # Deterministic pseudo-noise, shrinking with fidelity like real
        # variance does with more timed iterations. crc32, not builtin
        # hash(): the latter is salted per process (PYTHONHASHSEED), which
        # would make "deterministic" quietly false across runs.
        h = (zlib.crc32(f"{trial.label()}|{pad}|{batch}".encode())
             % 997 / 997.0)
        cost *= 1.0 + (h - 0.5) * 0.02 / max(1, int(math.sqrt(fidelity)))
        detail = {
            "objective": "train_scan_ms_per_step",
            "train_scan_ms_per_step": cost,
            "synthetic": True,
        }
        return cost, detail

    return measure
